"""Guarded continuous learning (ISSUE 14): validation-gated candidate
deploys, VersionManager/ModelServer rollback, probation-window breach
handling, and SIGTERM preemption of the online loop."""

import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from flink_ml_tpu import obs
from flink_ml_tpu.serving import (
    ContinuousLearningController,
    ModelServer,
    VersionManager,
)
from flink_ml_tpu.serving.lifecycle import (
    BLOCK_HOLDOUT_REGRESSION,
    BLOCK_NUMERIC_HEALTH,
    BLOCK_SCORE_DRIFT,
    latest_candidate,
)
from flink_ml_tpu.table.schema import DataTypes, Schema
from flink_ml_tpu.table.sources import ColumnarUnboundedSource
from flink_ml_tpu.table.table import Table

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCHEMA = Schema.of(("features", DataTypes.DENSE_VECTOR), ("label", "double"))
DIM = 4
TRUE_W = np.array([2.0, -1.5, 1.0, 0.5])
WAIT = 60


@pytest.fixture(autouse=True)
def _obs_on(tmp_path, monkeypatch):
    monkeypatch.setenv("FMT_OBS_REPORTS", str(tmp_path / "_reports"))
    obs.enable()
    obs.reset()
    yield
    obs.disable()
    obs.reset()


def _xy(n, seed):
    r = np.random.RandomState(seed)
    X = r.randn(n, DIM)
    y = ((X @ TRUE_W) > 0).astype(np.float64)
    return X.astype(np.float32), y


def _table(n=256, seed=0):
    X, y = _xy(n, seed)
    return Table.from_columns(SCHEMA, {"features": X, "label": y})


def _fit_lr(table, iters=3, lr=0.5):
    from flink_ml_tpu.lib import LogisticRegression

    return (
        LogisticRegression().set_vector_col("features")
        .set_label_col("label").set_prediction_col("pred")
        .set_learning_rate(lr).set_max_iter(iters).fit(table)
    )


def _online_est(window_ms=1000, lr=0.5):
    from flink_ml_tpu.lib.online import OnlineLogisticRegression

    return (
        OnlineLogisticRegression().set_vector_col("features")
        .set_label_col("label").set_prediction_col("pred")
        .set_learning_rate(lr).set_window_ms(window_ms)
    )


def _stream(n=1200, seed=1, interval=50):
    X, y = _xy(n, seed)
    ts = np.arange(n, dtype=np.int64) * interval
    return ColumnarUnboundedSource(ts, {"features": X, "label": y}, SCHEMA)


def _controller(tmp_path, server=None, **kw):
    kw.setdefault("candidate_every", 10)
    kw.setdefault("probation_s", 0.01)
    return ContinuousLearningController(
        _online_est(), _stream(), _table(400, seed=2), server=server,
        candidate_dir=str(tmp_path / "cands"), **kw,
    )


class TestVersionManagerRollback:
    def test_rollback_reactivates_previous(self):
        vm = VersionManager()
        m1, m2 = _fit_lr(_table()), _fit_lr(_table(seed=5))
        vm.deploy(m1, "v1")
        vm.deploy(m2, "v2")
        assert vm.previous_version == "v1"
        deployed = vm.rollback()
        assert deployed.version == "v1"
        assert vm.active_version == "v1"
        # the rollback IS a deploy: history records the redeploy
        assert vm.history == ["v1", "v2", "v1"]
        c = obs.registry().snapshot()["counters"]
        assert c.get("serving.rollbacks") == 1

    def test_second_rollback_steps_further_back(self):
        vm = VersionManager(keep=4)
        models = [_fit_lr(_table(seed=s)) for s in range(3)]
        for i, m in enumerate(models):
            vm.deploy(m, f"v{i + 1}")
        vm.rollback()
        assert vm.active_version == "v2"
        # v3 was rolled away from: the next rollback must NOT return to
        # it, nor re-land on v2 — it steps to v1
        vm.rollback()
        assert vm.active_version == "v1"

    def test_rollback_without_previous_raises(self):
        vm = VersionManager()
        vm.deploy(_fit_lr(_table()), "v1")
        with pytest.raises(RuntimeError, match="no previous version"):
            vm.rollback()

    def test_path_sourced_rollback_reverifies_integrity(self, tmp_path):
        from flink_ml_tpu.serve import ModelIntegrityError

        d1, d2 = str(tmp_path / "v1"), str(tmp_path / "v2")
        _fit_lr(_table()).save(d1)
        _fit_lr(_table(seed=5)).save(d2)
        vm = VersionManager()
        vm.deploy(d1, "v1")
        vm.deploy(d2, "v2")
        # the v1 artifact rots on disk AFTER its first deploy: a bare
        # pointer flip would serve it anyway; the re-verifying rollback
        # refuses and the current version keeps serving
        mdf = tmp_path / "v1" / "model_data.jsonl"
        blob = bytearray(mdf.read_bytes())
        blob[len(blob) // 2] ^= 0xFF
        mdf.write_bytes(bytes(blob))
        with pytest.raises(ModelIntegrityError):
            vm.rollback()
        assert vm.active_version == "v2"
        assert vm.previous_version == "v1"  # retained set untouched
        c = obs.registry().snapshot()["counters"]
        assert c.get("serving.deploy_failures") == 1
        assert "serving.rollbacks" not in c

    def test_history_depth_knob_bounds_retained(self, monkeypatch):
        monkeypatch.setenv("FMT_LIFECYCLE_HISTORY", "2")
        vm = VersionManager()
        for i in range(5):
            vm.deploy(_fit_lr(_table(seed=i)), f"v{i + 1}")
        # only the previous version remains retained at depth 2: one
        # rollback works, a second has nothing older to step to
        assert vm.previous_version == "v4"
        vm.rollback()
        assert vm.active_version == "v4"
        with pytest.raises(RuntimeError, match="no previous version"):
            vm.rollback()

    def test_rollback_warmup_runs_with_deploy_in_progress(self):
        import threading

        class SlowModel:
            def __init__(self):
                self.release = threading.Event()
                self.warmed = threading.Event()

            def transform(self, table):
                self.warmed.set()
                assert self.release.wait(WAIT)
                return (table,)

        slow = SlowModel()
        vm = VersionManager()
        vm.deploy(slow, "v1")
        vm.deploy(_fit_lr(_table()), "v2")
        warmup = _table(4)
        done = []
        t = threading.Thread(
            target=lambda: done.append(vm.rollback(warmup=warmup))
        )
        t.start()
        # /readyz semantics: while the rolled-back-to version pre-warms,
        # the manager reports a deploy in flight and v2 keeps serving
        assert slow.warmed.wait(WAIT)
        assert vm.deploy_in_progress
        assert vm.active_version == "v2"
        slow.release.set()
        t.join(WAIT)
        assert done and done[0].version == "v1"
        assert not vm.deploy_in_progress


class TestModelServerRollback:
    def test_rollback_serves_previous_bit_identically(self, tmp_path):
        m1 = _fit_lr(_table(), iters=2)
        m2 = _fit_lr(_table(seed=5), iters=4)
        batch = _table(16, seed=9)
        (solo1,) = m1.transform(batch)
        expect = np.asarray(solo1.col("pred"))
        server = ModelServer(m1, max_wait_ms=5,
                             warmup=batch.slice_rows(0, 4))
        try:
            server.deploy(m2, "v2")
            assert server.active_version == "v2"
            assert server.previous_version == "v1"
            server.rollback()
            assert server.active_version == "v1"
            res = server.predict(batch, timeout=WAIT)
            assert res.version == "v1"
            np.testing.assert_array_equal(
                np.asarray(res.table.col("pred")), expect)
            assert server.stats().get("serving.rollbacks") == 1
        finally:
            server.shutdown()


class TestValidationGate:
    def test_numeric_health_blocks_and_resets_trainer(self, tmp_path):
        ctl = _controller(tmp_path)
        good = {"version": "g", "path": None,
                "w": np.asarray(TRUE_W), "b": 0.25,
                "auc": 0.9, "scores": ctl._holdout_x @ TRUE_W + 0.25}
        ctl._incumbent = good
        import jax.numpy as jnp

        bad_state = (jnp.asarray(np.full(DIM, np.nan, np.float32)),
                     jnp.asarray(np.float32(0)))
        replacement = ctl._candidate(bad_state)
        # the gate blocked the swap AND handed the trainer its reset:
        # the last validated candidate's params, as device arrays
        assert replacement is not None
        np.testing.assert_allclose(np.asarray(replacement[0]), TRUE_W)
        assert float(np.asarray(replacement[1])) == 0.25
        c = obs.registry().snapshot()["counters"]
        assert c.get("lifecycle.blocked") == 1
        assert c.get(f"lifecycle.blocked.{BLOCK_NUMERIC_HEALTH}") == 1
        assert c.get("lifecycle.trainer_resets") == 1
        assert "lifecycle.swaps" not in c
        assert obs.flight.last_dump_path() is not None

    def test_holdout_regression_blocks_without_reset(self, tmp_path):
        ctl = _controller(tmp_path)
        scores = ctl._holdout_x @ TRUE_W
        ctl._incumbent = {"version": "g", "path": None,
                          "w": np.asarray(TRUE_W), "b": 0.0,
                          "auc": ctl_auc(ctl, scores), "scores": scores}
        import jax.numpy as jnp

        # anti-signal params: AUC well under the incumbent's
        worse = (jnp.asarray(-np.asarray(TRUE_W, np.float32)),
                 jnp.asarray(np.float32(0)))
        assert ctl._candidate(worse) is None  # blocked, but NOT poisoned
        c = obs.registry().snapshot()["counters"]
        assert c.get(f"lifecycle.blocked.{BLOCK_HOLDOUT_REGRESSION}") == 1
        assert "lifecycle.trainer_resets" not in c

    def test_degenerate_constant_scores_block_as_drift(self, tmp_path):
        ctl = _controller(tmp_path)
        scores = ctl._holdout_x @ TRUE_W
        ctl._incumbent = {"version": "g", "path": None,
                          "w": np.asarray(TRUE_W), "b": 0.0,
                          "auc": 0.5, "scores": scores}
        verdict = ctl._gate(np.zeros(DIM), 5.0)
        assert verdict["reason"] == BLOCK_SCORE_DRIFT
        assert "degenerate" in verdict["detail"]

    def test_scale_growth_passes_the_drift_gate(self, tmp_path):
        """Continued online training legitimately grows score magnitude
        window over window — raw-score PSI would block every healthy
        candidate, so the gate judges STANDARDIZED shape."""
        ctl = _controller(tmp_path, score_psi=0.25)
        scores = ctl._holdout_x @ TRUE_W
        ctl._incumbent = {"version": "g", "path": None,
                          "w": np.asarray(TRUE_W), "b": 0.0,
                          "auc": 0.5, "scores": scores}
        assert ctl._gate(100.0 * TRUE_W, 0.0)["reason"] is None

    def test_score_psi_catches_shape_change_not_scale(self):
        from flink_ml_tpu.serving.lifecycle import _score_psi

        rng = np.random.RandomState(3)
        ref = rng.randn(2000)
        # scale + shift: the same function, sharper — passes
        assert _score_psi(ref, 100.0 * ref + 7.0) < 0.05
        # a bimodal split (the candidate scores a different function,
        # e.g. it collapsed onto one near-binary feature) — blocks
        bimodal = np.where(rng.rand(2000) > 0.5, 10.0, 0.0)
        bimodal += 0.01 * rng.randn(2000)
        assert _score_psi(ref, bimodal) > 0.25
        # near-constant scores are degenerate, reported as None
        assert _score_psi(ref, np.full(2000, 3.0)) is None


def ctl_auc(ctl, scores):
    from flink_ml_tpu.serving.lifecycle import _auc

    return _auc(ctl._holdout_y, scores)


class TestControllerLoop:
    def test_validated_candidates_swap_under_live_traffic(self, tmp_path):
        init = _fit_lr(_table(200, seed=0), iters=2)
        holdout = _table(400, seed=2)
        server = ModelServer(init, max_wait_ms=5,
                             warmup=holdout.slice_rows(0, 8))
        try:
            ctl = ContinuousLearningController(
                _online_est(), _stream(), holdout, server=server,
                candidate_dir=str(tmp_path / "c"), candidate_every=20,
                probation_s=0.01,
            )
            ctl.start()
            # live traffic rides beside the training loop
            futs = []
            while ctl._trainer.is_alive():
                futs.append(server.submit(holdout.slice_rows(0, 8)))
                time.sleep(0.005)
            ctl.join(WAIT)
            ctl.stop()
            results = [f.result(WAIT) for f in futs]
            assert results, "no live traffic flowed during the loop"
            stats = ctl.stats()
            assert stats.get("lifecycle.swaps", 0) >= 2
            assert server.active_version == stats["incumbent"]
            assert server.active_version.startswith("cl-")
            # committed candidates are integrity-verified loadable
            path, meta = latest_candidate(str(tmp_path / "c"))
            from flink_ml_tpu.api.core import load_stage

            loaded = load_stage(path)
            assert loaded.coefficients().shape == (DIM,)
            assert meta["version"] == stats["incumbent"]
            assert server.stats().get("serving.failed_requests",
                                      0) == 0
        finally:
            server.shutdown()

    def test_probation_breach_rolls_back(self, tmp_path, monkeypatch):
        init = _fit_lr(_table(200, seed=0), iters=2)
        holdout = _table(400, seed=2)
        server = ModelServer(init, max_wait_ms=5,
                             warmup=holdout.slice_rows(0, 8))
        try:
            ctl = ContinuousLearningController(
                _online_est(), _stream(600), holdout, server=server,
                candidate_dir=str(tmp_path / "c"), candidate_every=30,
                probation_s=30.0, max_windows=30,
            )
            # stand in for the server's SLO monitor: the live p99/drift
            # burn signal flips right after the first swap
            burning = {}
            monkeypatch.setattr(ctl, "_burning_now", lambda: dict(burning))
            ctl.run()
            assert server.active_version == "cl-1"
            burning["drift"] = 7.5
            deadline = time.monotonic() + WAIT
            while (server.active_version != "v1"
                   and time.monotonic() < deadline):
                time.sleep(0.01)
            ctl.stop()
            assert server.active_version == "v1"
            c = obs.registry().snapshot()["counters"]
            assert c.get("lifecycle.rollbacks") == 1
            assert c.get("serving.rollbacks") == 1
            # baseline followed the pointer: next candidate gates
            # against the restored incumbent
            assert ctl.incumbent_version == "v1"
            # one breach, one rollback — probation disarmed itself
            time.sleep(0.1)
            assert obs.registry().snapshot()["counters"].get(
                "lifecycle.rollbacks") == 1
        finally:
            server.shutdown()

    def test_publish_only_restart_resumes_incumbent_and_stream(
            self, tmp_path):
        cdir = str(tmp_path / "c")
        ctl = ContinuousLearningController(
            _online_est(), _stream(400), _table(400, seed=2),
            candidate_dir=cdir, candidate_every=10,
        )
        ctl.run()
        ctl.stop()
        first = ctl.stats()
        assert first.get("lifecycle.published", 0) >= 2
        incumbent = first["incumbent"]
        # a fresh controller over the same directory bootstraps its
        # baseline (and sequence numbers) from the committed candidates,
        # and the stream checkpoint fast-forwards past the 400 rows the
        # first run consumed (RandomState draws are prefix-stable, so
        # the longer stream replays the same first 400 rows)
        ctl2 = ContinuousLearningController(
            _online_est(), _stream(800), _table(400, seed=2),
            candidate_dir=cdir, candidate_every=10,
        )
        assert ctl2.incumbent_version == incumbent
        ctl2.run()
        ctl2.stop()
        assert ctl2.windows > first["windows"]
        path, meta = latest_candidate(cdir)
        assert int(meta["seq"]) > int(incumbent.split("-")[1])
        assert ctl2.stats()["incumbent"] == meta["version"]


class TestPreemption:
    def _killing_stream(self, n, kill_after_chunk, chunk=100):
        from flink_ml_tpu.table.sources import UnboundedSource

        X, y = _xy(n, seed=11)
        ts = np.arange(n, dtype=np.int64) * 50

        class KillingSource(UnboundedSource):
            def stream_chunks(self, max_rows=None):
                def gen():
                    for i, a in enumerate(range(0, n, chunk)):
                        if i == kill_after_chunk:
                            os.kill(os.getpid(), signal.SIGTERM)
                        b = a + chunk
                        yield ts[a:b], {"features": X[a:b],
                                        "label": y[a:b]}

                return gen()

            def stream(self):
                from flink_ml_tpu.table.sources import chunk_row_iter

                for t, cols in self.stream_chunks():
                    yield from chunk_row_iter(t, cols, SCHEMA)

            def schema(self):
                return SCHEMA

        return KillingSource()

    def test_sigterm_mid_stream_emergency_snapshot_then_exact_resume(
            self, tmp_path):
        """In-process satellite core: a real SIGTERM mid-stream commits
        an emergency snapshot at a span boundary and raises the clean
        exit; a resumed run over the replayed source finishes with
        params BIT-IDENTICAL to an uninterrupted run's."""
        from flink_ml_tpu.fault import guard
        from flink_ml_tpu.iteration.checkpoint import CheckpointConfig

        plain_dir = tmp_path / "plain"
        model, _ = _online_est().fit_unbounded(
            self._killing_stream(1000, kill_after_chunk=None),
            checkpoint=CheckpointConfig(str(plain_dir), every_n_epochs=5),
        )
        ref_w, ref_b = model.coefficients(), model.intercept()

        crash_dir = tmp_path / "crash"
        with pytest.raises(SystemExit) as exc:
            _online_est().fit_unbounded(
                self._killing_stream(1000, kill_after_chunk=6),
                checkpoint=CheckpointConfig(str(crash_dir),
                                            every_n_epochs=5),
            )
        assert exc.value.code == 0  # the Preempted clean-exit contract
        assert os.listdir(crash_dir), "no emergency snapshot committed"
        c = obs.registry().snapshot()["counters"]
        assert c.get("fault.emergency_checkpoints") == 1
        guard.reset_preempted()

        resumed, _ = _online_est().fit_unbounded(
            self._killing_stream(1000, kill_after_chunk=None),
            checkpoint=CheckpointConfig(str(crash_dir), every_n_epochs=5),
        )
        np.testing.assert_array_equal(resumed.coefficients(), ref_w)
        assert resumed.intercept() == ref_b

    def test_subprocess_controller_kill_and_resume_bit_identical(
            self, tmp_path):
        """The satellite's full scenario in real processes, extending the
        test_fault pattern: the controller's loop dies to a delivered
        SIGTERM with exit code 0 after committing an emergency candidate
        + stream snapshot; a restarted loop resumes and finishes
        bit-identical to an uninterrupted one."""
        worker = os.path.join(REPO, "tests", "online_preempt_worker.py")
        env = {k: v for k, v in os.environ.items()
               if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}

        def run(phase, ckpt):
            return subprocess.run(
                [sys.executable, worker, phase, str(ckpt)],
                capture_output=True, text=True, timeout=240, env=env,
            )

        plain = run("plain", tmp_path / "ref")
        assert plain.returncode == 0, plain.stderr
        ref_line = [ln for ln in plain.stdout.splitlines()
                    if ln.startswith("PARAMS")]
        assert ref_line, plain.stdout

        crashed = run("crash", tmp_path / "c")
        assert crashed.returncode == 0, (crashed.stdout, crashed.stderr)
        assert "PARAMS" not in crashed.stdout  # died before completion
        # the emergency candidate committed through the sidecar scheme
        latest = latest_candidate(str(tmp_path / "c"))
        assert latest is not None, "no emergency candidate committed"
        path, meta = latest
        assert meta["emergency"] is True
        assert os.path.exists(os.path.join(path, "model_data.jsonl"))
        assert os.listdir(tmp_path / "c" / "stream"), "no stream snapshot"

        resumed = run("resume", tmp_path / "c")
        assert resumed.returncode == 0, resumed.stderr
        res_line = [ln for ln in resumed.stdout.splitlines()
                    if ln.startswith("PARAMS")]
        assert res_line == ref_line  # bit-identical
