"""Pipeline API tests — parity with PipelineTest.java:38-51 (mock stages, no
device, fit/transform chaining order) plus working save/load coverage the
reference never implemented."""

import numpy as np
import pytest

from flink_ml_tpu.api import (
    AlgoOperator,
    Estimator,
    Model,
    Pipeline,
    PipelineModel,
    load_stage,
)
from flink_ml_tpu.api.core import Transformer
from flink_ml_tpu.params import param_info
from flink_ml_tpu.table import DataTypes, Schema, Table
from flink_ml_tpu.table.sources import ChunkedTable, CollectionSource
from flink_ml_tpu.utils import MLEnvironmentFactory, load_table, save_table


def _tag_table(tag: str) -> Table:
    return Table.from_rows([(tag,)], Schema(["tag"], [DataTypes.STRING]))


def _tag(table: Table) -> str:
    return table.col("tag")[0]


class MockTransformer(AlgoOperator):
    """Appends its suffix to the tag — observable chaining order."""

    SUFFIX = param_info("suffix", default="t")

    def transform(self, *inputs):
        (t,) = inputs
        return (_tag_table(_tag(t) + "_" + self.get(self.SUFFIX)),)


class MockModel(Model):
    SUFFIX = param_info("suffix", default="m")

    def transform(self, *inputs):
        (t,) = inputs
        return (_tag_table(_tag(t) + "_m" + self.get(self.SUFFIX)),)


class MockEstimator(Estimator):
    SUFFIX = param_info("suffix", default="e")

    def fit(self, *inputs):
        model = MockModel()
        model.set(MockModel.SUFFIX, self.get(self.SUFFIX))
        return model


class TestPipelineChaining:
    """The a_b_c_d -> a_mb_mc_d shape of PipelineTest.java:38-51."""

    def test_fit_transform_order(self):
        # stages: transformer(a) estimator(b) estimator(c) transformer(d)
        stages = [
            MockTransformer().set(MockTransformer.SUFFIX, "a"),
            MockEstimator().set(MockEstimator.SUFFIX, "b"),
            MockEstimator().set(MockEstimator.SUFFIX, "c"),
            MockTransformer().set(MockTransformer.SUFFIX, "d"),
        ]
        pm = Pipeline(stages).fit(_tag_table("x"))
        assert isinstance(pm, PipelineModel)
        (out,) = pm.transform(_tag_table("x"))
        # fit: transform chains through a, mb (to feed c); d not fit-applied
        # transform: x -> a -> mb -> mc -> d
        assert _tag(out) == "x_a_mb_mc_d"

    def test_trailing_estimator_not_applied_during_fit(self):
        calls = []

        class SpyModel(MockModel):
            def transform(self, *inputs):
                calls.append("transform")
                return super().transform(*inputs)

        class SpyEstimator(MockEstimator):
            def fit(self, *inputs):
                m = SpyModel()
                m.set(MockModel.SUFFIX, self.get(self.SUFFIX))
                return m

        Pipeline([SpyEstimator()]).fit(_tag_table("x"))
        # single (last) estimator: its model must NOT be applied during fit
        assert calls == []

    def test_pipeline_of_only_transformers(self):
        pm = Pipeline(
            [MockTransformer().set(MockTransformer.SUFFIX, s) for s in "ab"]
        ).fit(_tag_table("x"))
        (out,) = pm.transform(_tag_table("x"))
        assert _tag(out) == "x_a_b"

    def test_non_stage_rejected(self):
        with pytest.raises(TypeError, match="neither"):
            Pipeline([object()]).fit(_tag_table("x"))

    def test_append_stage(self):
        p = Pipeline().append_stage(MockTransformer())
        assert len(p.stages) == 1


NUM_SCHEMA = Schema(["v"], [DataTypes.DOUBLE])


class AddOne(Transformer):
    """Numeric 1-in/1-out stage for the chunked forwarding path."""

    def transform(self, *inputs):
        (t,) = inputs
        v = np.asarray(t.col("v"), dtype=np.float64) + 1.0
        return (Table.from_columns(NUM_SCHEMA, {"v": v}),)


class SumModel(Model):
    def __init__(self, total=0.0):
        super().__init__()
        self.total = total

    def transform(self, *inputs):
        return inputs


class SumEstimator(Estimator):
    """Consumes chunked or materialized input; records how it was fed."""

    def __init__(self):
        super().__init__()
        self.saw_chunks = None

    def fit(self, *inputs):
        (t,) = inputs
        if getattr(t, "is_chunked", False):
            assert list(t.schema.field_names) == ["v"]  # schema probe must work
            chunks = list(t.chunks())
            self.saw_chunks = len(chunks)
            total = sum(float(np.sum(np.asarray(c.col("v")))) for c in chunks)
        else:
            self.saw_chunks = 0
            total = float(np.sum(np.asarray(t.col("v"))))
        return SumModel(total)


class TestChunkedPipeline:
    """Pipeline.fit over a ChunkedTable with stages ahead of the last
    estimator (r3 advisor finding): intermediate Transformers must stream
    chunk-by-chunk; non-Transformer intermediates are rejected loudly
    instead of crashing downstream with an AttributeError."""

    def _chunked(self, n=10, chunk_rows=3):
        rows = [(float(i),) for i in range(n)]
        return ChunkedTable(CollectionSource(rows, NUM_SCHEMA), chunk_rows)

    def test_multi_stage_chunked_fit_streams_and_matches_materialized(self):
        est = SumEstimator()
        pm = Pipeline([AddOne(), AddOne(), est]).fit(self._chunked())
        assert isinstance(pm, PipelineModel)
        # 10 rows in chunks of 3 -> 4 chunks streamed through both AddOnes
        assert est.saw_chunks == 4
        expect = sum(float(i) + 2.0 for i in range(10))
        assert pm.stages[-1].total == expect

        est2 = SumEstimator()
        dense = Table.from_rows([(float(i),) for i in range(10)], NUM_SCHEMA)
        pm2 = Pipeline([AddOne(), AddOne(), est2]).fit(dense)
        assert est2.saw_chunks == 0
        assert pm2.stages[-1].total == expect

    def test_non_transformer_intermediate_rejected_on_chunked_input(self):
        with pytest.raises(TypeError, match="cannot forward a chunked input"):
            Pipeline([MockTransformer(), SumEstimator()]).fit(self._chunked())

    def test_single_estimator_chunked_fit_unwrapped(self):
        est = SumEstimator()
        Pipeline([est]).fit(self._chunked())
        assert est.saw_chunks == 4


class TestSaveLoad:
    def test_stage_save_load_round_trip(self, tmp_path):
        t = MockTransformer().set(MockTransformer.SUFFIX, "z")
        t.save(str(tmp_path / "s"))
        restored = load_stage(str(tmp_path / "s"))
        assert isinstance(restored, MockTransformer)
        assert restored.get(MockTransformer.SUFFIX) == "z"

    def test_pipeline_save_load(self, tmp_path):
        p = Pipeline(
            [
                MockTransformer().set(MockTransformer.SUFFIX, "a"),
                MockEstimator().set(MockEstimator.SUFFIX, "b"),
            ]
        )
        p.save(str(tmp_path / "p"))
        restored = Pipeline.load(str(tmp_path / "p"))
        pm = restored.fit(_tag_table("x"))
        (out,) = pm.transform(_tag_table("x"))
        assert _tag(out) == "x_a_mb"

    def test_pipeline_model_save_load(self, tmp_path):
        pm = Pipeline(
            [MockEstimator().set(MockEstimator.SUFFIX, "q")]
        ).fit(_tag_table("x"))
        pm.save(str(tmp_path / "pm"))
        restored = PipelineModel.load(str(tmp_path / "pm"))
        (out,) = restored.transform(_tag_table("y"))
        assert _tag(out) == "y_mq"

    def test_nested_pipeline(self, tmp_path):
        inner = Pipeline([MockTransformer().set(MockTransformer.SUFFIX, "i")])
        outer = Pipeline([inner, MockEstimator()])
        outer.save(str(tmp_path / "o"))
        restored = Pipeline.load(str(tmp_path / "o"))
        pm = restored.fit(_tag_table("x"))
        (out,) = pm.transform(_tag_table("x"))
        assert _tag(out) == "x_i_me"

    def test_kind_mismatch_raises(self, tmp_path):
        Pipeline([MockTransformer()]).save(str(tmp_path / "p"))
        with pytest.raises(ValueError, match="not a PipelineModel"):
            PipelineModel.load(str(tmp_path / "p"))

    def test_model_data_default_unsupported(self):
        with pytest.raises(NotImplementedError):
            MockModel().get_model_data()
        with pytest.raises(NotImplementedError):
            MockModel().set_model_data()


class TestTablePersistence:
    def test_round_trip_with_vectors(self, tmp_path):
        from flink_ml_tpu.ops import DenseVector, SparseVector

        s = Schema(
            ["w", "name", "n"], [DataTypes.VECTOR, DataTypes.STRING, DataTypes.LONG]
        )
        t = Table.from_rows(
            [
                (DenseVector([1.5, -2.0]), "dense", 1),
                (SparseVector(4, [1, 3], [2.0, 4.0]), "sparse", 2),
            ],
            s,
        )
        save_table(t, str(tmp_path / "m" / "data.jsonl"))
        back = load_table(str(tmp_path / "m" / "data.jsonl"))
        assert back.schema == s
        assert back.col("w")[0] == DenseVector([1.5, -2.0])
        assert back.col("w")[1].indices.tolist() == [1, 3]
        assert back.col("name").tolist() == ["dense", "sparse"]
        assert back.col("n").tolist() == [1, 2]

    def test_nan_round_trip(self, tmp_path):
        s = Schema(["x"], [DataTypes.DOUBLE])
        t = Table.from_rows([(np.nan,), (1.0,)], s)
        save_table(t, str(tmp_path / "t.jsonl"))
        back = load_table(str(tmp_path / "t.jsonl"))
        assert np.isnan(back.col("x")[0]) and back.col("x")[1] == 1.0


class TestMLEnvironment:
    def test_registry_semantics(self):
        env_id = MLEnvironmentFactory.get_new_ml_environment_id()
        env = MLEnvironmentFactory.get(env_id)
        assert env is MLEnvironmentFactory.get(env_id)
        assert MLEnvironmentFactory.remove(env_id) is env
        with pytest.raises(ValueError, match="Cannot find"):
            MLEnvironmentFactory.get(env_id)

    def test_default_env_unremovable(self):
        default = MLEnvironmentFactory.get_default()
        assert MLEnvironmentFactory.remove(0) is default
        assert MLEnvironmentFactory.get(0) is default

    def test_monotonic_ids(self):
        a = MLEnvironmentFactory.get_new_ml_environment_id()
        b = MLEnvironmentFactory.get_new_ml_environment_id()
        assert b > a
        MLEnvironmentFactory.remove(a)
        MLEnvironmentFactory.remove(b)
