"""Online LogisticRegression tests: streaming convergence, concurrent
prediction freshness, bounded-replay fit, window accounting."""

import numpy as np

from flink_ml_tpu.lib.online import OnlineLogisticRegression
from flink_ml_tpu.ops.vector import DenseVector
from flink_ml_tpu.table.schema import DataTypes, Schema
from flink_ml_tpu.table.sources import GeneratorSource
from flink_ml_tpu.table.table import Table

SCHEMA = Schema.of(("features", DataTypes.DENSE_VECTOR), ("label", "double"))
QSCHEMA = Schema.of(("features", DataTypes.DENSE_VECTOR),)


def stream_rows(n=600, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, 3)
    true_w = np.array([2.0, -1.5, 1.0])
    y = ((X @ true_w + 0.2 * rng.randn(n)) > 0).astype(np.float64)
    return [(DenseVector(X[i]), y[i]) for i in range(n)], X, y


def make_estimator():
    return (
        OnlineLogisticRegression()
        .set_vector_col("features")
        .set_label_col("label")
        .set_prediction_col("pred")
        .set_learning_rate(0.5)
        .set_window_ms(1000)
    )


class TestOnlineLogisticRegression:
    def test_streaming_convergence(self):
        rows, X, y = stream_rows()
        # 20 rows per 1000ms window -> 30 windows
        source = GeneratorSource.linear_timestamps(rows, 50, SCHEMA)
        model, result = make_estimator().fit_unbounded(source)
        assert result.windows_fired == 30
        t = Table.from_rows([(DenseVector(x),) for x in X], QSCHEMA)
        probs = model.predict_proba(t)
        acc = np.mean((probs > 0.5) == (y == 1))
        assert acc > 0.9

    def test_concurrent_prediction_uses_fresh_model(self):
        rows, X, y = stream_rows(200, seed=1)
        train_src = GeneratorSource.linear_timestamps(rows, 50, SCHEMA)
        # prediction stream over the same timeline
        qrows = [(DenseVector(X[i]),) for i in range(200)]
        pred_src = GeneratorSource.linear_timestamps(qrows, 50, QSCHEMA)
        model, result = make_estimator().fit_unbounded(
            train_src, prediction_source=pred_src
        )
        assert len(result.predictions) == 200
        # late predictions (after training) are far better than early ones
        late = result.predictions[150:]
        late_acc = np.mean(
            [p == y[150 + i] for i, (_, p) in enumerate(late)]
        )
        assert late_acc > 0.8

    def test_model_history(self):
        rows, _, _ = stream_rows(100, seed=2)
        source = GeneratorSource.linear_timestamps(rows, 50, SCHEMA)
        _, result = make_estimator().fit_unbounded(source, keep_model_history=True)
        assert len(result.model_updates) == result.windows_fired
        # each update is a (window_end_ts, params) pair with increasing ts
        stamps = [ts for ts, _ in result.model_updates]
        assert stamps == sorted(stamps)

    def test_max_windows_cap(self):
        rows, _, _ = stream_rows(500, seed=3)
        source = GeneratorSource.linear_timestamps(rows, 50, SCHEMA)
        _, result = make_estimator().fit_unbounded(source, max_windows=5)
        assert result.windows_fired == 5

    def test_bounded_fit_replay(self):
        rows, X, y = stream_rows(400, seed=4)
        t = Table.from_rows(rows, SCHEMA)
        model = make_estimator().set_global_batch_size(40).fit(t)
        probs = model.predict_proba(
            Table.from_rows([(DenseVector(x),) for x in X], QSCHEMA)
        )
        assert np.mean((probs > 0.5) == (y == 1)) > 0.88


class TestSinglePassSource:
    def test_dim_probe_keeps_first_record(self):
        """Regression: _infer_dim peeks the first record off the stream; a
        single-pass (non-re-iterable) source must not lose it to the probe."""
        from flink_ml_tpu.table.sources import UnboundedSource

        rows, X, y = stream_rows(40, seed=3)

        class OneShotSource(UnboundedSource):
            def __init__(self):
                self.calls = 0

            def stream(self):
                self.calls += 1
                assert self.calls == 1, "stream() consumed more than once"
                return ((i * 50, rows[i]) for i in range(len(rows)))

            def schema(self):
                return SCHEMA

        model, result = make_estimator().fit_unbounded(OneShotSource())
        # all 40 rows trained: 20 rows / 1000ms window -> 2 windows
        assert result.windows_fired == 2
        assert model.coefficients().shape == (3,)
