"""Online LogisticRegression tests: streaming convergence, concurrent
prediction freshness, bounded-replay fit, window accounting."""

import numpy as np

from flink_ml_tpu.lib.online import OnlineLogisticRegression
from flink_ml_tpu.ops.vector import DenseVector
from flink_ml_tpu.table.schema import DataTypes, Schema
from flink_ml_tpu.table.sources import GeneratorSource
from flink_ml_tpu.table.table import Table

SCHEMA = Schema.of(("features", DataTypes.DENSE_VECTOR), ("label", "double"))
QSCHEMA = Schema.of(("features", DataTypes.DENSE_VECTOR),)


def stream_rows(n=600, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, 3)
    true_w = np.array([2.0, -1.5, 1.0])
    y = ((X @ true_w + 0.2 * rng.randn(n)) > 0).astype(np.float64)
    return [(DenseVector(X[i]), y[i]) for i in range(n)], X, y


def make_estimator():
    return (
        OnlineLogisticRegression()
        .set_vector_col("features")
        .set_label_col("label")
        .set_prediction_col("pred")
        .set_learning_rate(0.5)
        .set_window_ms(1000)
    )


class TestOnlineLogisticRegression:
    def test_streaming_convergence(self):
        rows, X, y = stream_rows()
        # 20 rows per 1000ms window -> 30 windows
        source = GeneratorSource.linear_timestamps(rows, 50, SCHEMA)
        model, result = make_estimator().fit_unbounded(source)
        assert result.windows_fired == 30
        t = Table.from_rows([(DenseVector(x),) for x in X], QSCHEMA)
        probs = model.predict_proba(t)
        acc = np.mean((probs > 0.5) == (y == 1))
        assert acc > 0.9

    def test_concurrent_prediction_uses_fresh_model(self):
        rows, X, y = stream_rows(200, seed=1)
        train_src = GeneratorSource.linear_timestamps(rows, 50, SCHEMA)
        # prediction stream over the same timeline
        qrows = [(DenseVector(X[i]),) for i in range(200)]
        pred_src = GeneratorSource.linear_timestamps(qrows, 50, QSCHEMA)
        model, result = make_estimator().fit_unbounded(
            train_src, prediction_source=pred_src
        )
        assert len(result.predictions) == 200
        # late predictions (after training) are far better than early ones
        late = result.predictions[150:]
        late_acc = np.mean(
            [p == y[150 + i] for i, (_, p) in enumerate(late)]
        )
        assert late_acc > 0.8

    def test_model_history(self):
        rows, _, _ = stream_rows(100, seed=2)
        source = GeneratorSource.linear_timestamps(rows, 50, SCHEMA)
        _, result = make_estimator().fit_unbounded(source, keep_model_history=True)
        assert len(result.model_updates) == result.windows_fired
        # each update is a (window_end_ts, params) pair with increasing ts
        stamps = [ts for ts, _ in result.model_updates]
        assert stamps == sorted(stamps)

    def test_max_windows_cap(self):
        rows, _, _ = stream_rows(500, seed=3)
        source = GeneratorSource.linear_timestamps(rows, 50, SCHEMA)
        _, result = make_estimator().fit_unbounded(source, max_windows=5)
        assert result.windows_fired == 5

    def test_bounded_fit_replay(self):
        rows, X, y = stream_rows(400, seed=4)
        t = Table.from_rows(rows, SCHEMA)
        model = make_estimator().set_global_batch_size(40).fit(t)
        probs = model.predict_proba(
            Table.from_rows([(DenseVector(x),) for x in X], QSCHEMA)
        )
        assert np.mean((probs > 0.5) == (y == 1)) > 0.88


class TestDegenerateWindows:
    """ISSUE 14 satellite: empty/degenerate training windows must not
    crash the loop or emit an all-zero candidate — skip, count, keep
    streaming."""

    def test_empty_window_returns_none(self):
        est = make_estimator()
        est._dim = 3
        empty = Table.from_columns(SCHEMA, {"features": [], "label": []})
        assert est._window_xyw(empty) is None

    def test_all_null_vector_window_returns_none_and_counts(self):
        from flink_ml_tpu import obs

        obs.enable()
        obs.reset()
        try:
            est = make_estimator()
            est._dim = 3
            bad = Table.from_columns(
                SCHEMA, {"features": [None, None], "label": [1.0, 0.0]}
            )
            # red before the fix: AttributeError out of features_dense
            assert est._window_xyw(bad) is None
            c = obs.registry().snapshot()["counters"]
            assert c.get("online.dropped_rows") == 2
        finally:
            obs.disable()
            obs.reset()

    def test_degenerate_window_mid_stream_skips_and_keeps_training(self):
        """A whole window of null-vector rows lands mid-stream: the loop
        must survive it, count the skip, and still converge — never an
        all-zero model."""
        from flink_ml_tpu import obs

        obs.enable()
        obs.reset()
        try:
            rows, X, y = stream_rows(400, seed=6)
            poisoned = list(rows)
            # window [1000, 2000) becomes all-degenerate: null vectors
            for i in range(20, 40):
                poisoned[i] = (None, rows[i][1])
            source = GeneratorSource.linear_timestamps(poisoned, 50, SCHEMA)
            model, result = make_estimator().fit_unbounded(source)
            assert result.windows_fired == 20
            c = obs.registry().snapshot()["counters"]
            assert c.get("online.skipped_windows") == 1
            assert c.get("online.dropped_rows") == 20
            w = model.coefficients()
            assert np.any(w != 0.0)
            t = Table.from_rows([(DenseVector(x),) for x in X], QSCHEMA)
            acc = np.mean((model.predict_proba(t) > 0.5) == (y == 1))
            assert acc > 0.85
        finally:
            obs.disable()
            obs.reset()

    def test_feature_cols_degenerate_rows_masked_not_crashed(self):
        """The row-wise fallback must also work for featureCols-configured
        estimators (no vector column to re-densify) — junk cells coerce
        to NaN and mask out."""
        from flink_ml_tpu.lib.online import OnlineLogisticRegression
        from flink_ml_tpu.table.schema import Schema

        schema = Schema(["f0", "f1", "label"],
                        ["double", "double", "double"])
        est = (
            OnlineLogisticRegression().set_feature_cols(["f0", "f1"])
            .set_label_col("label").set_prediction_col("pred")
            .set_learning_rate(0.5).set_window_ms(1000)
        )
        est._dim = 2
        bad = Table.from_columns(schema, {
            "f0": [1.0, None, 3.0], "f1": [2.0, 2.0, None],
            "label": [1.0, 0.0, 1.0],
        })
        xyw = est._window_xyw(bad)
        assert xyw is not None
        _, _, wp = xyw
        np.testing.assert_array_equal(wp[:3], [1.0, 0.0, 0.0])

    def test_junk_label_cells_coerce_to_nan(self):
        """Object-dtype label columns (nullable paths) coerce cell-wise:
        junk becomes NaN for the mask, never a coercion crash.  (A
        string in a typed double column is rejected at Table
        construction — this guards the object-column route.)"""
        from flink_ml_tpu.lib.online import _f64_or_nan

        assert _f64_or_nan(3) == 3.0
        assert np.isnan(_f64_or_nan(None))
        assert np.isnan(_f64_or_nan("n/a"))
        assert np.isnan(_f64_or_nan(object()))

    def test_masked_poison_row_is_bit_identical_to_its_absence(self):
        """A NaN-label row appended at a window's tail is zeroed and
        weight-0 masked — exactly a padding row, so the fitted params
        EQUAL the clean stream's bit for bit (weight-0 masking alone
        would let NaN * 0 poison the gradient)."""
        from flink_ml_tpu.table.sources import ColumnarUnboundedSource

        rng = np.random.RandomState(8)
        X = rng.randn(200, 3).astype(np.float32)
        y = ((X @ np.array([2.0, -1.5, 1.0], np.float32)) > 0).astype(
            np.float64)
        ts = np.arange(200, dtype=np.int64) * 50

        clean = ColumnarUnboundedSource(
            ts, {"features": X, "label": y}, SCHEMA)
        model_a, _ = make_estimator().fit_unbounded(clean)

        # the poison row rides at the END of window [0, 1000): ts 999
        cut = 20
        Xp = np.concatenate([X[:cut], rng.randn(1, 3).astype(np.float32),
                             X[cut:]])
        yp = np.concatenate([y[:cut], [np.nan], y[cut:]])
        tsp = np.concatenate([ts[:cut], [999], ts[cut:]])
        poisoned = ColumnarUnboundedSource(
            tsp, {"features": Xp, "label": yp}, SCHEMA)
        model_b, _ = make_estimator().fit_unbounded(poisoned)

        np.testing.assert_array_equal(
            model_b.coefficients(), model_a.coefficients())
        assert model_b.intercept() == model_a.intercept()


class TestSinglePassSource:
    def test_dim_probe_keeps_first_record(self):
        """Regression: _infer_dim peeks the first record off the stream; a
        single-pass (non-re-iterable) source must not lose it to the probe."""
        from flink_ml_tpu.table.sources import UnboundedSource

        rows, X, y = stream_rows(40, seed=3)

        class OneShotSource(UnboundedSource):
            def __init__(self):
                self.calls = 0

            def stream(self):
                self.calls += 1
                assert self.calls == 1, "stream() consumed more than once"
                return ((i * 50, rows[i]) for i in range(len(rows)))

            def schema(self):
                return SCHEMA

        model, result = make_estimator().fit_unbounded(OneShotSource())
        # all 40 rows trained: 20 rows / 1000ms window -> 2 windows
        assert result.windows_fired == 2
        assert model.coefficients().shape == (3,)
