"""Multi-device serving parity (ISSUE 15, tier-1).

The SPMD serving contract: serving on an 8-device mesh is a DEPLOYMENT
detail — every shipped mapper family (dense LR, sparse segment-CSR LR,
the scalers, KMeans assign, the Knn chunked scan) must produce the same
answers fused, staged, and across mesh widths (discrete outputs
bit-identical, floats within accumulation tolerance), quarantine
side-tables must carry the same original-feed offsets, and a
pressure-bisection run must recover bit-identically on the mesh.

The checks run in SUBPROCESSES (``XLA_FLAGS=--xla_force_host_platform_
device_count={8,1}``) because the device count pins at backend init:
the parent fits + saves the models once (model files are the
cross-process contract — both workers load identical bytes) and each
worker transforms identical deterministic tables; this module compares
their emitted results.  Until this PR, multi-chip correctness was only
exercised by scripts/scale_run.py dry-runs outside tier-1.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from tests.multichip_serve_worker import make_tables

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "multichip_serve_worker.py")


@pytest.fixture(scope="module")
def model_dir(tmp_path_factory):
    """Fit + save the five family pipelines ONCE; workers load them."""
    from flink_ml_tpu.api.pipeline import Pipeline
    from flink_ml_tpu.lib import KMeans, Knn, LogisticRegression
    from flink_ml_tpu.lib.feature import MinMaxScaler, StandardScaler

    dense, sparse = make_tables()
    root = tmp_path_factory.mktemp("multichip_models")
    Pipeline([
        StandardScaler().set_selected_col("features"),
        MinMaxScaler().set_selected_col("features"),
        LogisticRegression().set_vector_col("features")
        .set_label_col("label").set_prediction_col("pred")
        .set_prediction_detail_col("proba")
        .set_learning_rate(0.5).set_max_iter(4),
    ]).fit(dense).save(str(root / "dense_lr"))
    # MinMaxScaler(aux dense) + LR(sparse CSR) fuse into ONE dispatch
    # with a dense AND a segment-CSR input — the mixed sharded layout
    Pipeline([
        MinMaxScaler().set_selected_col("aux"),
        LogisticRegression().set_vector_col("features")
        .set_label_col("label").set_prediction_col("pred")
        .set_prediction_detail_col("proba")
        .set_learning_rate(0.5).set_max_iter(4),
    ]).fit(sparse).save(str(root / "sparse_lr"))
    Pipeline([
        StandardScaler().set_selected_col("features"),
        MinMaxScaler().set_selected_col("features"),
    ]).fit(dense).save(str(root / "scalers"))
    Pipeline([
        StandardScaler().set_selected_col("features"),
        KMeans().set_vector_col("features").set_k(4)
        .set_prediction_col("cluster").set_max_iter(3),
    ]).fit(dense).save(str(root / "kmeans"))
    Pipeline([
        StandardScaler().set_selected_col("features"),
        Knn().set_vector_col("features").set_label_col("label")
        .set_k(3).set_prediction_col("pred"),
    ]).fit(dense).save(str(root / "knn"))
    return str(root)


def _run_worker(model_dir: str, n_devices: int) -> dict:
    env = dict(os.environ)
    env.pop("FMT_FAULT_INJECT", None)
    env.pop("FMT_SERVE_MESH", None)
    env["FMT_OBS"] = "0"
    env["JAX_ENABLE_X64"] = "1"
    # replace (not append): the parent suite already forces 8 devices,
    # and XLA takes the FIRST occurrence of a repeated flag
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in f]
    flags.append(f"--xla_force_host_platform_device_count={n_devices}")
    env["XLA_FLAGS"] = " ".join(flags)
    out = subprocess.run(
        [sys.executable, WORKER, model_dir], capture_output=True,
        text=True, timeout=600, env=env, cwd=REPO,
    )
    assert out.returncode == 0, (out.stdout[-2000:], out.stderr[-4000:])
    lines = [ln for ln in out.stdout.splitlines()
             if ln.startswith("RESULT ")]
    assert lines, out.stdout
    return json.loads(lines[0][len("RESULT "):])


@pytest.fixture(scope="module")
def results(model_dir):
    """One worker per mesh width; in-worker fused-vs-staged parity has
    already been asserted by the time RESULT prints."""
    return {
        8: _run_worker(model_dir, 8),
        1: _run_worker(model_dir, 1),
    }


class TestMultichipServeParity:
    def test_workers_saw_their_meshes(self, results):
        assert results[8]["devices"] == 8
        assert results[1]["devices"] == 1

    @pytest.mark.parametrize("family,discrete_cols,float_cols", [
        ("dense_lr", ["pred"], ["proba"]),
        ("sparse_lr", ["pred"], ["proba"]),
        ("scalers", [], ["features"]),
        ("kmeans", ["cluster"], []),
        ("knn", ["pred"], []),
    ])
    def test_family_parity_8dev_vs_1dev(self, results, family,
                                        discrete_cols, float_cols):
        rec8 = results[8]["families"][family]
        rec1 = results[1]["families"][family]
        for c in discrete_cols:
            assert rec8[c] == rec1[c], (
                f"{family}.{c}: 8-device discrete outputs diverge from "
                "1-device")
        for c in float_cols:
            np.testing.assert_allclose(
                np.asarray(rec8[c]), np.asarray(rec1[c]),
                rtol=1e-4, atol=3e-5,
                err_msg=f"{family}.{c}: 8-device floats diverge",
            )

    def test_sharded_path_ran_on_the_mesh_only(self, results):
        """The 8-device worker must have dispatched through shard_map
        (the CSR bypass is gone); the 1-device worker must not have."""
        assert results[8]["shard_map_dispatches"] > 0, results[8]
        assert results[1]["shard_map_dispatches"] == 0, results[1]
        assert results[8]["fused_dispatches"] > 0
        assert results[8]["plan_fallbacks"] == 0, (
            "a fused plan silently fell back to the staged path on the "
            "8-device mesh")
        assert results[1]["plan_fallbacks"] == 0

    def test_quarantine_offsets_match_across_meshes(self, results):
        assert results[8]["quarantine_rows"] == [5, 130, 383]
        assert results[1]["quarantine_rows"] == [5, 130, 383]
        assert (results[8]["quarantine_survivor_pred"]
                == results[1]["quarantine_survivor_pred"])

    def test_pressure_bisection_on_the_mesh(self, results):
        """The injected HBM ceiling forces bisection on BOTH meshes
        (bit-identical recovery asserted in-worker); the 8-device cap is
        per-device-denominated, so it lands well below the 1-device
        surface's cap."""
        assert results[8]["bisections"] > 0
        assert results[1]["bisections"] > 0
        cap8, cap1 = results[8]["per_device_cap"], \
            results[1]["per_device_cap"]
        assert cap8 is not None and cap1 is not None
        # per-device denomination: both meshes converge to the SAME
        # global working size under the same row ceiling — the 8-device
        # mesh's cap is that size divided across its 8 shards, not a
        # collapse of the whole mesh to a 1-device budget
        assert cap8 * 8 == cap1, (cap8, cap1)
