"""Vectorized streaming ingest (columnar span processing) vs the per-record
merge loop: the two drivers must produce IDENTICAL StreamingResults on
time-ordered streams — same predictions (ts, value) for every record, same
windows fired, same final state, same model history.  The vectorized path is
the hot path (zero per-record Python); the per-record loop remains the
semantics oracle and the out-of-order/checkpointed path."""

import numpy as np
import pytest

from flink_ml_tpu.iteration.unbounded import StreamingDriver
from flink_ml_tpu.table.schema import DataTypes, Schema
from flink_ml_tpu.table.sources import (
    ColumnarUnboundedSource,
    GeneratorSource,
)

TRAIN_SCHEMA = Schema.of(("x", "double"), ("y", "double"))
PRED_SCHEMA = Schema.of(("x", "double"),)


def _train_rows(n, seed=0, interval=7):
    rng = np.random.RandomState(seed)
    ts = np.arange(n, dtype=np.int64) * interval
    x = rng.randn(n)
    y = rng.randn(n)
    return ts, x, y


def _pred_rows(n, seed=1, interval=11, offset=3):
    rng = np.random.RandomState(seed)
    ts = np.arange(n, dtype=np.int64) * interval + offset
    return ts, rng.randn(n)


def _update(state, table, epoch):
    # deterministic, order-sensitive: catches any row reordering
    x = np.asarray(table.col("x"))
    y = np.asarray(table.col("y"))
    return state + float(np.sum(x * y)) + 0.001 * float(x[0]) * (epoch + 1)


def _predict(state, table):
    x = np.asarray(table.col("x"))
    return (x * state).tolist()


def _per_record_sources(ts_t, x, y, ts_p=None, xp=None):
    """The same data as per-record sources (time_ordered=False forces the
    merge-loop path)."""
    train = GeneratorSource(
        lambda: iter(
            [(int(t), (float(a), float(b))) for t, a, b in zip(ts_t, x, y)]
        ),
        TRAIN_SCHEMA,
    )
    pred = None
    if ts_p is not None:
        pred = GeneratorSource(
            lambda: iter([(int(t), (float(a),)) for t, a in zip(ts_p, xp)]),
            PRED_SCHEMA,
        )
    return train, pred


def _columnar_sources(ts_t, x, y, ts_p=None, xp=None, chunk_rows=64):
    train = ColumnarUnboundedSource(
        ts_t, {"x": x, "y": y}, TRAIN_SCHEMA, chunk_rows=chunk_rows
    )
    pred = None
    if ts_p is not None:
        pred = ColumnarUnboundedSource(
            ts_p, {"x": xp}, PRED_SCHEMA, chunk_rows=chunk_rows
        )
    return train, pred


def _run(driver_kwargs, train, pred, **run_kwargs):
    driver = StreamingDriver(**driver_kwargs)
    if pred is not None:
        run_kwargs.setdefault("prediction_source", pred)
        run_kwargs.setdefault("predict", _predict)
    return driver.run(0.0, train, _update, **run_kwargs)


def _assert_same(r_vec, r_rec):
    assert r_vec.windows_fired == r_rec.windows_fired
    assert r_vec.final_state == pytest.approx(r_rec.final_state, rel=1e-12)
    assert len(r_vec.predictions) == len(r_rec.predictions)
    for (t1, v1), (t2, v2) in zip(r_vec.predictions, r_rec.predictions):
        assert t1 == t2
        assert v1 == pytest.approx(v2, rel=1e-12)
    assert [t for t, _ in r_vec.model_updates] == [
        t for t, _ in r_rec.model_updates
    ]
    for (_, s1), (_, s2) in zip(r_vec.model_updates, r_rec.model_updates):
        assert s1 == pytest.approx(s2, rel=1e-12)
    assert r_vec.late_records == [] and r_rec.late_records == []


class TestEquivalence:
    def test_train_only(self):
        ts, x, y = _train_rows(500)
        kw = dict(window_ms=100, keep_model_history=True)
        r_vec = _run(kw, *_columnar_sources(ts, x, y))
        r_rec = _run(kw, *_per_record_sources(ts, x, y))
        assert r_vec.windows_fired > 3
        _assert_same(r_vec, r_rec)

    def test_train_and_predict(self):
        ts, x, y = _train_rows(400)
        tp, xp = _pred_rows(300)
        kw = dict(window_ms=100, keep_model_history=True)
        r_vec = _run(kw, *_columnar_sources(ts, x, y, tp, xp))
        r_rec = _run(kw, *_per_record_sources(ts, x, y, tp, xp))
        assert len(r_vec.predictions) == 300
        _assert_same(r_vec, r_rec)

    def test_with_lateness_held_watermark(self):
        """allowed_lateness holds windows open; ordered streams still fire
        them in the same places on both paths."""
        ts, x, y = _train_rows(400)
        tp, xp = _pred_rows(250)
        kw = dict(window_ms=100, allowed_lateness_ms=150,
                  keep_model_history=True)
        r_vec = _run(kw, *_columnar_sources(ts, x, y, tp, xp))
        r_rec = _run(kw, *_per_record_sources(ts, x, y, tp, xp))
        _assert_same(r_vec, r_rec)

    def test_small_flush_rows(self):
        """Tiny prediction_flush_rows changes batch grouping, never values."""
        ts, x, y = _train_rows(300)
        tp, xp = _pred_rows(300)
        kw = dict(window_ms=100, prediction_flush_rows=16)
        r_vec = _run(kw, *_columnar_sources(ts, x, y, tp, xp))
        r_rec = _run(kw, *_per_record_sources(ts, x, y, tp, xp))
        _assert_same(r_vec, r_rec)

    def test_lateness_with_small_flush_rows(self):
        """allowed_lateness > 0 combined with a tiny prediction_flush_rows
        (ADVICE r4): the early-flush cut at watermark+1 must group flushes
        identically on both paths even while lateness holds windows open."""
        ts, x, y = _train_rows(400)
        tp, xp = _pred_rows(300)
        kw = dict(window_ms=100, allowed_lateness_ms=150,
                  prediction_flush_rows=8, keep_model_history=True)
        r_vec = _run(kw, *_columnar_sources(ts, x, y, tp, xp))
        r_rec = _run(kw, *_per_record_sources(ts, x, y, tp, xp))
        assert len(r_vec.predictions) == 300
        _assert_same(r_vec, r_rec)

    @pytest.mark.parametrize("max_windows", [1, 3, 7])
    def test_max_windows_stop(self, max_windows):
        """Mid-stream stop: the vectorized path serves exactly the
        predictions the per-record loop had consumed at its stopping
        record."""
        ts, x, y = _train_rows(400)
        tp, xp = _pred_rows(400)
        kw = dict(window_ms=100, keep_model_history=True)
        r_vec = _run(kw, *_columnar_sources(ts, x, y, tp, xp),
                     max_windows=max_windows)
        r_rec = _run(kw, *_per_record_sources(ts, x, y, tp, xp),
                     max_windows=max_windows)
        assert r_vec.windows_fired == max_windows
        _assert_same(r_vec, r_rec)

    def test_max_windows_firing_record_is_prediction(self):
        """The record that advances the watermark past the stopping window
        end is itself a prediction — it must be served, and nothing after."""
        ts_t = np.asarray([10, 20, 110], dtype=np.int64)  # window [0,100) + next
        x = np.asarray([1.0, 2.0, 3.0])
        y = np.asarray([1.0, 1.0, 1.0])
        # prediction at ts=105 arrives BEFORE the train record at 110; at
        # ts=100 exactly the window end: fires the window itself
        ts_p = np.asarray([5, 100, 100, 200], dtype=np.int64)
        xp = np.asarray([1.0, 2.0, 3.0, 4.0])
        kw = dict(window_ms=100)
        r_vec = _run(kw, *_columnar_sources(ts_t, x, y, ts_p, xp),
                     max_windows=1)
        r_rec = _run(kw, *_per_record_sources(ts_t, x, y, ts_p, xp),
                     max_windows=1)
        _assert_same(r_vec, r_rec)
        # the firing prediction (first at ts=100) is served; its twin at
        # the same ts and everything later never consumed
        assert [t for t, _ in r_vec.predictions] == [5, 100]

    def test_pred_stream_outlives_train(self):
        ts, x, y = _train_rows(100)
        tp, xp = _pred_rows(400, interval=13)
        kw = dict(window_ms=100)
        r_vec = _run(kw, *_columnar_sources(ts, x, y, tp, xp))
        r_rec = _run(kw, *_per_record_sources(ts, x, y, tp, xp))
        _assert_same(r_vec, r_rec)

    def test_train_stream_outlives_pred(self):
        ts, x, y = _train_rows(500)
        tp, xp = _pred_rows(40)
        kw = dict(window_ms=100)
        r_vec = _run(kw, *_columnar_sources(ts, x, y, tp, xp))
        r_rec = _run(kw, *_per_record_sources(ts, x, y, tp, xp))
        _assert_same(r_vec, r_rec)

    def test_listener_epochs_match(self):
        from flink_ml_tpu.iteration.listener import IterationListener

        class Rec(IterationListener):
            def __init__(self):
                self.epochs = []
                self.terminated = 0

            def on_epoch_watermark_incremented(self, epoch, ctx, collector=None):
                self.epochs.append(epoch)

            def on_iteration_terminated(self, ctx, collector=None):
                self.terminated += 1

        ts, x, y = _train_rows(300)
        l_vec, l_rec = Rec(), Rec()
        _run(dict(window_ms=100), *_columnar_sources(ts, x, y),
             listeners=[l_vec])
        _run(dict(window_ms=100), *_per_record_sources(ts, x, y),
             listeners=[l_rec])
        assert l_vec.epochs == l_rec.epochs and l_vec.epochs
        assert l_vec.terminated == l_rec.terminated == 1

    def test_chunk_boundary_straddles_window(self):
        """Windows spanning chunk boundaries accumulate across spans."""
        ts, x, y = _train_rows(257)  # prime-ish vs chunk_rows=32
        kw = dict(window_ms=1000)    # few big windows
        r_vec = _run(kw, *_columnar_sources(ts, x, y, chunk_rows=32))
        r_rec = _run(kw, *_per_record_sources(ts, x, y))
        _assert_same(r_vec, r_rec)

    def test_generator_source_time_ordered_takes_chunk_path(self):
        """linear_timestamps declares time order, so its chunk view exists
        and matches the per-record run."""
        rows = [(float(i), float(i % 3)) for i in range(200)]
        src = GeneratorSource.linear_timestamps(rows, 7, TRAIN_SCHEMA)
        assert src.stream_chunks() is not None
        r_vec = StreamingDriver(window_ms=100).run(0.0, src, _update)
        src2 = GeneratorSource(
            lambda: iter([(i * 7, r) for i, r in enumerate(rows)]),
            TRAIN_SCHEMA,
        )
        assert src2.stream_chunks() is None
        r_rec = StreamingDriver(window_ms=100).run(0.0, src2, _update)
        _assert_same(r_vec, r_rec)


class TestColumnarSource:
    def test_rejects_unordered_timestamps(self):
        with pytest.raises(ValueError, match="non-decreasing"):
            ColumnarUnboundedSource(
                [3, 1, 2], {"x": [1.0, 2.0, 3.0]}, PRED_SCHEMA
            )

    def test_rejects_length_mismatch(self):
        with pytest.raises(ValueError, match="length"):
            ColumnarUnboundedSource([1, 2], {"x": [1.0]}, PRED_SCHEMA)

    def test_rejects_missing_column(self):
        with pytest.raises(ValueError, match="missing column"):
            ColumnarUnboundedSource([1], {"z": [1.0]}, PRED_SCHEMA)

    def test_per_record_view_matches_chunks(self):
        """stream() decodes the same records the chunk view carries,
        including matrix-backed vector columns as DenseVectors."""
        from flink_ml_tpu.ops.vector import DenseVector

        schema = Schema.of(
            ("features", DataTypes.DENSE_VECTOR), ("label", "double")
        )
        X = np.arange(12, dtype=np.float64).reshape(4, 3)
        src = ColumnarUnboundedSource(
            [0, 1, 2, 3],
            {"features": X, "label": np.asarray([0.0, 1.0, 0.0, 1.0])},
            schema, chunk_rows=3,
        )
        recs = list(src.stream())
        assert [t for t, _ in recs] == [0, 1, 2, 3]
        assert type(recs[0][1][0]) is DenseVector
        np.testing.assert_array_equal(recs[2][1][0].values, X[2])

    def test_driver_validates_chunk_order_violation(self):
        """A lying time_ordered generator fails loudly, not silently."""
        rows = [(0, (1.0,)), (10, (2.0,)), (5, (3.0,))]
        src = GeneratorSource(lambda: iter(rows), PRED_SCHEMA,
                              time_ordered=True)
        with pytest.raises(ValueError, match="out-of-order"):
            StreamingDriver(window_ms=100).run(
                0.0, src, lambda s, t, e: s
            )


class TestReviewRegressions:
    def test_case_insensitive_vector_col_chunk_probe(self):
        """The dim probe resolves the vector column case-insensitively on
        the chunk path, like the per-record probe (TableUtil.findColIndex
        semantics)."""
        from flink_ml_tpu.lib.online import OnlineLogisticRegression

        rng = np.random.RandomState(0)
        n, d = 200, 4
        X = rng.randn(n, d)
        y = (rng.randn(n) > 0).astype(np.float64)
        schema = Schema.of(
            ("Features", DataTypes.DENSE_VECTOR), ("label", "double")
        )
        src = ColumnarUnboundedSource(
            np.arange(n, dtype=np.int64) * 10,
            {"Features": X, "label": y}, schema,
        )
        model, result = (
            OnlineLogisticRegression().set_vector_col("features")
            .set_label_col("label").set_prediction_col("p")
            .set_window_ms(500).fit_unbounded(src)
        )
        assert model.coefficients().shape == (d,)
        assert result.windows_fired > 0

    def test_mixed_matrix_and_list_segments_in_one_window(self):
        """Adjacent chunks of the same vector column columnizing
        differently (matrix vs object list — one ragged chunk) must still
        concatenate into a valid window table."""
        from flink_ml_tpu.ops.vector import DenseVector, SparseVector

        schema = Schema.of(
            ("features", DataTypes.VECTOR), ("label", "double")
        )
        # chunk 1: all dense width-3 (matrix-backed); chunk 2: one sparse
        # row forces the object-list form; both land in window [0, 1000)
        rows = [(DenseVector(np.asarray([float(i), 0.0, 1.0])), 1.0)
                for i in range(4)]
        rows += [(SparseVector(3, np.asarray([1]), np.asarray([2.0])), 0.0),
                 (DenseVector(np.asarray([9.0, 9.0, 9.0])), 1.0)]
        src = GeneratorSource(
            lambda: iter([(i * 10, r) for i, r in enumerate(rows)]),
            schema, time_ordered=True, chunk_rows=4,
        )
        seen = []

        def upd(state, table, epoch):
            seen.append(table.num_rows())
            # every row readable as a vector
            for v in table.col("features"):
                assert v.to_dense().size() == 3
            return state

        r = StreamingDriver(window_ms=1000).run(0.0, src, upd)
        assert r.windows_fired == 1 and seen == [6]

    def test_generator_chunk_rows_bounds_ingest_latency(self):
        """chunk_rows controls how much a time-ordered generator buffers
        before the driver can fire — a live source can match it to its
        window size."""
        rows = [(float(i), 1.0) for i in range(10)]
        fired_at = []

        def gen():
            for i, r in enumerate(rows):
                yield i * 100, r

        src = GeneratorSource(gen, TRAIN_SCHEMA, time_ordered=True,
                              chunk_rows=2)
        chunks = src.stream_chunks()
        first = next(iter(chunks))
        assert len(first[0]) == 2  # yields after 2 records, not 8192
        r = StreamingDriver(window_ms=200).run(
            0.0, GeneratorSource(gen, TRAIN_SCHEMA, time_ordered=True,
                                 chunk_rows=2),
            lambda s, t, e: fired_at.append(e) or s,
        )
        assert r.windows_fired == 5 and fired_at == [0, 1, 2, 3, 4]


class TestVectorizedStreamingEstimator:
    def test_online_lr_columnar_source(self):
        """OnlineLogisticRegression over a ColumnarUnboundedSource: the
        matrix-backed feature column rides zero-copy into the window
        update; results match the per-record GeneratorSource run."""
        from flink_ml_tpu.lib.online import OnlineLogisticRegression
        from flink_ml_tpu.ops.vector import DenseVector

        rng = np.random.RandomState(7)
        n, d = 2000, 8
        X = rng.randn(n, d)
        true_w = rng.randn(d)
        y = ((X @ true_w) > 0).astype(np.float64)
        schema = Schema.of(
            ("features", DataTypes.DENSE_VECTOR), ("label", "double")
        )
        ts = np.arange(n, dtype=np.int64) * 10

        def est():
            return (
                OnlineLogisticRegression().set_vector_col("features")
                .set_label_col("label").set_prediction_col("p")
                .set_learning_rate(0.5).set_window_ms(1000)
            )

        m_vec, r_vec = est().fit_unbounded(
            ColumnarUnboundedSource(
                ts, {"features": X, "label": y}, schema
            )
        )
        rows = [(DenseVector(X[i]), y[i]) for i in range(n)]
        m_rec, r_rec = est().fit_unbounded(
            GeneratorSource(
                lambda: iter([(int(ts[i]), rows[i]) for i in range(n)]),
                schema,
            )
        )
        assert r_vec.windows_fired == r_rec.windows_fired
        np.testing.assert_allclose(
            m_vec.coefficients(), m_rec.coefficients(), rtol=1e-6
        )


class TestCheckpointedVectorized:
    """VERDICT r4 #2: checkpointing must not leave the vectorized span path.
    Snapshots cut at span boundaries; either driver resumes either's
    snapshot (the cut is recorded as both a merged count and per-source
    counts over the deterministic (ts, kind) merge)."""

    def _cfg(self, tmp_path, **kw):
        from flink_ml_tpu.iteration.checkpoint import CheckpointConfig

        kw.setdefault("every_n_epochs", 2)
        return CheckpointConfig(directory=str(tmp_path / "ck"), **kw)

    @staticmethod
    def _crashing(at_epoch):
        def u(state, table, epoch):
            if epoch == at_epoch:
                raise RuntimeError("killed mid-stream")
            return _update(state, table, epoch)

        return u

    def test_vectorized_path_taken_with_checkpoint(self, tmp_path, monkeypatch):
        calls = {"vec": 0}
        orig = StreamingDriver._run_vectorized

        def spy(self, *a, **kw):
            calls["vec"] += 1
            return orig(self, *a, **kw)

        monkeypatch.setattr(StreamingDriver, "_run_vectorized", spy)
        ts, x, y = _train_rows(300)
        _run(dict(window_ms=100), *_columnar_sources(ts, x, y),
             checkpoint=self._cfg(tmp_path))
        assert calls["vec"] == 1

    def test_checkpointed_equals_uncheckpointed(self, tmp_path):
        ts, x, y = _train_rows(500)
        tp, xp = _pred_rows(300)
        kw = dict(window_ms=100, keep_model_history=True)
        base = _run(kw, *_columnar_sources(ts, x, y, tp, xp))
        ck = _run(kw, *_columnar_sources(ts, x, y, tp, xp),
                  checkpoint=self._cfg(tmp_path))
        _assert_same(ck, base)
        from flink_ml_tpu.iteration.checkpoint import latest_checkpoint

        assert latest_checkpoint(str(tmp_path / "ck")) is not None

    def test_kill_resume_matches_uninterrupted(self, tmp_path):
        ts, x, y = _train_rows(600)
        tp, xp = _pred_rows(400)
        kw = dict(window_ms=100, keep_model_history=True)
        base = _run(kw, *_columnar_sources(ts, x, y, tp, xp))

        cfg = self._cfg(tmp_path)
        with pytest.raises(RuntimeError, match="killed"):
            driver = StreamingDriver(**kw)
            driver.run(0.0, *_columnar_sources(ts, x, y)[:1],
                       self._crashing(9), checkpoint=cfg,
                       prediction_source=_columnar_sources(
                           ts, x, y, tp, xp)[1],
                       predict=_predict)
        resumed = _run(kw, *_columnar_sources(ts, x, y, tp, xp),
                       checkpoint=cfg)
        assert resumed.windows_fired == base.windows_fired
        assert resumed.final_state == pytest.approx(
            base.final_state, rel=1e-12
        )
        # post-resume emissions are a suffix of the uninterrupted run's
        n = len(resumed.predictions)
        assert n > 0
        for (t1, v1), (t2, v2) in zip(
            resumed.predictions, base.predictions[-n:]
        ):
            assert t1 == t2 and v1 == pytest.approx(v2, rel=1e-12)

    def test_lateness_kill_resume_open_windows(self, tmp_path):
        """Open (lateness-held) window buffers round-trip the columnar
        snapshot: with allowed_lateness several windows are open at every
        span cut, so the snapshot must carry them."""
        ts, x, y = _train_rows(500)
        kw = dict(window_ms=100, allowed_lateness_ms=250)
        base = _run(kw, *_columnar_sources(ts, x, y))
        cfg = self._cfg(tmp_path, every_n_epochs=1)
        with pytest.raises(RuntimeError, match="killed"):
            StreamingDriver(**kw).run(
                0.0, _columnar_sources(ts, x, y)[0], self._crashing(9),
                checkpoint=cfg,
            )
        resumed = _run(kw, *_columnar_sources(ts, x, y), checkpoint=cfg)
        assert resumed.windows_fired == base.windows_fired
        assert resumed.final_state == pytest.approx(
            base.final_state, rel=1e-12
        )

    def test_cross_path_resume_vec_to_per_record(self, tmp_path):
        """A snapshot cut by the span driver resumes on the per-record
        merge loop (per-source counts sum to the merged skip)."""
        ts, x, y = _train_rows(600)
        tp, xp = _pred_rows(400)
        kw = dict(window_ms=100)
        base = _run(kw, *_per_record_sources(ts, x, y, tp, xp))
        cfg = self._cfg(tmp_path)
        with pytest.raises(RuntimeError, match="killed"):
            driver = StreamingDriver(**kw)
            tr, pr = _columnar_sources(ts, x, y, tp, xp)
            driver.run(0.0, tr, self._crashing(9), checkpoint=cfg,
                       prediction_source=pr, predict=_predict)
        resumed = _run(kw, *_per_record_sources(ts, x, y, tp, xp),
                       checkpoint=cfg)
        assert resumed.windows_fired == base.windows_fired
        assert resumed.final_state == pytest.approx(
            base.final_state, rel=1e-12
        )

    def test_cross_path_resume_per_record_to_vec(self, tmp_path):
        """A snapshot cut by the per-record loop resumes on the span
        driver."""
        ts, x, y = _train_rows(600)
        tp, xp = _pred_rows(400)
        kw = dict(window_ms=100)
        base = _run(kw, *_columnar_sources(ts, x, y, tp, xp))
        cfg = self._cfg(tmp_path)
        with pytest.raises(RuntimeError, match="killed"):
            driver = StreamingDriver(**kw)
            tr, pr = _per_record_sources(ts, x, y, tp, xp)
            driver.run(0.0, tr, self._crashing(9), checkpoint=cfg,
                       prediction_source=pr, predict=_predict)
        resumed = _run(kw, *_columnar_sources(ts, x, y, tp, xp),
                       checkpoint=cfg)
        assert resumed.windows_fired == base.windows_fired
        assert resumed.final_state == pytest.approx(
            base.final_state, rel=1e-12
        )

    def test_min_interval_rate_limits_snapshots(self, tmp_path):
        import os

        ts, x, y = _train_rows(400)
        cfg_fast = self._cfg(tmp_path / "a", every_n_epochs=1)
        _run(dict(window_ms=100), *_columnar_sources(ts, x, y),
             checkpoint=cfg_fast)
        cfg_slow = self._cfg(tmp_path / "b", every_n_epochs=1,
                             min_interval_s=3600.0)
        _run(dict(window_ms=100), *_columnar_sources(ts, x, y),
             checkpoint=cfg_slow)
        assert os.path.isdir(cfg_fast.directory)
        assert not os.path.isdir(cfg_slow.directory)
