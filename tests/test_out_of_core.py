"""Out-of-core training tests (VERDICT r02 gap #1).

The contract under test: a fit that streams chunks from a file/source — with
an in-memory cap far smaller than the dataset — produces the *bit-identical*
model of the materialized in-memory fit, for any chunk size, because
step-major packing pins the row->SGD-step mapping regardless of chunking.
"""

import numpy as np
import pytest

from flink_ml_tpu.lib import LinearRegression, LogisticRegression
from flink_ml_tpu.ops.vector import SparseVector
from flink_ml_tpu.table.schema import DataTypes, Schema
from flink_ml_tpu.table.sources import (
    ChunkedTable,
    CollectionSource,
    CsvSource,
    LibSvmSource,
    ShardedSource,
)
from flink_ml_tpu.table.table import Table

SCHEMA = Schema.of(
    ("f0", "double"), ("f1", "double"), ("f2", "double"), ("label", "double")
)


def dense_data(n=5000, seed=7):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, 3)
    y = X @ np.array([2.0, -1.0, 0.5]) + 1.0 + 0.01 * rng.randn(n)
    table = Table.from_columns(
        SCHEMA, {"f0": X[:, 0], "f1": X[:, 1], "f2": X[:, 2], "label": y}
    )
    return table, X, y


def make_estimator(cls=LinearRegression, batch=256, iters=5):
    return (
        cls()
        .set_feature_cols(["f0", "f1", "f2"])
        .set_label_col("label")
        .set_prediction_col("pred")
        .set_learning_rate(0.05)
        .set_global_batch_size(batch)
        .set_max_iter(iters)
    )


class _CountingSource(CollectionSource):
    """Fails the test if anything materializes the full table."""

    def __init__(self, rows, schema):
        super().__init__(rows, schema)
        self.full_reads = 0

    def read(self):
        self.full_reads += 1
        return super().read()

    def read_chunks(self, max_rows):
        table = self._table
        for start in range(0, table.num_rows(), max_rows):
            yield table.slice_rows(start, min(start + max_rows, table.num_rows()))


class TestDenseOutOfCore:
    def test_bit_matches_in_memory_fit(self):
        table, X, y = dense_data()
        in_mem = make_estimator().fit(table)
        source = _CountingSource(table.to_rows(), SCHEMA)
        chunked = ChunkedTable(source, chunk_rows=1024)
        streamed = make_estimator().fit(chunked)
        np.testing.assert_array_equal(
            streamed.coefficients(), in_mem.coefficients()
        )
        assert streamed.intercept() == in_mem.intercept()
        assert source.full_reads == 0, "out-of-core fit materialized the table"
        assert streamed.train_epochs_ == in_mem.train_epochs_
        np.testing.assert_allclose(
            streamed.train_losses_, in_mem.train_losses_, rtol=1e-6
        )

    def test_chunk_size_invariance(self):
        table, _, _ = dense_data(3000)
        rows = table.to_rows()
        results = []
        for chunk_rows in (257, 1024, 2999, 5000):
            chunked = ChunkedTable(CollectionSource(rows, SCHEMA), chunk_rows)
            results.append(make_estimator(iters=3).fit(chunked).coefficients())
        for r in results[1:]:
            np.testing.assert_array_equal(r, results[0])

    def test_respects_memory_cap_and_trains_larger_dataset(self, tmp_path):
        """A CSV deliberately larger than the chunk cap streams through
        bounded chunks and still bit-matches the materialized fit."""
        table, X, y = dense_data(20000, seed=3)
        path = tmp_path / "big.csv"
        np.savetxt(path, np.column_stack([X, y]), delimiter=",", fmt="%.17g")
        cap_rows = 2048
        source = CsvSource(str(path), SCHEMA)
        max_seen = 0
        for chunk in source.read_chunks(cap_rows):
            max_seen = max(max_seen, chunk.num_rows())
        assert max_seen <= cap_rows
        in_mem = make_estimator(iters=3).fit(source.read())
        streamed = make_estimator(iters=3).fit(
            ChunkedTable(source, chunk_rows=cap_rows)
        )
        np.testing.assert_array_equal(
            streamed.coefficients(), in_mem.coefficients()
        )

    def test_sharded_source_matches_single_file(self, tmp_path):
        table, X, y = dense_data(4000, seed=11)
        data = np.column_stack([X, y])
        whole = tmp_path / "whole.csv"
        np.savetxt(whole, data, delimiter=",", fmt="%.17g")
        for i, lo in enumerate(range(0, 4000, 1000)):
            np.savetxt(
                tmp_path / f"part-{i:05d}.csv", data[lo : lo + 1000],
                delimiter=",", fmt="%.17g",
            )
        sharded = ShardedSource.glob(
            str(tmp_path / "part-*.csv"), lambda p: CsvSource(p, SCHEMA)
        )
        m1 = make_estimator(iters=3).fit(
            ChunkedTable(CsvSource(str(whole), SCHEMA), chunk_rows=640)
        )
        m2 = make_estimator(iters=3).fit(ChunkedTable(sharded, chunk_rows=640))
        np.testing.assert_array_equal(m2.coefficients(), m1.coefficients())

    def test_tol_early_stop_parity(self):
        table, _, _ = dense_data(2000)
        est = lambda: make_estimator(iters=200).set_tol(1e-3)  # noqa: E731
        in_mem = est().fit(table)
        streamed = est().fit(
            ChunkedTable(CollectionSource(table.to_rows(), SCHEMA), 512)
        )
        assert streamed.train_epochs_ == in_mem.train_epochs_
        np.testing.assert_array_equal(
            streamed.coefficients(), in_mem.coefficients()
        )

    def test_checkpoint_resume_matches_uninterrupted(self, tmp_path):
        table, _, _ = dense_data(2000)
        rows = table.to_rows()
        full = make_estimator(iters=6).fit(
            ChunkedTable(CollectionSource(rows, SCHEMA), 512)
        )
        ckpt = str(tmp_path / "ck")

        def est(iters):
            return (
                make_estimator(iters=iters)
                .set_checkpoint_dir(ckpt)
                .set_checkpoint_interval(2)
            )

        est(3).fit(ChunkedTable(CollectionSource(rows, SCHEMA), 512))
        resumed = est(6).fit(ChunkedTable(CollectionSource(rows, SCHEMA), 512))
        assert resumed.train_epochs_ == 6
        np.testing.assert_allclose(
            resumed.coefficients(), full.coefficients(), rtol=1e-6, atol=1e-9
        )

    def test_spill_bit_matches_direct_stream(self, tmp_path):
        """spill=True (binary blocks re-streamed from disk after epoch 1)
        replays the identical schedule: bit-equal to the direct stream."""
        table, X, y = dense_data(6000, seed=13)
        path = tmp_path / "d.csv"
        np.savetxt(path, np.column_stack([X, y]), delimiter=",", fmt="%.17g")
        source = CsvSource(str(path), SCHEMA)
        direct = make_estimator(iters=4).fit(ChunkedTable(source, 1500))
        spilled = make_estimator(iters=4).fit(
            ChunkedTable(source, 1500, spill=True)
        )
        np.testing.assert_array_equal(
            spilled.coefficients(), direct.coefficients()
        )

    def test_requires_explicit_batch_size(self):
        table, _, _ = dense_data(100)
        chunked = ChunkedTable(CollectionSource(table.to_rows(), SCHEMA), 64)
        with pytest.raises(ValueError, match="globalBatchSize"):
            make_estimator(batch=0).fit(chunked)


def sparse_data(n=3000, dim=500, nnz=8, seed=5):
    rng = np.random.RandomState(seed)
    true_w = rng.randn(dim) * (rng.rand(dim) < 0.2)
    vectors, labels = [], []
    for _ in range(n):
        idx = np.sort(rng.choice(dim, size=nnz, replace=False))
        vals = rng.randn(nnz)
        score = float(vals @ true_w[idx])
        labels.append(1.0 if score + 0.3 * rng.randn() > 0 else 0.0)
        vectors.append(SparseVector(dim, idx, vals))
    schema = Schema.of(("features", DataTypes.SPARSE_VECTOR), ("label", "double"))
    table = Table.from_columns(schema, {"features": vectors, "label": labels})
    return table, vectors, np.asarray(labels), dim


class TestSparseOutOfCore:
    def make_est(self, dim, iters=4):
        return (
            LogisticRegression()
            .set_vector_col("features")
            .set_label_col("label")
            .set_prediction_col("pred")
            .set_num_features(dim)
            .set_learning_rate(0.1)
            .set_global_batch_size(256)
            .set_max_iter(iters)
        )

    def test_bit_matches_in_memory_sparse_fit(self):
        table, vectors, labels, dim = sparse_data()
        in_mem = self.make_est(dim).fit(table)
        chunked = ChunkedTable(
            CollectionSource(table.to_rows(), table.schema), chunk_rows=700
        )
        streamed = self.make_est(dim).fit(chunked)
        np.testing.assert_array_equal(
            streamed.coefficients(), in_mem.coefficients()
        )
        assert streamed.intercept() == in_mem.intercept()

    def test_libsvm_stream_matches_materialized(self, tmp_path):
        table, vectors, labels, dim = sparse_data(n=1500)
        path = tmp_path / "data.svm"
        with open(path, "w") as f:
            for label, v in zip(labels, vectors):
                feats = " ".join(
                    f"{int(i) + 1}:{val:.17g}" for i, val in zip(v.indices, v.vals)
                )
                f.write(f"{label:g} {feats}\n")
        source = LibSvmSource(str(path), n_features=dim)
        in_mem = self.make_est(dim, iters=3).fit(source.read())
        streamed = self.make_est(dim, iters=3).fit(
            ChunkedTable(source, chunk_rows=400)
        )
        np.testing.assert_array_equal(
            streamed.coefficients(), in_mem.coefficients()
        )

    def test_chunked_libsvm_requires_dim(self, tmp_path):
        path = tmp_path / "d.svm"
        path.write_text("1 1:0.5 3:1.0\n0 2:0.25\n")
        source = LibSvmSource(str(path))
        with pytest.raises(ValueError, match="n_features"):
            next(source.read_chunks(10))

    def test_sparse_spill_bit_matches_direct_stream(self, tmp_path):
        """The two-leaf (ints, floats) sparse batch survives the npz
        round-trip bit-exactly."""
        table, vectors, labels, dim = sparse_data(n=1200)
        path = tmp_path / "s.svm"
        with open(path, "w") as f:
            for label, v in zip(labels, vectors):
                feats = " ".join(
                    f"{int(i) + 1}:{val:.17g}" for i, val in zip(v.indices, v.vals)
                )
                f.write(f"{label:g} {feats}\n")
        source = LibSvmSource(str(path), n_features=dim)
        direct = self.make_est(dim, iters=3).fit(ChunkedTable(source, 500))
        spilled = self.make_est(dim, iters=3).fit(
            ChunkedTable(source, 500, spill=True)
        )
        np.testing.assert_array_equal(
            spilled.coefficients(), direct.coefficients()
        )

    def test_overflowing_nnz_budget_fails_loudly(self):
        table, vectors, labels, dim = sparse_data(n=600, nnz=4)
        # densify the tail: the estimate from the stream head undershoots
        rng = np.random.RandomState(0)
        rows = table.to_rows()
        dense_tail = []
        for _, label in rows[-100:]:
            idx = np.sort(rng.choice(dim, size=400, replace=False))
            dense_tail.append((SparseVector(dim, idx, rng.randn(400)), label))
        source = CollectionSource(rows[:-100] + dense_tail, table.schema)
        with pytest.raises(ValueError, match="nnz_pad"):
            self.make_est(dim, iters=2).fit(ChunkedTable(source, chunk_rows=200))


class TestKMeansOutOfCore:
    def make_est(self, iters=8, tol=0.0):
        from flink_ml_tpu.lib import KMeans

        return (
            KMeans().set_feature_cols(["f0", "f1", "f2"])
            .set_prediction_col("cluster").set_k(5)
            .set_max_iter(iters).set_tol(tol).set_seed(7)
        )

    def test_matches_in_memory_fit(self):
        """Same init (stream-head sample == full sample under the cap), same
        Lloyd schedule; centroids agree to accumulation-order tolerance."""
        table, _, _ = dense_data(4000, seed=21)
        in_mem = self.make_est().fit(table)
        chunked = ChunkedTable(
            CollectionSource(table.to_rows(), SCHEMA), chunk_rows=900
        )
        streamed = self.make_est().fit(chunked)
        assert streamed.train_epochs_ == in_mem.train_epochs_
        np.testing.assert_allclose(
            np.sort(streamed.centroids(), axis=0),
            np.sort(in_mem.centroids(), axis=0),
            rtol=1e-4, atol=1e-5,
        )
        np.testing.assert_allclose(
            streamed.train_cost_, in_mem.train_cost_, rtol=1e-4
        )

    def test_streams_larger_than_cap_csv(self, tmp_path):
        table, X, y = dense_data(15000, seed=22)
        path = tmp_path / "km.csv"
        np.savetxt(path, np.column_stack([X, y]), delimiter=",", fmt="%.17g")
        source = CsvSource(str(path), SCHEMA)
        in_mem = self.make_est(iters=5).fit(source.read())
        streamed = self.make_est(iters=5).fit(
            ChunkedTable(source, chunk_rows=2048, spill=True)
        )
        np.testing.assert_allclose(
            np.sort(streamed.centroids(), axis=0),
            np.sort(in_mem.centroids(), axis=0),
            rtol=1e-4, atol=1e-5,
        )

    def test_checkpoint_resume(self, tmp_path):
        table, _, _ = dense_data(3000, seed=23)
        rows = table.to_rows()
        full = self.make_est(iters=6).fit(
            ChunkedTable(CollectionSource(rows, SCHEMA), 800)
        )
        ckpt = str(tmp_path / "ck")

        def est(iters):
            return (
                self.make_est(iters=iters)
                .set_checkpoint_dir(ckpt)
                .set_checkpoint_interval(2)
            )

        est(3).fit(ChunkedTable(CollectionSource(rows, SCHEMA), 800))
        resumed = est(6).fit(ChunkedTable(CollectionSource(rows, SCHEMA), 800))
        assert resumed.train_epochs_ == 6
        np.testing.assert_allclose(
            resumed.centroids(), full.centroids(), rtol=1e-5, atol=1e-6
        )

    def test_init_sample_is_uniform_over_grouped_stream(self):
        """Over-cap, cluster-grouped data: the reservoir init sample must
        cover the whole stream, not just its head."""
        from flink_ml_tpu.lib.out_of_core import reservoir_sample_rows

        rows = [(float(i), 0.0, 0.0, 0.0) for i in range(10000)]
        table_src = CollectionSource(rows, SCHEMA)
        chunked = ChunkedTable(table_src, chunk_rows=1000)
        rng = np.random.RandomState(0)
        sample, seen = reservoir_sample_rows(
            chunked.chunks(),
            lambda t: (t.numeric_matrix(["f0"]),),
            cap=500, rng=rng,
        )
        assert seen == 10000 and sample.shape == (500, 1)
        # head-biased sampling would put everything under 500; uniform
        # sampling spreads across [0, 10000)
        assert np.median(sample) > 3000
        assert sample.max() > 9000


def mesh_2d(data, model):
    """Context manager swapping the default environment onto a
    (data x model) mesh for the duration."""
    import contextlib

    import jax

    from flink_ml_tpu.parallel.mesh import create_mesh
    from flink_ml_tpu.utils.environment import MLEnvironmentFactory

    @contextlib.contextmanager
    def ctx():
        env = MLEnvironmentFactory.get_default()
        old = env.get_mesh()
        env.set_mesh(
            create_mesh({"data": data, "model": model},
                        jax.devices()[: data * model])
        )
        try:
            yield
        finally:
            env.set_mesh(old)

    return ctx()


class TestOutOfCore2D:
    """The north-star configuration: rows stream over the 'data' axis while
    the sparse weight vector shards over 'model' (Criteo-scale data AND a
    wider-than-one-chip model at once)."""

    def _mesh(self, data, model):
        return mesh_2d(data, model)

    def test_sparse_2d_stream_matches_in_memory_2d(self):
        table, vectors, labels, dim = sparse_data(n=2000, dim=501)

        def est():
            return (
                LogisticRegression().set_vector_col("features")
                .set_label_col("label").set_prediction_col("p")
                .set_num_features(dim).set_learning_rate(0.1)
                .set_global_batch_size(256).set_max_iter(4)
            )

        with self._mesh(4, 2):
            in_mem = est().fit(table)
            streamed = est().fit(
                ChunkedTable(CollectionSource(table.to_rows(), table.schema), 700)
            )
        assert streamed.coefficients().shape == (dim,)
        np.testing.assert_array_equal(
            streamed.coefficients(), in_mem.coefficients()
        )
        assert streamed.intercept() == in_mem.intercept()

    def test_sparse_2d_matches_1d_result(self):
        table, vectors, labels, dim = sparse_data(n=1600, dim=500)

        def est():
            return (
                LogisticRegression().set_vector_col("features")
                .set_label_col("label").set_prediction_col("p")
                .set_num_features(dim).set_learning_rate(0.1)
                .set_global_batch_size(256).set_max_iter(3)
            )

        chunked = lambda: ChunkedTable(  # noqa: E731
            CollectionSource(table.to_rows(), table.schema), 600
        )
        with self._mesh(4, 2):
            w2 = est().fit(chunked()).coefficients()
        with self._mesh(8, 1):
            w1 = est().fit(chunked()).coefficients()
        np.testing.assert_allclose(w2, w1, rtol=1e-5, atol=1e-7)

    def test_dense_stream_on_2d_mesh(self):
        table, _, _ = dense_data(3000)
        with self._mesh(4, 2):
            streamed = make_estimator(iters=3).fit(
                ChunkedTable(CollectionSource(table.to_rows(), SCHEMA), 800)
            )
            in_mem = make_estimator(iters=3).fit(table)
        np.testing.assert_array_equal(
            streamed.coefficients(), in_mem.coefficients()
        )

    def test_kmeans_stream_on_2d_mesh(self):
        table, _, _ = dense_data(2400, seed=31)
        from flink_ml_tpu.lib import KMeans

        def est():
            return (
                KMeans().set_feature_cols(["f0", "f1", "f2"])
                .set_prediction_col("c").set_k(4).set_max_iter(4).set_seed(2)
            )

        chunked = lambda: ChunkedTable(  # noqa: E731
            CollectionSource(table.to_rows(), SCHEMA), 600
        )
        with self._mesh(4, 2):
            c2 = est().fit(chunked()).centroids()
        with self._mesh(8, 1):
            c1 = est().fit(chunked()).centroids()
        np.testing.assert_allclose(
            np.sort(c2, axis=0), np.sort(c1, axis=0), rtol=1e-4, atol=1e-5
        )


class TestPipelineIntegration:
    def test_single_stage_pipeline_accepts_chunked_table(self):
        """Pipeline.fit passes a ChunkedTable straight to the estimator
        (the reference's pipeline over a partitioned source)."""
        from flink_ml_tpu.api.pipeline import Pipeline

        table, _, _ = dense_data(2000)
        chunked = ChunkedTable(CollectionSource(table.to_rows(), SCHEMA), 512)
        pipeline_model = Pipeline([make_estimator(iters=3)]).fit(chunked)
        direct = make_estimator(iters=3).fit(
            ChunkedTable(CollectionSource(table.to_rows(), SCHEMA), 512)
        )
        (out,) = pipeline_model.transform(table)
        direct_out = direct.transform(table)[0]
        np.testing.assert_array_equal(
            np.asarray(out.col("pred")), np.asarray(direct_out.col("pred"))
        )

    def test_dense_vector_col_stream_peeks_dim(self):
        """vectorCol dense streaming with no numFeatures pins the width by
        peeking one chunk, then bit-matches the in-memory fit."""
        from flink_ml_tpu.ops.vector import DenseVector

        rng = np.random.RandomState(17)
        X = rng.randn(3000, 4)
        y = X @ np.array([1.0, -1.0, 2.0, 0.5]) + 0.2
        schema = Schema.of(("features", DataTypes.DENSE_VECTOR), ("label", "double"))
        rows = [(DenseVector(r), float(v)) for r, v in zip(X, y)]
        table = Table.from_rows(rows, schema)

        def est():
            return (
                LinearRegression().set_vector_col("features")
                .set_label_col("label").set_prediction_col("p")
                .set_learning_rate(0.05).set_global_batch_size(256)
                .set_max_iter(3)
            )

        in_mem = est().fit(table)
        streamed = est().fit(
            ChunkedTable(CollectionSource(rows, schema), chunk_rows=700)
        )
        np.testing.assert_array_equal(
            streamed.coefficients(), in_mem.coefficients()
        )


class TestStreamedInference:
    def test_transform_chunks_matches_whole_transform(self, tmp_path):
        """Scoring a file chunk by chunk (model resident on device across
        chunks) equals scoring the materialized table, and the CSV sink
        round-trips the streamed output."""
        from flink_ml_tpu.utils.persistence import write_csv_chunks

        table, X, y = dense_data(6000, seed=41)
        path = tmp_path / "in.csv"
        np.savetxt(path, np.column_stack([X, y]), delimiter=",", fmt="%.17g")
        source = CsvSource(str(path), SCHEMA)
        model = make_estimator(iters=3).fit(ChunkedTable(source, 1500))

        whole = model.transform(source.read())[0]
        streamed = Table.concat(
            list(model.transform_chunks(ChunkedTable(source, 1100)))
        )
        np.testing.assert_array_equal(
            np.asarray(streamed.col("pred")), np.asarray(whole.col("pred"))
        )

        out_path = tmp_path / "scored.csv"
        n = write_csv_chunks(
            model.transform_chunks(ChunkedTable(source, 1100)), str(out_path)
        )
        assert n == 6000
        out_schema = Schema.of(
            *[(name, "double") for name in streamed.schema.field_names]
        )
        read_back = CsvSource(str(out_path), out_schema, skip_header=True).read()
        np.testing.assert_allclose(
            np.asarray(read_back.col("pred")),
            np.asarray(whole.col("pred")), rtol=1e-15,
        )

    def test_pipeline_model_streams_inference_too(self, tmp_path):
        from flink_ml_tpu.api.pipeline import Pipeline

        table, X, y = dense_data(3000, seed=43)
        path = tmp_path / "p.csv"
        np.savetxt(path, np.column_stack([X, y]), delimiter=",", fmt="%.17g")
        source = CsvSource(str(path), SCHEMA)
        pm = Pipeline([make_estimator(iters=3)]).fit(ChunkedTable(source, 800))
        whole = pm.transform(source.read())[0]
        streamed = Table.concat(list(pm.transform_chunks(ChunkedTable(source, 700))))
        np.testing.assert_array_equal(
            np.asarray(streamed.col("pred")), np.asarray(whole.col("pred"))
        )


class TestFeatureInteractions:
    """Combinations of out-of-core features that could interact badly:
    spill x checkpoint x kill, sharded libsvm files, 2-D x spill."""

    def test_spill_plus_checkpoint_resume(self, tmp_path):
        _, X, y = dense_data(4000, seed=51)
        path = tmp_path / "d.csv"
        np.savetxt(path, np.column_stack([X, y]), delimiter=",", fmt="%.17g")
        source = CsvSource(str(path), SCHEMA)
        full = make_estimator(iters=6).fit(
            ChunkedTable(source, 1000, spill=True)
        )
        ckpt = str(tmp_path / "ck")

        def est(iters):
            return (
                make_estimator(iters=iters)
                .set_checkpoint_dir(ckpt).set_checkpoint_interval(2)
            )

        est(3).fit(ChunkedTable(source, 1000, spill=True))
        resumed = est(6).fit(ChunkedTable(source, 1000, spill=True))
        assert resumed.train_epochs_ == 6
        np.testing.assert_allclose(
            resumed.coefficients(), full.coefficients(), rtol=1e-6, atol=1e-9
        )

    def test_sharded_libsvm_files_stream(self, tmp_path):
        table, vectors, labels, dim = sparse_data(n=1800)
        per = 600
        for s in range(3):
            with open(tmp_path / f"part-{s}.svm", "w") as f:
                for i in range(s * per, (s + 1) * per):
                    v = vectors[i]
                    feats = " ".join(
                        f"{int(j) + 1}:{val:.17g}"
                        for j, val in zip(v.indices, v.vals)
                    )
                    f.write(f"{labels[i]:g} {feats}\n")
        sharded = ShardedSource.glob(
            str(tmp_path / "part-*.svm"),
            lambda p: LibSvmSource(p, n_features=dim),
        )
        est = (
            LogisticRegression().set_vector_col("features")
            .set_label_col("label").set_prediction_col("p")
            .set_num_features(dim).set_learning_rate(0.1)
            .set_global_batch_size(256).set_max_iter(3)
        )
        streamed = est.fit(ChunkedTable(sharded, chunk_rows=500))
        in_mem = (
            LogisticRegression().set_vector_col("features")
            .set_label_col("label").set_prediction_col("p")
            .set_num_features(dim).set_learning_rate(0.1)
            .set_global_batch_size(256).set_max_iter(3)
            .fit(sharded.read())
        )
        np.testing.assert_array_equal(
            streamed.coefficients(), in_mem.coefficients()
        )

    def test_2d_mesh_with_spill(self, tmp_path):
        table, vectors, labels, dim = sparse_data(n=1200, dim=500)
        path = tmp_path / "s.svm"
        with open(path, "w") as f:
            for label, v in zip(labels, vectors):
                feats = " ".join(
                    f"{int(i) + 1}:{val:.17g}"
                    for i, val in zip(v.indices, v.vals)
                )
                f.write(f"{label:g} {feats}\n")
        source = LibSvmSource(str(path), n_features=dim)

        def est():
            return (
                LogisticRegression().set_vector_col("features")
                .set_label_col("label").set_prediction_col("p")
                .set_num_features(dim).set_learning_rate(0.1)
                .set_global_batch_size(256).set_max_iter(4)
            )

        with mesh_2d(4, 2):
            direct = est().fit(ChunkedTable(source, 400))
            spilled = est().fit(ChunkedTable(source, 400, spill=True))
        np.testing.assert_array_equal(
            spilled.coefficients(), direct.coefficients()
        )


class _ParseCountingSource:
    """Counts full chunk-stream iterations of the wrapped source — each one
    is a text parse the chunk cache exists to eliminate."""

    def __init__(self, inner):
        self.inner = inner
        self.chunk_reads = 0

    def schema(self):
        return self.inner.schema()

    def read_chunks(self, max_rows):
        self.chunk_reads += 1
        return self.inner.read_chunks(max_rows)

    def read(self):
        return self.inner.read()


class TestChunkSpillCache:
    """VERDICT r4 #3: fold the layout pre-pass into the spill pass — fits
    with a full pre-pass read the text source exactly once."""

    def _libsvm(self, tmp_path, n=1200, dim=400, nnz=6):
        table, vectors, labels, dim = sparse_data(n=n, dim=dim, nnz=nnz)
        path = tmp_path / "c.svm"
        with open(path, "w") as f:
            for label, v in zip(labels, vectors):
                feats = " ".join(
                    f"{int(i) + 1}:{val:.17g}"
                    for i, val in zip(v.indices, v.vals)
                )
                f.write(f"{label:g} {feats}\n")
        return LibSvmSource(str(path), n_features=dim), dim

    def test_replay_matches_recorded_chunks(self, tmp_path):
        from flink_ml_tpu.table.sources import chunk_cache

        source, dim = self._libsvm(tmp_path)
        counting = _ParseCountingSource(source)
        chunked = ChunkedTable(counting, chunk_rows=300, spill=True)
        with chunk_cache(chunked) as cached:
            first = [
                (np.asarray(t.col("label")).copy(), t.col("features"))
                for t in cached.chunks()
            ]
            second = [
                (np.asarray(t.col("label")), t.col("features"))
                for t in cached.chunks()
            ]
        assert counting.chunk_reads == 1  # second pass replayed binary
        assert len(first) == len(second)
        for (y1, v1), (y2, v2) in zip(first, second):
            np.testing.assert_array_equal(y1, y2)
            np.testing.assert_array_equal(
                np.asarray(v1.indices), np.asarray(v2.indices)
            )
            np.testing.assert_array_equal(
                np.asarray(v1.values), np.asarray(v2.values)
            )
            np.testing.assert_array_equal(
                np.asarray(v1.indptr), np.asarray(v2.indptr)
            )

    def test_partial_pass_leaves_cache_incomplete(self, tmp_path):
        from flink_ml_tpu.table.sources import chunk_cache

        source, dim = self._libsvm(tmp_path)
        counting = _ParseCountingSource(source)
        chunked = ChunkedTable(counting, chunk_rows=300, spill=True)
        with chunk_cache(chunked) as cached:
            it = cached.chunks()
            next(it)  # schema/width peek shape: consume one chunk, stop
            close = getattr(it, "close", None)
            if close:
                close()
            full = list(cached.chunks())  # re-records from text
            again = list(cached.chunks())  # replays
        assert counting.chunk_reads == 2
        assert len(full) == len(again)

    def test_uncacheable_column_falls_back_to_reparsing(self, tmp_path):
        from flink_ml_tpu.table.sources import chunk_cache

        table, vectors, labels, dim = sparse_data(n=400)
        # CollectionSource chunks carry per-row SparseVector objects (an
        # object column) -> uncacheable; behavior must be unchanged
        source = _ParseCountingSource(
            CollectionSource(table.to_rows(), table.schema)
        )
        chunked = ChunkedTable(source, chunk_rows=150, spill=True)
        with chunk_cache(chunked) as cached:
            a = sum(t.num_rows() for t in cached.chunks())
            b = sum(t.num_rows() for t in cached.chunks())
        assert a == b == 400
        assert source.chunk_reads == 2  # no caching: both passes parse

    def test_hotcold_ooc_fit_parses_text_once(self, tmp_path):
        source, dim = self._libsvm(tmp_path, n=1500)
        counting = _ParseCountingSource(source)
        est = (
            LogisticRegression()
            .set_vector_col("features")
            .set_label_col("label")
            .set_prediction_col("pred")
            .set_num_features(dim)
            .set_learning_rate(0.1)
            .set_global_batch_size(256)
            .set_max_iter(3)
            .set_num_hot_features(64)
        )
        cached_fit = est.fit(ChunkedTable(counting, 500, spill=True))
        # the frequency/layout scan is the ONE text parse; the pack pass
        # replays its binary recording and steady epochs read the packed
        # BlockSpill
        assert counting.chunk_reads == 1
        # result identical to the uncached fit
        est2 = (
            LogisticRegression()
            .set_vector_col("features")
            .set_label_col("label")
            .set_prediction_col("pred")
            .set_num_features(dim)
            .set_learning_rate(0.1)
            .set_global_batch_size(256)
            .set_max_iter(3)
            .set_num_hot_features(64)
        )
        plain_fit = est2.fit(ChunkedTable(source, 500))
        np.testing.assert_array_equal(
            cached_fit.coefficients(), plain_fit.coefficients()
        )

    def test_kmeans_ooc_fit_parses_text_once(self, tmp_path):
        rng = np.random.RandomState(0)
        X = rng.randn(900, 8)
        path = tmp_path / "k.csv"
        np.savetxt(path, X, delimiter=",")
        from flink_ml_tpu.lib import KMeans
        from flink_ml_tpu.table.sources import CsvSource

        schema = Schema.of(*[(f"f{i}", "double") for i in range(8)])
        source = _ParseCountingSource(CsvSource(str(path), schema))
        est = (
            KMeans().set_feature_cols([f"f{i}" for i in range(8)])
            .set_prediction_col("c").set_k(5).set_max_iter(3).set_seed(1)
        )
        est.fit(ChunkedTable(source, 250, spill=True))
        # init reservoir pass records; first Lloyd epoch replays binary;
        # steady epochs read the packed spill
        assert source.chunk_reads == 1


class TestChunkSpillCacheInterleaving:
    """ADVICE r5 low: an abandoned partial recording generator resumed
    after (or interleaved with) a second chunks() pass must never splice
    its descriptors into the other pass's replay sequence — descriptors
    publish atomically on exhaustion."""

    def _cached(self, tmp_path, n=900):
        from flink_ml_tpu.table.sources import ChunkSpillCache

        table, vectors, labels, dim = sparse_data(n=n, dim=120, nnz=4)
        path = tmp_path / "i.svm"
        with open(path, "w") as f:
            for label, v in zip(labels, vectors):
                feats = " ".join(
                    f"{int(i) + 1}:{val:.17g}"
                    for i, val in zip(v.indices, v.vals)
                )
                f.write(f"{label:g} {feats}\n")
        source = _ParseCountingSource(LibSvmSource(str(path), n_features=dim))
        chunked = ChunkedTable(source, chunk_rows=300, spill=True)
        return ChunkSpillCache(chunked, str(tmp_path / "cache")), source

    def test_interleaved_passes_replay_coherently(self, tmp_path):
        cached, source = self._cached(tmp_path)
        it1 = cached.chunks()  # recording pass 1 ...
        first1 = next(it1)
        it2 = cached.chunks()  # ... interleaved with recording pass 2
        chunks2 = [np.asarray(t.col("label")).copy() for t in it2]
        rest1 = [np.asarray(t.col("label")).copy() for t in it1]
        assert len(chunks2) == 3
        assert 1 + len(rest1) == 3
        # both passes parsed text (neither replay); the cache holds ONE
        # coherent pass, never a splice of the two
        replay = [np.asarray(t.col("label")) for t in cached.chunks()]
        assert len(replay) == 3
        for got, want in zip(replay, chunks2):
            np.testing.assert_array_equal(got, want)
        assert source.chunk_reads == 2  # the replay pass read no text

    def test_abandoned_partial_pass_does_not_publish(self, tmp_path):
        cached, source = self._cached(tmp_path)
        it = cached.chunks()
        next(it)  # partial: one chunk consumed, generator dropped
        close = getattr(it, "close", None)
        if close:
            close()
        assert not cached._complete
        assert cached._chunks == []  # nothing published by the partial pass
        full = [np.asarray(t.col("label")).copy() for t in cached.chunks()]
        assert cached._complete
        replay = [np.asarray(t.col("label")) for t in cached.chunks()]
        for got, want in zip(replay, full):
            np.testing.assert_array_equal(got, want)
        assert source.chunk_reads == 2
