"""Table layer tests — parity with TableUtilTest, OutputColsHelperTest (44-194),
DataStreamConversionUtilTest failure modes, plus columnar/device-bridge coverage."""

import numpy as np
import pytest

from flink_ml_tpu.ops import DenseVector, SparseVector
from flink_ml_tpu.table import (
    CollectionSource,
    CsvSource,
    DataTypes,
    GeneratorSource,
    LibSvmSource,
    OutputColsHelper,
    Schema,
    Table,
    table_util,
)


def _schema():
    return Schema(["id", "f1", "f2"], [DataTypes.INT, DataTypes.FLOAT, DataTypes.DOUBLE])


class TestSchema:
    def test_case_insensitive_lookup(self):
        s = _schema()
        assert s.find_col_index("F1") == 1
        assert s.find_col_index("nope") == -1
        assert s.type_of("ID") == DataTypes.INT
        assert s.resolve("iD") == "id"

    def test_select_missing_raises(self):
        with pytest.raises(ValueError, match="not found"):
            _schema().select(["id", "zz"])

    def test_round_trip_dict(self):
        s = _schema()
        assert Schema.from_dict(s.to_dict()) == s


class TestTable:
    def test_from_rows_and_back(self):
        t = Table.from_rows([(1, 2.0, 3.0), (4, 5.0, 6.0)], _schema())
        assert t.num_rows() == 2
        assert t.to_rows()[1][0] == 4
        assert t.col("F2").tolist() == [3.0, 6.0]

    def test_row_arity_check(self):
        with pytest.raises(ValueError, match="arity"):
            Table.from_rows([(1, 2.0)], _schema())

    def test_ragged_columns_raise(self):
        with pytest.raises(ValueError, match="ragged"):
            Table(_schema(), {"id": np.zeros(2), "f1": np.zeros(3), "f2": np.zeros(2)})

    def test_select_with_column_slice(self):
        t = Table.from_rows([(1, 2.0, 3.0), (4, 5.0, 6.0)], _schema())
        sel = t.select(["id"])
        assert sel.schema.field_names == ["id"]
        t2 = t.with_column("pred", DataTypes.DOUBLE, [0.1, 0.9])
        assert t2.schema.field_names == ["id", "f1", "f2", "pred"]
        t3 = t2.with_column("f1", DataTypes.DOUBLE, [9.0, 9.0])  # replace keeps position
        assert t3.schema.field_names == ["id", "f1", "f2", "pred"]
        assert t3.col("f1").tolist() == [9.0, 9.0]
        assert t.slice_rows(1, 2).to_rows() == [(4, 5.0, 6.0)]

    def test_concat_and_batches(self):
        t = Table.from_rows([(1, 2.0, 3.0), (4, 5.0, 6.0), (7, 8.0, 9.0)], _schema())
        parts = list(t.iter_batches(2))
        assert [p.num_rows() for p in parts] == [2, 1]
        back = Table.concat(parts)
        assert back.to_rows() == t.to_rows()

    def test_vector_column_bridge(self):
        s = Schema(["features", "label"], [DataTypes.VECTOR, DataTypes.DOUBLE])
        t = Table.from_rows(
            [(DenseVector([1, 2]), 1.0), (SparseVector(2, [1], [5.0]), 0.0)], s
        )
        dense = t.features_dense("features")
        assert dense.tolist() == [[1, 2], [0, 5]]
        csr = t.features_csr("features", n_cols=2, pad_multiple=8)
        assert np.asarray(csr.to_dense()).tolist() == [[1, 2], [0, 5]]

    def test_vector_column_type_check(self):
        s = Schema(["features"], [DataTypes.VECTOR])
        with pytest.raises(TypeError, match="non-vector"):
            Table.from_rows([("not a vector",)], s)

    def test_numeric_matrix(self):
        t = Table.from_rows([(1, 2.0, 3.0), (4, 5.0, 6.0)], _schema())
        m = t.numeric_matrix(["f1", "f2"])
        assert m.tolist() == [[2, 3], [5, 6]]
        s2 = Schema(["a"], [DataTypes.STRING])
        t2 = Table.from_rows([("x",)], s2)
        with pytest.raises(ValueError, match="numeric"):
            t2.numeric_matrix(["a"])


class TestOutputColsHelper:
    """Mirrors OutputColsHelperTest.java:44-194 rule coverage."""

    def test_javadoc_example(self):
        helper = OutputColsHelper(
            _schema(), ["label"], [DataTypes.STRING], reserved_col_names=["id"]
        )
        rs = helper.get_result_schema()
        assert rs.field_names == ["id", "label"]
        assert rs.field_types == [DataTypes.INT, DataTypes.STRING]

    def test_reserve_all_default(self):
        helper = OutputColsHelper(_schema(), ["label"], [DataTypes.STRING])
        assert helper.get_result_schema().field_names == ["id", "f1", "f2", "label"]

    def test_output_overrides_in_place(self):
        helper = OutputColsHelper(_schema(), ["f1"], [DataTypes.STRING])
        rs = helper.get_result_schema()
        assert rs.field_names == ["id", "f1", "f2"]
        assert rs.field_types == [DataTypes.INT, DataTypes.STRING, DataTypes.DOUBLE]

    def test_merge_values(self):
        t = Table.from_rows([(1, 2.0, 3.0), (4, 5.0, 6.0)], _schema())
        helper = OutputColsHelper(
            t.schema, ["pred"], [DataTypes.DOUBLE], reserved_col_names=["id", "f2"]
        )
        out = helper.get_result_table(t, {"pred": [0.5, 0.7]})
        assert out.schema.field_names == ["id", "f2", "pred"]
        assert out.to_rows() == [(1, 3.0, 0.5), (4, 6.0, 0.7)]

    def test_missing_output_col_raises(self):
        t = Table.from_rows([(1, 2.0, 3.0)], _schema())
        helper = OutputColsHelper(t.schema, ["pred"], [DataTypes.DOUBLE])
        with pytest.raises(ValueError, match="did not produce"):
            helper.get_result_table(t, {"other": [1.0]})


class TestTableUtil:
    def test_temp_table_name_unique(self):
        assert table_util.get_temp_table_name() != table_util.get_temp_table_name()

    def test_find_col_index_null_raises(self):
        with pytest.raises(ValueError):
            table_util.find_col_index(["a"], None)
        assert table_util.find_col_index(["a", "B"], "b") == 1

    def test_assertions(self):
        s = Schema(["num", "txt", "vec"], [DataTypes.DOUBLE, DataTypes.STRING, DataTypes.VECTOR])
        table_util.assert_selected_col_exist(s.field_names, "num")
        with pytest.raises(ValueError):
            table_util.assert_selected_col_exist(s.field_names, "zz")
        table_util.assert_numerical_cols(s, "num")
        with pytest.raises(ValueError):
            table_util.assert_numerical_cols(s, "txt")
        table_util.assert_string_cols(s, "txt")
        with pytest.raises(ValueError):
            table_util.assert_string_cols(s, "vec")
        table_util.assert_vector_cols(s, "vec")
        with pytest.raises(ValueError):
            table_util.assert_vector_cols(s, "num")

    def test_typed_col_selection(self):
        s = Schema(["a", "b", "c"], [DataTypes.DOUBLE, DataTypes.STRING, DataTypes.INT])
        assert table_util.get_numeric_cols(s) == ["a", "c"]
        assert table_util.get_numeric_cols(s, exclude_cols=["A"]) == ["c"]
        assert table_util.get_string_cols(s) == ["b"]
        assert table_util.get_categorical_cols(s, ["a", "b"], None) == ["b"]
        assert table_util.get_categorical_cols(s, ["a", "b"], ["a"]) == ["a", "b"]
        with pytest.raises(ValueError, match="featureCols"):
            table_util.get_categorical_cols(s, ["a"], ["c"])

    def test_format_markdown(self):
        t = Table.from_rows([(1, 2.0, None)], Schema(["x", "y", "z"],
                            [DataTypes.INT, DataTypes.DOUBLE, DataTypes.STRING]))
        text = table_util.format(t)
        assert text.splitlines()[0] == "|x|y|z|"
        assert "null" in text.splitlines()[2]


class TestSources:
    def test_collection_source(self):
        src = CollectionSource([(1, 2.0, 3.0)], _schema())
        assert src.read().num_rows() == 1

    def test_csv_source(self, tmp_path):
        p = tmp_path / "data.csv"
        p.write_text("id,f1,vec\n1,2.5,1 2 3\n2,,0:1 4:5\n")
        s = Schema(["id", "f1", "vec"], [DataTypes.INT, DataTypes.DOUBLE, DataTypes.VECTOR])
        t = CsvSource(str(p), s, skip_header=True).read()
        assert t.num_rows() == 2
        assert t.col("id").tolist() == [1, 2]
        assert np.isnan(t.col("f1")[1])
        assert isinstance(t.col("vec")[0], DenseVector)
        assert isinstance(t.col("vec")[1], SparseVector)

    def test_csv_arity_error(self, tmp_path):
        p = tmp_path / "bad.csv"
        p.write_text("1,2\n")
        with pytest.raises(ValueError, match="fields"):
            CsvSource(str(p), _schema()).read()

    def test_libsvm_source(self, tmp_path):
        p = tmp_path / "d.svm"
        p.write_text("1 1:0.5 3:1.5  # comment\n-1 2:2.0\n\n")
        t = LibSvmSource(str(p)).read()
        assert t.col("label").tolist() == [1.0, -1.0]
        v0 = t.col("features")[0]
        assert v0.indices.tolist() == [0, 2] and v0.vals.tolist() == [0.5, 1.5]
        assert v0.size() == 3

    def test_generator_source_linear_timestamps(self):
        s = Schema(["v"], [DataTypes.INT])
        src = GeneratorSource.linear_timestamps([(1,), (2,), (3,)], 10, s)
        events = list(src.stream())
        assert events == [(0, (1,)), (10, (2,)), (20, (3,))]
        # re-iterable
        assert len(list(src.stream())) == 3


def test_output_cols_case_insensitive_override():
    """Regression: output col differing only in case overrides the input col
    in place instead of silently shadowing behind it."""
    from flink_ml_tpu.table.output_cols import OutputColsHelper

    schema = Schema.of(("f0", "double"), ("sum", "double"))
    t = Table.from_columns(schema, {"f0": [1.0, 2.0], "sum": [5.0, 6.0]})
    helper = OutputColsHelper(schema, ["Sum"], ["double"])
    assert helper.get_result_schema().field_names == ["f0", "Sum"]
    out = helper.get_result_table(t, {"Sum": np.asarray([100.0, 200.0])})
    np.testing.assert_allclose(out.col("sum"), [100.0, 200.0])
    np.testing.assert_allclose(out.col("Sum"), [100.0, 200.0])


def test_output_cols_reserved_case_insensitive():
    """Reserved names match case-insensitively like all other column lookup."""
    from flink_ml_tpu.table.output_cols import OutputColsHelper

    schema = Schema.of(("f0", "double"), ("label", "double"))
    helper = OutputColsHelper(schema, ["out"], ["double"], reserved_col_names=["Label"])
    assert helper.get_result_schema().field_names == ["label", "out"]


def test_tracing_helpers():
    from flink_ml_tpu.utils.tracing import annotate, timed

    calls = []
    with timed("phase", sink=lambda l, s: calls.append((l, s))):
        with annotate("step"):
            pass
    assert calls and calls[0][0] == "phase" and calls[0][1] >= 0


class TestMatrixBackedColumn:
    """Matrix-backed dense-vector columns: the million-row fast path — a 2D
    float array stored directly instead of rows of DenseVector objects."""

    def _table(self):
        X = np.arange(12, dtype=np.float32).reshape(4, 3)
        schema = Schema.of(("features", DataTypes.DENSE_VECTOR), ("label", "double"))
        return X, Table.from_columns(
            schema, {"features": X, "label": [0.0, 1.0, 0.0, 1.0]}
        )

    def test_features_dense_zero_copy(self):
        X, t = self._table()
        out = t.features_dense("features")
        assert out is X  # no conversion, no copy

    def test_features_dense_dim_pad(self):
        X, t = self._table()
        out = t.features_dense("features", dim=5)
        assert out.shape == (4, 5)
        np.testing.assert_allclose(out[:, :3], X)
        np.testing.assert_allclose(out[:, 3:], 0.0)

    def test_to_rows_wraps_dense_vectors(self):
        from flink_ml_tpu.ops.vector import DenseVector

        X, t = self._table()
        rows = t.to_rows()
        assert isinstance(rows[0][0], DenseVector)
        np.testing.assert_allclose(rows[2][0].values, X[2])
        assert rows[2][1] == 0.0

    def test_row_ops_slice_filter(self):
        X, t = self._table()
        sub = t.slice_rows(1, 3)
        np.testing.assert_allclose(sub.features_dense("features"), X[1:3])
        f = t.filter_rows(np.asarray([True, False, True, False]))
        np.testing.assert_allclose(f.features_dense("features"), X[[0, 2]])

    def test_train_matches_object_column(self):
        """A GLM fit over a matrix-backed column bit-matches the same fit
        over the equivalent DenseVector-object column."""
        from flink_ml_tpu.lib import LogisticRegression
        from flink_ml_tpu.ops.vector import DenseVector

        rng = np.random.RandomState(0)
        X = rng.randn(64, 5).astype(np.float64)
        y = (X @ rng.randn(5) > 0).astype(np.float64)
        schema = Schema.of(("features", DataTypes.DENSE_VECTOR), ("label", "double"))
        t_mat = Table.from_columns(schema, {"features": X, "label": y})
        t_obj = Table.from_columns(
            schema, {"features": [DenseVector(r) for r in X], "label": y}
        )

        def fit(t):
            m = (LogisticRegression().set_vector_col("features")
                 .set_label_col("label").set_prediction_col("p")
                 .set_learning_rate(0.5).set_max_iter(5).fit(t))
            return m.coefficients(), m.intercept()

        w1, b1 = fit(t_mat)
        w2, b2 = fit(t_obj)
        np.testing.assert_array_equal(w1, w2)
        assert b1 == b2


class TestPackCacheBounds:
    def test_lru_eviction(self):
        from flink_ml_tpu.table import table as table_mod

        schema = Schema.of(("x", "double"))
        t = Table.from_columns(schema, {"x": [1.0]})
        cap = table_mod._PACK_CACHE_CAPACITY
        builds = []
        for i in range(cap + 2):
            t.cached_pack(("k", i), lambda i=i: builds.append(i) or i)
        assert len(t._pack_cache) == cap
        # oldest entries evicted; re-requesting rebuilds
        t.cached_pack(("k", 0), lambda: builds.append("rebuild") or 0)
        assert "rebuild" in builds

    def test_hit_returns_same_object(self):
        schema = Schema.of(("x", "double"))
        t = Table.from_columns(schema, {"x": [1.0]})
        a = t.cached_pack("a", lambda: object())
        assert t.cached_pack("a", lambda: object()) is a

def test_features_dense_narrower_dim_raises():
    X, t = TestMatrixBackedColumn()._table()
    with pytest.raises(ValueError):
        t.features_dense("features", dim=2)


def test_concat_mixed_layouts():
    from flink_ml_tpu.ops.vector import DenseVector

    X, t_mat = TestMatrixBackedColumn()._table()
    schema = t_mat.schema
    t_obj = Table.from_rows([(DenseVector([9.0, 9.0, 9.0]), 5.0)], schema)
    out = Table.concat([t_mat, t_obj])
    assert out.num_rows() == 5
    np.testing.assert_allclose(out.features_dense("features")[:4], X)
    np.testing.assert_allclose(out.features_dense("features")[4], [9.0, 9.0, 9.0])


class TestCsrRowsColumn:
    """CSR-backed sparse columns: the contiguous-array counterpart of the
    matrix-backed dense column (native streaming feeds these)."""

    def _rows(self, n=20, dim=30, seed=0):
        from flink_ml_tpu.ops.batch import CsrRows
        from flink_ml_tpu.ops.vector import SparseVector

        rng = np.random.RandomState(seed)
        vecs = []
        for _ in range(n):
            k = rng.randint(0, 5)
            idx = np.sort(rng.choice(dim, k, replace=False))
            vecs.append(SparseVector(dim, idx, rng.randn(k)))
        return CsrRows.from_vectors(vecs, dim=dim), vecs

    def test_round_trip_and_indexing(self):
        rows, vecs = self._rows()
        assert len(rows) == len(vecs)
        for i in (0, 5, len(vecs) - 1, -1):
            got, want = rows[i], vecs[i]
            np.testing.assert_array_equal(got.indices, want.indices)
            np.testing.assert_array_equal(got.vals, want.vals)
        sub = rows[3:11]
        assert len(sub) == 8
        np.testing.assert_array_equal(sub[0].indices, vecs[3].indices)
        gathered = rows[np.array([7, 2, 19])]
        np.testing.assert_array_equal(gathered[1].vals, vecs[2].vals)
        masked = rows[np.arange(len(rows)) % 2 == 0]
        assert len(masked) == 10

    def test_concat(self):
        from flink_ml_tpu.ops.batch import CsrRows

        a, va = self._rows(seed=1)
        b, vb = self._rows(seed=2)
        cat = CsrRows.concat([a, b])
        assert len(cat) == len(va) + len(vb)
        np.testing.assert_array_equal(cat[len(va)].vals, vb[0].vals)

    def test_table_ops_on_csr_column(self):
        from flink_ml_tpu.ops.batch import CsrRows

        rows, vecs = self._rows()
        schema = Schema.of(("features", DataTypes.SPARSE_VECTOR), ("y", "double"))
        t = Table.from_columns(
            schema, {"features": rows, "y": np.arange(float(len(rows)))}
        )
        assert isinstance(t.col("features"), CsrRows)
        sliced = t.slice_rows(2, 6)
        assert sliced.num_rows() == 4
        np.testing.assert_array_equal(
            sliced.to_rows()[0][0].indices, vecs[2].indices
        )
        both = Table.concat([t, t])
        assert isinstance(both.col("features"), CsrRows)
        assert both.num_rows() == 2 * len(rows)
        csr = t.features_csr("features", n_cols=30)
        assert csr.n_rows == len(rows)

    def test_pack_paths_bit_identical(self):
        """The vectorized CSR packer must produce byte-identical minibatch
        stacks to the per-row SparseVector packer."""
        from flink_ml_tpu.lib.common import pack_sparse_minibatches

        rows, vecs = self._rows(n=533, dim=100, seed=3)
        y = np.random.RandomState(4).randn(533)
        for n_dev, gbs in ((1, 64), (4, 128), (8, 0)):
            a = pack_sparse_minibatches(vecs, y, n_dev, gbs, dim=100)
            b = pack_sparse_minibatches(rows, y, n_dev, gbs, dim=100)
            assert (a.steps, a.mb, a.nnz_pad, a.dim, a.n_rows) == (
                b.steps, b.mb, b.nnz_pad, b.dim, b.n_rows
            )
            np.testing.assert_array_equal(a.ints, b.ints)
            np.testing.assert_array_equal(a.floats, b.floats)

    def test_pack_csr_validates_range(self):
        from flink_ml_tpu.lib.common import pack_sparse_minibatches

        rows, _ = self._rows(n=10, dim=30)
        with pytest.raises(ValueError, match="out of range"):
            pack_sparse_minibatches(rows, np.zeros(10), 1, 4, dim=3)

    def test_features_dense_on_csr_column(self):
        rows, vecs = self._rows(n=15, dim=30)
        schema = Schema.of(("features", DataTypes.SPARSE_VECTOR))
        t = Table.from_columns(schema, {"features": rows})
        dense = t.features_dense("features")
        assert dense.shape == (15, 30)
        for i, v in enumerate(vecs):
            np.testing.assert_array_equal(dense[i], v.to_dense().values)
        wider = t.features_dense("features", dim=40)
        assert wider.shape == (15, 40)
        np.testing.assert_array_equal(wider[:, :30], dense)
        with pytest.raises(ValueError, match="out of range"):
            t.features_dense("features", dim=5)

    def test_csr_densify_sums_duplicates_and_rejects_negatives(self):
        from flink_ml_tpu.ops.batch import CsrRows

        dup = CsrRows(10, [0, 3], [2, 2, 5], [1.0, 2.5, -1.0])
        dense = dup.to_dense()
        assert dense[0, 2] == 3.5 and dense[0, 5] == -1.0
        neg = CsrRows(10, [0, 1], [-1], [1.0])
        with pytest.raises(ValueError, match="out of range"):
            neg.to_dense()
