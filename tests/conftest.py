"""Test harness config.

Runs the whole suite on a virtual 8-device CPU mesh so psum/shard_map tests
exercise real collectives without TPU hardware — the analog of the reference
running parallel subtasks in Flink's in-JVM mini-cluster (SURVEY.md §4).

Note: this environment pre-imports jax at interpreter startup (sitecustomize)
and forces the platform list programmatically, so env vars alone are not
enough — the jax config must be updated before the first backend use.
"""

import os
import tempfile

# the persistent compilation cache is a production warm-start feature; in
# tests it only adds disk churn and cross-process atime races (and the
# suite's programs are tiny), so keep it off unless a test opts in
os.environ.setdefault("FLINK_ML_TPU_COMPILE_CACHE", "off")

# flight-recorder dumps (breaker-open tests fire them) and trace sinks go
# to a throwaway dir, not the committed reports/ — a test run must leave
# the repo clean
os.environ.setdefault("FMT_FLIGHT_DIR",
                      tempfile.mkdtemp(prefix="fmt_test_flight_"))
os.environ.setdefault("FMT_TRACE_DIR",
                      tempfile.mkdtemp(prefix="fmt_test_traces_"))

#: FMT_TEST_TPU=1 runs the suite on the real TPU backend instead of the
#: virtual CPU mesh — the only way to exercise the Mosaic-lowered (non-
#: interpret) Pallas tests, which are skipped on CPU.
_ON_TPU = os.environ.get("FMT_TEST_TPU", "").lower() in ("1", "true", "yes")

os.environ.setdefault("JAX_ENABLE_X64", "0" if _ON_TPU else "1")
_flags = os.environ.get("XLA_FLAGS", "")
if not _ON_TPU and "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

if not _ON_TPU:
    jax.config.update("jax_platforms", "cpu")
jax.config.update(
    "jax_enable_x64",
    os.environ["JAX_ENABLE_X64"].lower() not in ("0", "false", "f", "no", "off"),
)

if not _ON_TPU:
    assert jax.device_count() == 8, (
        f"expected 8 virtual CPU devices, got {jax.device_count()} on "
        f"{jax.default_backend()}; backend was initialized before conftest"
    )
