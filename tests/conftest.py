"""Test harness config.

Runs the whole suite on a virtual 8-device CPU mesh so psum/shard_map tests
exercise real collectives without TPU hardware — the analog of the reference
running parallel subtasks in Flink's in-JVM mini-cluster (SURVEY.md §4).

Note: this environment pre-imports jax at interpreter startup (sitecustomize)
and forces the platform list programmatically, so env vars alone are not
enough — the jax config must be updated before the first backend use.
"""

import os

os.environ.setdefault("JAX_ENABLE_X64", "1")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update(
    "jax_enable_x64",
    os.environ["JAX_ENABLE_X64"].lower() not in ("0", "false", "f", "no", "off"),
)

assert jax.device_count() == 8, (
    f"expected 8 virtual CPU devices, got {jax.device_count()} on "
    f"{jax.default_backend()}; backend was initialized before conftest"
)
