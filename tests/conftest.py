"""Test harness config.

Runs the whole suite on a virtual 8-device CPU mesh so psum/shard_map tests
exercise real collectives without TPU hardware — the analog of the reference
running parallel subtasks in Flink's in-JVM mini-cluster (SURVEY.md §4).
Must set env vars before jax is imported anywhere.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "1")
