"""Fleet-wide distributed tracing (ISSUE 16).

The contracts under test:

* **context propagation** — the router ships (trace_id, parent_span_id)
  over the replica wire, the replica ``adopt``s it, and one routed
  request renders as ONE trace whose spans come from >= 2 processes with
  correct parent/child nesting (the acceptance criterion, tested against
  a REAL router + replica subprocess);
* **cost attribution** — every record carries a phase class and a pid;
  compile-bearing dispatches land in the persistent per-rung ledger;
* **fleet stitching** — per-pid ``traces-<pid>.jsonl`` sinks merge by
  trace id, clock-offset corrected and causally clamped, and a kill -9'd
  replica's torn final line never breaks the merge;
* **tail sampling** — ``FMT_TRACE_TAIL`` persists only anomalous traces
  (the disabled path stays one module-bool check);
* **rotation** — the sink rotates at ``FMT_TRACE_MAX_MB`` with the
  reports-style commit sidecar, and ``load_spans`` reads both
  generations.
"""

import json
import os
import time

import numpy as np
import pytest

from flink_ml_tpu.api.pipeline import Pipeline
from flink_ml_tpu.common import fused
from flink_ml_tpu.lib import LogisticRegression
from flink_ml_tpu.lib.feature import StandardScaler
from flink_ml_tpu.obs import flight, telemetry, trace
from flink_ml_tpu.serve import integrity
from flink_ml_tpu.serving import (
    ReplicaRouter,
    ServerOverloadedError,
)
from flink_ml_tpu.serving.batcher import ServeResult
from flink_ml_tpu.table.schema import DataTypes, Schema
from flink_ml_tpu.table.table import Table

N, D = 192, 5
SCHEMA = Schema.of(("features", DataTypes.DENSE_VECTOR), ("label", "double"))
WAIT = 120  # generous future timeout: a hang fails loudly, not flakily


@pytest.fixture(scope="module")
def dense_table():
    rng = np.random.RandomState(23)
    X = (2.0 * rng.randn(N, D) + 1.0).astype(np.float32)
    w = rng.randn(D).astype(np.float32)
    y = ((X - 1.0) @ w > 0).astype(np.float64)
    return Table.from_columns(SCHEMA, {"features": X, "label": y})


@pytest.fixture(scope="module")
def saved(tmp_path_factory, dense_table):
    """One fitted+saved pipeline the real-subprocess fleet serves."""
    root = tmp_path_factory.mktemp("fleet_trace_models")
    model = Pipeline([
        StandardScaler().set_selected_col("features"),
        LogisticRegression().set_vector_col("features")
        .set_label_col("label").set_prediction_col("pred")
        .set_learning_rate(0.5).set_max_iter(3),
    ]).fit(dense_table)
    path = str(root / "v1")
    model.save(path)
    return {"path": path, "model": model}


@pytest.fixture
def traced(tmp_path, monkeypatch):
    """Tracing on at sample=1.0, spans to a per-test sink; clean exit."""
    monkeypatch.setenv("FMT_TRACE_DIR", str(tmp_path))
    trace.reset()
    trace.enable(True, sample=1.0)
    yield tmp_path
    trace.enable(False, sample=1.0)
    trace.set_tail("")
    trace.reset()


def _spans(trace_id=None):
    spans = trace.recent_spans()
    if trace_id is None:
        return spans
    return [s for s in spans if s["trace_id"] == trace_id]


# -- adopt: the cross-process handoff -----------------------------------------


class TestAdopt:
    def test_disabled_or_empty_is_shared_nullcontext(self):
        assert not trace.enabled()
        assert trace.adopt("cafe", "beef") is trace.adopt("", "")
        assert trace.adopt(None) is trace.span("x")

    def test_span_under_adopt_lands_in_remote_trace(self, traced):
        with trace.adopt("cafe01", "beef02"):
            with trace.span("work", {"k": 1}):
                pass
        (rec,) = _spans("cafe01")
        assert rec["parent_id"] == "beef02"
        assert rec["name"] == "work"

    def test_start_request_joins_adopted_context(self, traced):
        with trace.adopt("cafe01", "beef02"):
            rt = trace.start_request("serving.request", {"rows": 3})
            assert rt is not None
            assert rt.trace_id == "cafe01"
            trace.record_span((rt.ctx,), "queue_wait", 0.01)
            rt.end(status="ok")
        recs = {s["name"]: s for s in _spans("cafe01")}
        # the joined root parents under the REMOTE span, not ""
        assert recs["serving.request"]["parent_id"] == "beef02"
        assert recs["queue_wait"]["parent_id"] == rt.ctx.span_id

    def test_joined_root_skips_the_sampling_coin_flip(self, traced):
        trace.enable(True, sample=0.0)
        assert trace.start_request("r") is None  # true mint: sampled out
        with trace.adopt("cafe01", "beef02"):
            # adopted context IS the remote sampled-in verdict
            assert trace.start_request("r") is not None

    def test_joined_root_end_flushes_the_sink(self, traced):
        """An adopted request never records a parentless line, so the
        BOUNDARY flag (not parent-lessness) must trigger the flush."""
        with trace.adopt("cafe01", "beef02"):
            rt = trace.start_request("serving.request")
            rt.end()
        spans = trace.load_spans(str(traced))
        assert [s["name"] for s in spans] == ["serving.request"]


# -- phase + pid attribution --------------------------------------------------


class TestPhases:
    def test_known_span_names_classify(self):
        assert trace.phase_of("queue_wait") == "queue"
        assert trace.phase_of("coalesce") == "coalesce"
        assert trace.phase_of("place_h2d") == "h2d"
        assert trace.phase_of("fused_dispatch") == "compute"
        assert trace.phase_of("device_sync") == "compute"
        assert trace.phase_of("demux") == "demux"
        assert trace.phase_of("compile") == "compile"
        assert trace.phase_of("router.dispatch") == "net"
        assert trace.phase_of("router.request") == "queue"
        assert trace.phase_of("something_else") == "compute"

    def test_records_carry_phase_and_pid(self, traced):
        with trace.root_span("fit"):
            with trace.span("place_h2d"):
                pass
        by_name = {s["name"]: s for s in _spans()}
        assert by_name["place_h2d"]["phase"] == "h2d"
        assert by_name["fit"]["pid"] == os.getpid()

    def test_phase_totals_use_self_time(self):
        spans = [
            {"trace_id": "t", "span_id": "a", "parent_id": "", "name": "r",
             "ts": 0.0, "dur_s": 1.0, "phase": "queue"},
            {"trace_id": "t", "span_id": "b", "parent_id": "a",
             "name": "transform", "ts": 0.1, "dur_s": 0.8,
             "phase": "compute"},
        ]
        totals = trace.phase_totals(spans, "t")
        assert totals["queue"] == pytest.approx(0.2)
        assert totals["compute"] == pytest.approx(0.8)


# -- tail sampling ------------------------------------------------------------


class TestTailSampling:
    def test_fast_ok_trace_is_dropped_slow_kept(self, traced, monkeypatch):
        monkeypatch.setenv("FMT_TRACE_SLOW_MS", "40")
        trace.set_tail("slow")
        fast = trace.start_request("serving.request")
        with trace.use((fast.ctx,)):
            with trace.span("coalesce"):
                pass
        fast.end()
        slow = trace.start_request("serving.request")
        time.sleep(0.06)
        slow.end()
        trace.flush()
        kept = trace.trace_ids(trace.load_spans(str(traced)))
        assert kept == [slow.trace_id]
        # the dropped trace still reached the in-memory ring (debugging)
        assert fast.trace_id in {s["trace_id"] for s in _spans()}
        assert trace.sink_status()["tail_dropped"] >= 1

    def test_error_and_shed_modes(self, traced):
        trace.set_tail("error,shed")
        ok = trace.start_request("r")
        ok.end(status="ok")
        err = trace.start_request("r")
        err.end(status="error")
        shed = trace.start_request("r")
        shed.end(status="shed")
        kept = set(trace.trace_ids(trace.load_spans(str(traced))))
        assert kept == {err.trace_id, shed.trace_id}

    def test_kept_trace_keeps_its_children_too(self, traced):
        trace.set_tail("error")
        rt = trace.start_request("r")
        with trace.use((rt.ctx,)):
            with trace.span("transform"):
                pass
        rt.end(status="error")
        names = {s["name"] for s in trace.load_spans(str(traced))}
        assert names == {"r", "transform"}

    def test_disabled_hot_path_unchanged(self):
        """Tail sampling must not touch the FMT_TRACE=0 contract."""
        assert not trace.enabled()
        assert trace.span("x") is trace.span("y")
        assert trace.start_request("r") is None


# -- rotation + commit sidecar ------------------------------------------------


class TestRotation:
    def test_sink_rotates_with_commit_sidecar(self, traced, monkeypatch):
        monkeypatch.setenv("FMT_TRACE_MAX_MB", "0.001")  # ~1 KiB
        written = 0
        while trace.sink_status()["rotations"] == 0 and written < 64:
            with trace.root_span("fit", {"pad": "x" * 64}):
                pass
            written += 1
        assert trace.sink_status()["rotations"] == 1
        with trace.root_span("fit", {"pad": "x" * 64}):
            pass  # one span in the fresh post-rotation sink
        trace.flush()
        rotated = trace.traces_path() + ".1"
        assert os.path.exists(rotated)
        assert integrity.verify_commit_record(rotated, required=True)
        # one rotation deep: both generations merge on read
        assert len(trace.load_spans(str(traced))) == written + 1

    def test_default_cap_does_not_rotate_tiny_sinks(self, traced):
        with trace.root_span("fit"):
            pass
        trace.flush()
        assert not os.path.exists(trace.traces_path() + ".1")


# -- fleet stitching ----------------------------------------------------------


def _write_sink(directory, pid, records, torn_tail=False):
    path = os.path.join(str(directory), f"traces-{pid}.jsonl")
    with open(path, "w") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")
        if torn_tail:
            f.write('{"trace_id": "t1", "span_id": "to')  # kill -9 mid-write
    return path


class TestStitching:
    def _fleet(self, directory):
        root = {"trace_id": "t1", "span_id": "r", "parent_id": "",
                "name": "router.request", "ts": 10.0, "dur_s": 0.5,
                "status": "ok", "phase": "queue", "pid": 100, "attrs": {}}
        disp = {"trace_id": "t1", "span_id": "d", "parent_id": "r",
                "name": "router.dispatch", "ts": 10.1, "dur_s": 0.3,
                "status": "ok", "phase": "net", "pid": 100, "attrs": {}}
        # the replica's clock runs 2 s ahead: uncorrected, its spans
        # would render far outside the router's window
        serve = {"trace_id": "t1", "span_id": "s", "parent_id": "d",
                 "name": "serving.request", "ts": 12.15, "dur_s": 0.2,
                 "status": "ok", "phase": "queue", "pid": 200, "attrs": {}}
        _write_sink(directory, 100, [root, disp])
        _write_sink(directory, 200, [serve], torn_tail=True)
        return root, disp, serve

    def test_torn_partial_file_still_stitches(self, tmp_path):
        self._fleet(tmp_path)
        spans = trace.load_spans(str(tmp_path))
        assert len(spans) == 3  # the torn line is skipped, not fatal
        out = trace.render_waterfall(spans, "t1")
        assert "serving.request" in out and "2 process(es)" in out
        assert "@100" in out and "@200" in out

    def test_clock_offset_correction_and_causal_clamp(self, tmp_path,
                                                      monkeypatch):
        monkeypatch.setenv("FMT_TRACE_DIR", str(tmp_path))
        self._fleet(tmp_path)
        trace.note_clock_offset(200, 2.0, 0.004)
        trace.note_clock_offset(200, 5.0, 0.5)  # worse RTT: ignored
        offsets = trace.load_clock_offsets(str(tmp_path))
        assert offsets == {200: 2.0}
        stitched = trace.stitch(trace.load_spans(str(tmp_path)), offsets)
        by_id = {s["span_id"]: s for s in stitched}
        assert by_id["s"]["ts"] == pytest.approx(10.15)
        # children never render before their cause, even if the offset
        # estimate overshoots
        assert by_id["s"]["ts"] >= by_id["d"]["ts"]

    def test_fleet_cli_renders_and_rolls_up(self, tmp_path, capsys):
        self._fleet(tmp_path)
        assert trace.fleet_main(["--traces", str(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "2 process(es)" in out
        assert "phase self-time:" in out
        assert trace.fleet_main(["--traces", str(tmp_path), "--list"]) == 0
        assert "processes=2" in capsys.readouterr().out

    def test_fleet_cli_empty_dir(self, tmp_path, capsys):
        assert trace.fleet_main(["--traces", str(tmp_path)]) == 1


# -- the compile ledger -------------------------------------------------------


class TestCompileLedger:
    def test_note_compile_writes_ledger_and_span(self, traced, tmp_path,
                                                 monkeypatch):
        monkeypatch.setenv("FMT_OBS_REPORTS", str(tmp_path / "reports"))
        with trace.root_span("fit"):
            trace.note_compile("lr_serve", 32, 8, "float32", 1.25)
            trace.note_compile("lr_serve", 32, 8, "float32", 9.0)  # dup
        by_name = {s["name"]: s for s in _spans()}
        assert by_name["compile"]["phase"] == "compile"
        assert by_name["compile"]["attrs"]["bucket"] == 32
        with open(trace.compile_ledger_path()) as f:
            entries = [json.loads(line) for line in f]
        assert len(entries) == 1  # keyed: one line per rung, not per call
        assert entries[0]["kernel"] == "lr_serve"
        assert entries[0]["mesh"] == 8
        assert entries[0]["dur_s"] == pytest.approx(1.25)

    def test_fused_serve_ledgers_its_first_dispatch(self, traced, tmp_path,
                                                    monkeypatch, saved,
                                                    dense_table):
        monkeypatch.setenv("FMT_OBS_REPORTS", str(tmp_path / "reports"))
        fused.reset_compile_keys()
        with trace.root_span("transform"):
            saved["model"].transform(dense_table.slice_rows(0, 16))
        compiles = [s for s in _spans() if s["name"] == "compile"]
        assert compiles, "first fused dispatch must record a compile span"
        assert os.path.exists(trace.compile_ledger_path())


# -- router spans against scripted fakes --------------------------------------


class _FakeClient:
    """Scripted ReplicaClient speaking the traced wire: ``script``
    entries are consumed per submit — an exception instance raises,
    anything else echoes the request back as a served result."""

    def __init__(self, name, script=()):
        self.name = name
        self.script = list(script)
        self.submits = 0
        self.trace_ctxs = []

    def submit(self, table, deadline_ms=None, timeout_s=120.0,
               trace_ctx=None):
        self.submits += 1
        self.trace_ctxs.append(trace_ctx)
        if self.script:
            step = self.script.pop(0)
            if isinstance(step, BaseException):
                raise step
        return ServeResult(table=table, quarantine={}, version="v1")

    def deploy(self, path, version, timeout_s=600.0):
        return version

    def probe(self, timeout_s=2.0, depth=True):
        out = {"ready": True, "reasons": []}
        if depth:
            out["queue_depth"] = 0.0
        return out


def _fake_router(clients, **kw):
    table = {f"replica-{i}-g{i + 1}": c for i, c in enumerate(clients)}

    def factory(name, path, version):
        return table[name], None

    kw.setdefault("poll_ms", 600_000.0)
    return ReplicaRouter("/nonexistent", replicas=len(clients),
                         replica_factory=factory, **kw)


class TestRouterSpans:
    def test_served_request_has_root_dispatch_and_wire_ctx(self, traced,
                                                           dense_table):
        a = _FakeClient("a")
        router = _fake_router([a])
        try:
            res = router.predict(dense_table.slice_rows(0, 4), timeout=WAIT)
        finally:
            router.shutdown()
        # satellite 1: the SUCCESS response surfaces the trace id
        assert res.trace_id is not None
        recs = {s["name"]: s for s in _spans(res.trace_id)}
        assert recs["router.request"]["parent_id"] == ""
        assert recs["router.request"]["status"] == "ok"
        root_id = recs["router.request"]["span_id"]
        assert recs["queue_wait"]["parent_id"] == root_id
        assert recs["submit"]["parent_id"] == root_id
        assert recs["router.dispatch"]["parent_id"] == root_id
        # the wire context the replica would adopt IS the dispatch span
        (ctx,) = a.trace_ctxs
        assert ctx == (res.trace_id, recs["router.dispatch"]["span_id"])

    def test_retries_are_sibling_spans_under_one_root(self, traced,
                                                      dense_table):
        a = _FakeClient("a", script=[ServerOverloadedError("queue_full")])
        b = _FakeClient("b", script=[ServerOverloadedError("queue_full")])
        router = _fake_router([a, b])
        try:
            res = router.predict(dense_table.slice_rows(0, 4), timeout=WAIT)
        finally:
            router.shutdown()
        dispatches = [s for s in _spans(res.trace_id)
                      if s["name"] == "router.dispatch"]
        assert len(dispatches) >= 2
        assert len({s["parent_id"] for s in dispatches}) == 1  # siblings
        statuses = [s["status"] for s in dispatches]
        assert statuses.count("shed") >= 1 and statuses[-1] == "ok"
        attempts = [s["attrs"]["attempt"] for s in dispatches]
        assert attempts == sorted(attempts)

    def test_failed_request_ends_root_with_status(self, traced,
                                                  dense_table):
        a = _FakeClient("a", script=[ServerOverloadedError("breaker_open"),
                                     ServerOverloadedError("breaker_open")])
        router = _fake_router([a], retries=0)
        try:
            with pytest.raises(ServerOverloadedError):
                router.predict(dense_table.slice_rows(0, 4), timeout=WAIT)
        finally:
            router.shutdown()
        roots = [s for s in _spans() if s["name"] == "router.request"]
        assert roots and roots[-1]["status"] == "shed"

    def test_untraced_requests_pass_no_wire_ctx(self, dense_table):
        assert not trace.enabled()
        a = _FakeClient("a")
        router = _fake_router([a])
        try:
            res = router.predict(dense_table.slice_rows(0, 4), timeout=WAIT)
        finally:
            router.shutdown()
        assert res.trace_id is None
        assert a.trace_ctxs == [None]


# -- status + flight ----------------------------------------------------------


class TestStatusSurfaces:
    def test_statusz_has_trace_section(self, traced):
        snap = telemetry.status_snapshot()
        assert snap["trace"]["enabled"] is True
        assert snap["trace"]["sample"] == 1.0

    def test_flight_events_carry_pid(self):
        flight.reset()
        flight.record("router.retry", replica="r0", why="test")
        (event,) = [e for e in flight.events()
                    if e["kind"] == "router.retry"]
        assert event["pid"] == os.getpid()
        flight.reset()


# -- the acceptance criterion: a REAL router -> replica waterfall -------------


class TestFleetEndToEnd:
    def test_routed_request_stitches_across_processes(self, traced, saved,
                                                      dense_table, capsys):
        router = ReplicaRouter(saved["path"], version="v1", replicas=1,
                               poll_ms=50, spawn_timeout_s=120)
        try:
            res = router.predict(dense_table.slice_rows(0, 8), timeout=WAIT)
        finally:
            router.shutdown()
        assert res.num_rows == 8
        assert res.trace_id is not None
        trace.flush()
        spans = trace.load_spans(str(traced))
        mine = [s for s in spans if s["trace_id"] == res.trace_id]
        pids = {s["pid"] for s in mine}
        assert len(pids) >= 2, f"spans from one process only: {pids}"
        by_name = {}
        for s in mine:
            by_name.setdefault(s["name"], s)
        # nesting across the process boundary: router.request ->
        # router.dispatch -> serving.request -> ... -> fused_dispatch
        root = by_name["router.request"]
        assert root["parent_id"] == "" and root["pid"] == os.getpid()
        disp = by_name["router.dispatch"]
        assert disp["parent_id"] == root["span_id"]
        serve = by_name["serving.request"]
        assert serve["parent_id"] == disp["span_id"]
        assert serve["pid"] != os.getpid()
        assert "fused_dispatch" in by_name
        # the router probed the replica's clock on spawn
        offsets = trace.load_clock_offsets(str(traced))
        assert serve["pid"] in offsets
        # and the fleet CLI renders it as ONE stitched waterfall
        assert trace.fleet_main(
            ["--traces", str(traced), res.trace_id]) == 0
        out = capsys.readouterr().out
        assert "2 process(es)" in out
        assert "serving.request" in out
        assert "phase self-time:" in out
