"""KMeans + Knn tests: cluster recovery, assignment correctness, kNN accuracy
vs a numpy brute-force reference, save/load round-trips."""

import os

import numpy as np
import pytest

from flink_ml_tpu.api.core import load_stage
from flink_ml_tpu.lib.clustering import KMeans, KMeansModel, kmeans_plus_plus
from flink_ml_tpu.lib.knn import Knn, KnnModel
from flink_ml_tpu.ops.vector import DenseVector
from flink_ml_tpu.table.schema import DataTypes, Schema
from flink_ml_tpu.table.table import Table


def blob_data(n_per=60, seed=0):
    rng = np.random.RandomState(seed)
    centers = np.array([[0.0, 0.0], [8.0, 0.0], [0.0, 8.0]])
    X = np.concatenate(
        [c + 0.4 * rng.randn(n_per, 2) for c in centers]
    )
    labels = np.repeat(np.arange(3), n_per).astype(np.float64)
    vectors = [DenseVector(row) for row in X]
    schema = Schema.of(("features", DataTypes.DENSE_VECTOR), ("label", "double"))
    t = Table.from_columns(schema, {"features": vectors, "label": labels})
    return t, X, labels, centers


class TestKMeans:
    def test_recovers_blob_centers(self):
        t, X, _, centers = blob_data()
        model = (
            KMeans()
            .set_vector_col("features")
            .set_k(3)
            .set_max_iter(30)
            .set_prediction_col("cluster")
            .fit(t)
        )
        found = model.centroids()
        # each true center has a found centroid within 0.2
        for c in centers:
            assert np.min(np.linalg.norm(found - c, axis=1)) < 0.2

    def test_assignments_are_consistent(self):
        t, X, labels, _ = blob_data()
        model = (
            KMeans()
            .set_vector_col("features")
            .set_k(3)
            .set_max_iter(30)
            .set_prediction_col("cluster")
            .set_prediction_detail_col("dist")
            .fit(t)
        )
        (out,) = model.transform(t)
        assigned = np.asarray(out.col("cluster"))
        # same true blob -> same cluster id
        for g in range(3):
            ids = assigned[labels == g]
            assert len(np.unique(ids)) == 1
        # distance detail is the distance to the assigned centroid
        cents = model.centroids()
        expect = np.linalg.norm(X - cents[assigned.astype(int)], axis=1)
        np.testing.assert_allclose(np.asarray(out.col("dist")), expect, atol=1e-4)

    def test_tol_early_stop_and_cost(self):
        t, *_ = blob_data()
        model = (
            KMeans()
            .set_vector_col("features")
            .set_k(3)
            .set_max_iter(100)
            .set_tol(1e-4)
            .set_prediction_col("cluster")
            .fit(t)
        )
        assert model.train_epochs_ < 100
        assert model.train_cost_ > 0

    def test_save_load(self, tmp_path):
        t, *_ = blob_data()
        model = (
            KMeans()
            .set_vector_col("features")
            .set_k(3)
            .set_max_iter(20)
            .set_prediction_col("cluster")
            .fit(t)
        )
        path = os.path.join(tmp_path, "kmeans")
        model.save(path)
        loaded = load_stage(path)
        assert isinstance(loaded, KMeansModel)
        np.testing.assert_allclose(loaded.centroids(), model.centroids())

    def test_k_exceeds_rows_raises(self):
        t, *_ = blob_data(n_per=1)
        with pytest.raises(ValueError):
            KMeans().set_vector_col("features").set_k(10).set_prediction_col(
                "c"
            ).fit(t)

    def test_kmeans_plus_plus_spreads_centers(self):
        rng = np.random.RandomState(0)
        X = np.array([[0.0, 0.0], [0.1, 0.0], [10.0, 10.0], [10.1, 10.0]])
        centers = kmeans_plus_plus(X, 2, rng)
        # the two centers come from different corners
        d = np.linalg.norm(centers[0] - centers[1])
        assert d > 5


class TestKnn:
    def test_matches_numpy_bruteforce(self):
        t, X, labels, _ = blob_data(seed=2)
        rng = np.random.RandomState(3)
        Q = rng.randn(40, 2) * 4 + 2
        qschema = Schema.of(("features", DataTypes.DENSE_VECTOR),)
        qt = Table.from_columns(
            qschema, {"features": [DenseVector(r) for r in Q]}
        )
        k = 5
        model = (
            Knn()
            .set_vector_col("features")
            .set_label_col("label")
            .set_k(k)
            .set_prediction_col("pred")
            .set_prediction_detail_col("nearest")
            .fit(t)
        )
        (out,) = model.transform(qt)

        # numpy reference
        d = ((Q[:, None, :] - X[None, :, :]) ** 2).sum(-1)
        idx = np.argsort(d, axis=1)[:, :k]
        votes = labels[idx]
        expect = []
        for row in votes:
            vals, counts = np.unique(row, return_counts=True)
            expect.append(vals[np.argmax(counts)])
        np.testing.assert_array_equal(np.asarray(out.col("pred")), expect)
        np.testing.assert_allclose(
            np.asarray(out.col("nearest")),
            np.sqrt(d.min(axis=1)),
            rtol=1e-4, atol=1e-4,
        )

    def test_training_accuracy_k1(self):
        t, X, labels, _ = blob_data(seed=4)
        model = (
            Knn()
            .set_vector_col("features")
            .set_label_col("label")
            .set_k(1)
            .set_prediction_col("pred")
            .fit(t)
        )
        (out,) = model.transform(t)
        np.testing.assert_array_equal(np.asarray(out.col("pred")), labels)

    def test_save_load(self, tmp_path):
        t, *_ = blob_data(n_per=10)
        model = (
            Knn()
            .set_vector_col("features")
            .set_label_col("label")
            .set_k(3)
            .set_prediction_col("pred")
            .fit(t)
        )
        path = os.path.join(tmp_path, "knn")
        model.save(path)
        loaded = load_stage(path)
        assert isinstance(loaded, KnnModel)
        (out,) = loaded.transform(t)
        (orig,) = model.transform(t)
        np.testing.assert_array_equal(out.col("pred"), orig.col("pred"))

    def test_bf16_distances_opt_in(self):
        """bf16Distances: well-separated data classifies identically; the
        flag is opt-in because exact ties/bit-parity are not guaranteed."""
        t, X, labels, _ = blob_data(seed=8)
        rng = np.random.RandomState(9)
        Q = rng.randn(40, 2) * 4 + 2
        qt = Table.from_columns(
            Schema.of(("features", DataTypes.DENSE_VECTOR),),
            {"features": [DenseVector(r) for r in Q]},
        )

        def preds(bf16):
            m = (
                Knn().set_vector_col("features").set_label_col("label")
                .set_k(5).set_prediction_col("pred")
                .set_bf16_distances(bf16).fit(t)
            )
            return np.asarray(m.transform(qt)[0].col("pred"))

        np.testing.assert_array_equal(preds(True), preds(False))

    def test_non_contiguous_labels(self):
        """Labels need not be 0..c-1 — e.g. {-1, 7}."""
        schema = Schema.of(("features", DataTypes.DENSE_VECTOR), ("label", "double"))
        X = np.array([[0.0], [0.1], [5.0], [5.1]])
        y = np.array([-1.0, -1.0, 7.0, 7.0])
        t = Table.from_columns(
            schema, {"features": [DenseVector(r) for r in X], "label": y}
        )
        model = (
            Knn()
            .set_vector_col("features")
            .set_label_col("label")
            .set_k(2)
            .set_prediction_col("pred")
            .fit(t)
        )
        (out,) = model.transform(t)
        np.testing.assert_array_equal(np.asarray(out.col("pred")), y)


class TestReviewRegressions:
    def test_knn_k_exceeding_train_size_raises(self):
        """Regression: k > training rows used to emit phantom class-0 votes."""
        schema = Schema.of(("features", DataTypes.DENSE_VECTOR), ("label", "double"))
        X = np.array([[0.0], [0.1], [5.0]])
        y = np.array([7.0, 7.0, -1.0])
        t = Table.from_columns(
            schema, {"features": [DenseVector(r) for r in X], "label": y}
        )
        model = (
            Knn().set_vector_col("features").set_label_col("label")
            .set_k(5).set_prediction_col("pred").fit(t)
        )
        with pytest.raises(ValueError, match="exceeds training-set size"):
            model.transform(t)

    def test_transform_on_empty_table(self):
        """Regression: 0-row transform used to crash on output rank."""
        t, *_ = blob_data(n_per=10)
        empty = t.slice_rows(0, 0)

        km = (
            KMeans().set_vector_col("features").set_k(3)
            .set_max_iter(5).set_prediction_col("c").fit(t)
        )
        (out,) = km.transform(empty)
        assert out.num_rows() == 0

        kn = (
            Knn().set_vector_col("features").set_label_col("label")
            .set_k(3).set_prediction_col("p").fit(t)
        )
        (out2,) = kn.transform(empty)
        assert out2.num_rows() == 0


class TestKMeansFusedCheckpoint:
    def _est(self, max_iter, ckpt=None, tol=0.0):
        e = (KMeans().set_vector_col("features").set_k(3)
             .set_max_iter(max_iter).set_prediction_col("c").set_seed(0))
        if tol:
            e.set_tol(tol)
        if ckpt:
            e.set_checkpoint_dir(str(ckpt)).set_checkpoint_interval(3)
        return e

    def test_resume_matches_uninterrupted(self, tmp_path):
        t, *_ = blob_data(seed=2)
        full = self._est(10).fit(t)
        ckpt = tmp_path / "km"
        self._est(6, ckpt).fit(t)
        resumed = self._est(10, ckpt).fit(t)
        assert resumed.train_epochs_ == 10
        np.testing.assert_allclose(
            resumed.centroids(), full.centroids(), rtol=1e-5, atol=1e-6
        )

    def test_converged_refit_is_noop(self, tmp_path):
        t, *_ = blob_data(seed=3)
        ckpt = tmp_path / "km2"
        first = self._est(100, ckpt, tol=1e-4).fit(t)
        assert first.train_epochs_ < 100
        again = self._est(100, ckpt, tol=1e-4).fit(t)
        assert again.train_epochs_ == first.train_epochs_
        np.testing.assert_array_equal(again.centroids(), first.centroids())

    def test_metrics_recorded(self):
        t, *_ = blob_data()
        model = self._est(5).fit(t)
        s = model.train_metrics_.summary(skip_warmup=0)
        assert s["total_samples"] == 5 * 180  # epochs * rows
        assert s["total_seconds"] > 0
