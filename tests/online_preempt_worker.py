"""Worker for the continuous-learning kill-and-resume test (ISSUE 14).

Run as: python online_preempt_worker.py <phase> <candidate_dir>

Phase ``plain``: drive a :class:`ContinuousLearningController` (publish-
only: no server, the trainer-box half of a split deployment) over a
deterministic columnar label stream to completion and print the final
model parameters.  Phase ``crash``: the same loop, but a real SIGTERM is
delivered MID-STREAM (from a hook between source chunks, so the timing
is deterministic); the streaming driver commits an emergency snapshot at
the next span boundary, the controller commits an emergency CANDIDATE
through the sidecar-commit scheme, and the process exits cleanly with
code 0 — the worker never reaches the final print.  Phase ``resume``:
the same loop over the same candidate dir; the stream checkpoint fast-
forwards to the committed cut and the finished run's parameters must be
BIT-IDENTICAL to the ``plain`` run's (asserted by the parent test).
"""

import os
import sys

phase = sys.argv[1]
candidate_dir = sys.argv[2]

os.environ.setdefault("FLINK_ML_TPU_COMPILE_CACHE", "off")
os.environ.pop("XLA_FLAGS", None)

import jax

jax.config.update("jax_platforms", "cpu")

import signal  # noqa: E402

import numpy as np  # noqa: E402

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from flink_ml_tpu.lib.online import OnlineLogisticRegression  # noqa: E402
from flink_ml_tpu.serving import ContinuousLearningController  # noqa: E402
from flink_ml_tpu.table.schema import DataTypes, Schema  # noqa: E402
from flink_ml_tpu.table.sources import UnboundedSource  # noqa: E402
from flink_ml_tpu.table.table import Table  # noqa: E402

SCHEMA = Schema.of(("features", DataTypes.DENSE_VECTOR), ("label", "double"))
ROWS, DIM, CHUNK = 1000, 4, 100
TRUE_W = np.array([2.0, -1.5, 1.0, 0.5])


def _xy(n, seed):
    r = np.random.RandomState(seed)
    X = r.randn(n, DIM)
    y = ((X @ TRUE_W) > 0).astype(np.float64)
    return X.astype(np.float32), y


class ChunkSource(UnboundedSource):
    """Deterministic columnar stream; in the ``crash`` phase a real
    SIGTERM is delivered to this process between chunks 6 and 7 —
    mid-stream, after several windows have fired."""

    def __init__(self, kill_at_chunk=None):
        self._kill_at = kill_at_chunk
        self._x, self._y = _xy(ROWS, seed=11)
        self._ts = np.arange(ROWS, dtype=np.int64) * 50

    def stream_chunks(self, max_rows=None):
        def gen():
            for i, a in enumerate(range(0, ROWS, CHUNK)):
                if i == self._kill_at:
                    os.kill(os.getpid(), signal.SIGTERM)
                b = a + CHUNK
                yield self._ts[a:b], {"features": self._x[a:b],
                                      "label": self._y[a:b]}

        return gen()

    def stream(self):
        from flink_ml_tpu.table.sources import chunk_row_iter

        for ts, cols in self.stream_chunks():
            yield from chunk_row_iter(ts, cols, SCHEMA)

    def schema(self):
        return SCHEMA


Xh, yh = _xy(300, seed=12)
holdout = Table.from_columns(SCHEMA, {"features": Xh, "label": yh})
estimator = (
    OnlineLogisticRegression().set_vector_col("features")
    .set_label_col("label").set_prediction_col("pred")
    .set_learning_rate(0.5).set_window_ms(1000)
)
source = ChunkSource(kill_at_chunk=6 if phase == "crash" else None)
controller = ContinuousLearningController(
    estimator, source, holdout, candidate_dir=candidate_dir,
    candidate_every=5,
)
model = controller.run()  # a crash-phase SIGTERM exits here with code 0
controller.stop()
w = model.coefficients()
b = model.intercept()
print(
    "PARAMS " + " ".join(f"{v:.17g}" for v in list(w) + [b]),
    flush=True,
)
