"""Subprocess worker for tests/test_multichip_serve.py (ISSUE 15).

Launched once per device count (``XLA_FLAGS=--xla_force_host_platform_
device_count=N`` set by the parent BEFORE jax initializes), loads the
pipeline models the parent fitted and saved, transforms the SAME
deterministic tables, and prints one ``RESULT {json}`` line holding:

* per family (dense LR, sparse segment-CSR LR, scalers, KMeans assign,
  Knn chunked scan): the fused transform's discrete outputs verbatim and
  float outputs rounded to comparison precision — with fused-vs-staged
  parity asserted IN-WORKER (discrete bit-identical, floats ~1e-5);
* quarantine offsets of a fused transform with planted bad rows;
* a pressure-bisection run (``fault.oom``-injected HBM ceiling) whose
  output must equal the clean fused run bit-identically;
* the fused/shard_map dispatch counters, so the parent can assert the
  sharded path actually ran on the multi-device mesh (and did NOT on
  the 1-device mesh).

The parent compares RESULTs across device counts: multi-chip serving
must be a deployment detail, never a numerics change.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

N, D = 384, 6  # 384 is deliberately not a ladder rung (pads to 512)
SPARSE_DIM, NNZ = 64, 4


def make_tables():
    """Deterministic serving tables — identical in parent and workers."""
    from flink_ml_tpu.ops.vector import SparseVector
    from flink_ml_tpu.table.schema import DataTypes, Schema
    from flink_ml_tpu.table.table import Table

    rng = np.random.RandomState(29)
    X = (2.0 * rng.randn(N, D) + 1.0).astype(np.float32)
    w = rng.randn(D).astype(np.float32)
    y = ((X - 1.0) @ w > 0).astype(np.float64)
    dense = Table.from_columns(
        Schema.of(("features", DataTypes.DENSE_VECTOR),
                  ("label", "double")),
        {"features": X, "label": y},
    )
    vecs = []
    true_w = np.zeros(SPARSE_DIM)
    true_w[:8] = rng.randn(8) * 2
    ys = []
    for _ in range(N):
        idx = np.sort(rng.choice(SPARSE_DIM, NNZ, replace=False))
        val = rng.randn(NNZ)
        vecs.append(SparseVector(SPARSE_DIM, idx.astype(np.int64), val))
        ys.append(float(val @ true_w[idx] > 0))
    aux = rng.randn(N, 3).astype(np.float32)
    sparse = Table.from_columns(
        Schema.of(("aux", DataTypes.DENSE_VECTOR),
                  ("features", DataTypes.SPARSE_VECTOR),
                  ("label", "double")),
        {"aux": aux, "features": vecs, "label": np.asarray(ys)},
    )
    return dense, sparse


#: family -> (model subdir, table key, discrete output cols, float cols)
FAMILIES = {
    "dense_lr": ("dense_lr", "dense", ["pred"], ["proba"]),
    "sparse_lr": ("sparse_lr", "sparse", ["pred"], ["proba"]),
    "scalers": ("scalers", "dense", [], ["features"]),
    "kmeans": ("kmeans", "dense", ["cluster"], []),
    "knn": ("knn", "dense", ["pred"], []),
}


def _col(table, name):
    from flink_ml_tpu.table.schema import DataTypes

    if DataTypes.is_vector(table.schema.type_of(name)):
        return np.asarray(table.features_dense(name), dtype=np.float64)
    return np.asarray(table.col(name), dtype=np.float64)


def _transform(model, table, fuse: bool):
    os.environ["FMT_FUSE_TRANSFORM"] = "1" if fuse else "0"
    try:
        (out,) = model.transform(table)
    finally:
        os.environ.pop("FMT_FUSE_TRANSFORM", None)
    return out


def main(model_dir: str) -> None:
    import jax

    from flink_ml_tpu import fault, obs
    from flink_ml_tpu.api.pipeline import PipelineModel
    from flink_ml_tpu.serve import quarantine
    from flink_ml_tpu.table.table import Table

    dense, sparse = make_tables()
    tables = {"dense": dense, "sparse": sparse}
    obs.enable()
    obs.reset()
    result = {"devices": jax.device_count(), "families": {}}

    for fam, (sub, tkey, discrete_cols, float_cols) in FAMILIES.items():
        model = PipelineModel.load(os.path.join(model_dir, sub))
        table = tables[tkey]
        fused_out = _transform(model, table, True)
        staged_out = _transform(model, table, False)
        rec = {}
        for c in discrete_cols:
            f, s = _col(fused_out, c), _col(staged_out, c)
            assert np.array_equal(f, s), (
                f"{fam}.{c}: fused discrete diverges from staged")
            rec[c] = f.tolist()
        for c in float_cols:
            f, s = _col(fused_out, c), _col(staged_out, c)
            np.testing.assert_allclose(
                f, s, rtol=1e-5, atol=1e-5,
                err_msg=f"{fam}.{c}: fused floats diverge from staged")
            rec[c] = np.round(f, 5).tolist()
        result["families"][fam] = rec

    # -- quarantine offsets through the fused sharded path -------------------
    X = np.asarray(dense.features_dense("features")).copy()
    bad_rows = [5, 130, N - 1]
    for i, r in enumerate(bad_rows):
        X[r, i % D] = np.nan if i % 2 == 0 else np.inf
    bad = Table.from_columns(dense.schema, {
        "features": X.astype(np.float32), "label": dense.col("label")})
    model = PipelineModel.load(os.path.join(model_dir, "dense_lr"))
    quarantine.reset()
    q_out = _transform(model, bad, True)
    assert q_out.num_rows() == N - len(bad_rows), q_out.num_rows()
    qt = quarantine.quarantine_table("StandardScalerModel")
    assert qt is not None, "no quarantine side-table emitted"
    result["quarantine_rows"] = sorted(
        int(r) for r in qt.col(quarantine.QUARANTINE_ROW_COL))
    result["quarantine_survivor_pred"] = _col(q_out, "pred").tolist()
    quarantine.reset()

    # -- pressure bisection on this mesh: bit-identical recovery -------------
    from flink_ml_tpu.fault import pressure

    pressure.reset_states()
    clean = _transform(model, dense, True)
    c0 = obs.registry().snapshot()["counters"]
    fault.configure("fault.oom>96", seed=0)
    try:
        pressured = _transform(model, dense, True)
    finally:
        fault.configure(None)
    c1 = obs.registry().snapshot()["counters"]
    assert np.array_equal(_col(pressured, "pred"), _col(clean, "pred")), (
        "pressure-bisected predictions diverge from the clean run")
    np.testing.assert_allclose(
        _col(pressured, "proba"), _col(clean, "proba"), rtol=1e-5,
        atol=1e-5, err_msg="pressure-bisected probas diverge")
    result["bisections"] = int(
        c1.get("pressure.bisections", 0) - c0.get("pressure.bisections", 0))
    caps = pressure.current_caps()
    result["per_device_cap"] = next(
        (v for k, v in caps.items() if k.startswith("FusedPlan[")), None)
    pressure.reset_states()

    counters = obs.registry().snapshot()["counters"]
    result["fused_dispatches"] = counters.get("pipeline.fused_dispatches", 0)
    result["shard_map_dispatches"] = counters.get(
        "fused.shard_map_dispatches", 0)
    result["plan_fallbacks"] = counters.get(
        "pipeline.plan_fallback_batches", 0)
    print("RESULT " + json.dumps(result))


if __name__ == "__main__":
    # worker-only jax config: the parent suite imports make_tables from
    # this module, and a module-level config update would leak
    # cpu/x64 into the importing process's backend (e.g. a TPU tier run)
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)
    main(sys.argv[1])
