"""Worker for the two-process jax.distributed smoke test (test_distributed.py).

Run as: python distributed_worker.py <process_id> <num_processes> <port>

Each process owns 4 virtual CPU devices; after ``initialize_distributed`` the
global mesh spans 8 devices across both OS processes and a jitted global sum
exercises one cross-process (DCN-path) collective.  This is the multi-host
bring-up the reference delegates to Flink's runtime (flink-ml-lib/pom.xml:40-58
provided deps; job/task managers over TCP), realized as a jax.distributed
control plane + XLA collective data plane.
"""

import os
import sys

process_id = int(sys.argv[1])
num_processes = int(sys.argv[2])
port = sys.argv[3]

if os.environ.get("FMT_WORKER_DUMP"):
    # debug aid: dump all thread stacks if the worker wedges
    import faulthandler

    faulthandler.dump_traceback_later(
        int(os.environ["FMT_WORKER_DUMP"]), exit=True
    )

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=4"
)

import jax

# Some environments pre-import jax at interpreter startup (see conftest.py), so
# the platform must be forced via config, not env vars.
jax.config.update("jax_platforms", "cpu")
# CPU cross-process collectives need a backend; gloo is the in-tree one.
jax.config.update("jax_cpu_collectives_implementation", "gloo")

import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from flink_ml_tpu.parallel.mesh import default_mesh, initialize_distributed, shutdown_distributed

initialize_distributed(
    coordinator_address=f"localhost:{port}",
    num_processes=num_processes,
    process_id=process_id,
)

assert jax.process_count() == num_processes, jax.process_count()
assert len(jax.local_devices()) == 4, jax.local_devices()
assert len(jax.devices()) == 4 * num_processes, jax.devices()

mesh = default_mesh()  # spans all global devices on the 'data' axis

# Each process contributes its own rows; the global array is sharded over the
# full mesh, so the jitted sum must reduce across the process boundary.
local_rows = np.arange(4, dtype=np.float32) + 4.0 * process_id
garr = jax.make_array_from_process_local_data(
    NamedSharding(mesh, P("data")), local_rows, global_shape=(4 * num_processes,)
)

total = jax.jit(jnp.sum, out_shardings=NamedSharding(mesh, P()))(garr)
print(f"RESULT {float(total)}", flush=True)

# One REAL framework training epoch across the process boundary: both
# processes deterministically pack the same global minibatch stack, each
# feeds only its local shard, and the epoch step's in-step gradient psum
# crosses the process boundary.  The parent test runs the identical epoch
# on a single-process 8-device mesh and compares the numbers — 2x4
# multi-process must equal 1x8 single-process.
from tests._distributed_common import make_epoch_inputs, make_epoch_step

combined, params0 = make_epoch_inputs()  # (n_dev*steps, mb, d+2)
local = combined[combined.shape[0] // num_processes * process_id :
                 combined.shape[0] // num_processes * (process_id + 1)]
# x/y/w as separate leaves, all sharded from process-local slices
x_l, y_l, w_l = local[..., :-2], local[..., -2], local[..., -1]
batch = tuple(
    jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("data")), arr,
        global_shape=(combined.shape[0],) + arr.shape[1:],
    )
    for arr in (x_l, y_l, w_l)
)
params = tuple(
    jax.make_array_from_process_local_data(
        NamedSharding(mesh, P()), p, global_shape=p.shape
    )
    for p in params0
)
epoch_step = make_epoch_step(mesh)
(w, b), (loss, delta) = epoch_step(params, batch)
vals = [float(v) for v in np.asarray(w)] + [float(b), float(loss)]
print("TRAIN " + " ".join(f"{v:.9e}" for v in vals), flush=True)

# The REAL multi-host data plane (VERDICT r3 item 2): each process reads a
# DISJOINT CSV file shard and runs the full estimator-level fit — packing
# targets the local share of the data axis and shard_batch assembles the
# global batch from per-process slices (make_array_from_process_local_data).
# The parent compares both fits against the single-process fit over the
# equivalent interleaved row order.
if len(sys.argv) > 4:
    shard_dir = sys.argv[4]
    from tests._distributed_common import fit_shard_table, shard_schema
    from flink_ml_tpu.table.sources import ChunkedTable, CsvSource
    from flink_ml_tpu.utils.environment import MLEnvironmentFactory

    MLEnvironmentFactory.get_default().set_mesh(mesh)
    source = CsvSource(
        os.path.join(shard_dir, f"shard{process_id}.csv"), shard_schema()
    )

    w_mem, b_mem = fit_shard_table(source.read())
    print(
        "FITMEM " + " ".join(f"{v:.9e}" for v in list(w_mem) + [b_mem]),
        flush=True,
    )

    # the same fit out-of-core: the local shard streams through the block
    # queue in chunks; placement rides the same process-local data plane
    w_ooc, b_ooc = fit_shard_table(ChunkedTable(source, chunk_rows=64))
    print(
        "FITOOC " + " ".join(f"{v:.9e}" for v in list(w_ooc) + [b_ooc]),
        flush=True,
    )

    # SPARSE per-process fit: the shards carry deliberately UNEQUAL nnz
    # densities, so each process's local pack lands on a different padded
    # nnz width and the cross-process agree_max repack (parallel/mesh.py)
    # must reconcile the compiled block shapes before the fused loop runs
    from tests._distributed_common import (
        fit_sparse_shard_table,
        make_sparse_shard_rows,
        sparse_shard_schema,
    )
    from flink_ml_tpu.table.table import Table

    svecs, sy = make_sparse_shard_rows(num_processes)[process_id]
    sparse_table = Table.from_columns(
        sparse_shard_schema(), {"features": svecs, "label": sy}
    )
    w_sp, b_sp = fit_sparse_shard_table(sparse_table)
    # the weight vector is 2048-dim: print a stable digest + probe slice
    digest = [float(np.sum(w_sp)), float(np.sum(w_sp * w_sp))]
    probe = [float(v) for v in w_sp[:8]]
    print(
        "FITSPARSE " + " ".join(
            f"{v:.9e}" for v in digest + probe + [b_sp]
        ),
        flush=True,
    )

    # hot/cold fit across processes: the hot set must come from the GLOBAL
    # frequency vector (agree_sum of per-shard counts — each shard's local
    # top-K differs) and both processes must fill the agreed pad widths
    w_hc, b_hc = fit_sparse_shard_table(sparse_table, hot_k=16)
    digest = [float(np.sum(w_hc)), float(np.sum(w_hc * w_hc))]
    probe = [float(v) for v in w_hc[:8]]
    print(
        "FITHOT " + " ".join(
            f"{v:.9e}" for v in digest + probe + [b_hc]
        ),
        flush=True,
    )

    # sparse OUT-OF-CORE across processes: one exact local stream scan +
    # agree_max fixes the block shapes; equal shards here, so the result
    # must bit-match the in-memory sparse fit (the OOC engine's
    # schedule-exact contract) and hence the parent's single-process
    # reference digest
    from flink_ml_tpu.table.sources import ChunkedTable, CollectionSource

    ooc_table = ChunkedTable(
        CollectionSource(list(zip(svecs, sy)), sparse_shard_schema()),
        chunk_rows=64,
    )
    w_so, b_so = fit_sparse_shard_table(ooc_table)
    digest = [float(np.sum(w_so)), float(np.sum(w_so * w_so))]
    probe = [float(v) for v in w_so[:8]]
    print(
        "FITSOOC " + " ".join(f"{v:.9e}" for v in digest + probe + [b_so]),
        flush=True,
    )

    # hot/cold OUT-OF-CORE across processes: the scan-derived local counts
    # agree_sum into the global frequency vector, the shared feature plan
    # permutes identically everywhere, and the streamed fit must bit-match
    # the in-memory hot/cold fit (-> the parent's FITHOT reference digest)
    ooc_hot = ChunkedTable(
        CollectionSource(list(zip(svecs, sy)), sparse_shard_schema()),
        chunk_rows=64,
    )
    w_ho, b_ho = fit_sparse_shard_table(ooc_hot, hot_k=16)
    digest = [float(np.sum(w_ho)), float(np.sum(w_ho * w_ho))]
    probe = [float(v) for v in w_ho[:8]]
    print(
        "FITHOOC " + " ".join(f"{v:.9e}" for v in digest + probe + [b_ho]),
        flush=True,
    )

    # UNEQUAL shards: the short shard pads its epochs with gated no-op
    # blocks; both processes must land on the identical global model
    from tests._distributed_common import make_unequal_sparse_shard_rows

    uvecs, uy = make_unequal_sparse_shard_rows(num_processes)[process_id]
    ooc_unequal = ChunkedTable(
        CollectionSource(list(zip(uvecs, uy)), sparse_shard_schema()),
        chunk_rows=64,
    )
    w_su, b_su = fit_sparse_shard_table(ooc_unequal)
    digest = [float(np.sum(w_su)), float(np.sum(w_su * w_su))]
    probe = [float(v) for v in w_su[:8]]
    print(
        "FITSOOCU " + " ".join(f"{v:.9e}" for v in digest + probe + [b_su]),
        flush=True,
    )

    # KMeans across processes: the k-means++ init must seed from the
    # allgathered cross-process sample pool (identical on every process),
    # and Lloyd epochs psum cluster sums across the process boundary
    from tests._distributed_common import fit_kmeans_shard_table

    cents, cost = fit_kmeans_shard_table(source.read())
    digest = [float(np.sum(cents)), float(np.sum(cents * cents)), cost]
    probe = [float(v) for v in cents[0]]
    print(
        "FITKM " + " ".join(f"{v:.9e}" for v in digest + probe),
        flush=True,
    )

    # TRANSFORM in a multi-process session runs on the process-LOCAL mesh
    # (subtask-local ModelMapperAdapter semantics): each process scores its
    # own rows with its own model copy, no collectives.  GLM scoring and
    # sharded-reference Knn both must match the parent's single-process
    # transform of the same shard.
    from flink_ml_tpu.lib import Knn, LogisticRegression
    from tests._distributed_common import (
        LEARNING_RATE,
        SHARD_EPOCHS,
        SHARD_FEATURES,
        SHARD_G,
    )

    est = (
        LogisticRegression().set_feature_cols(SHARD_FEATURES)
        .set_label_col("label").set_prediction_col("pred")
        .set_learning_rate(LEARNING_RATE).set_max_iter(SHARD_EPOCHS)
        .set_global_batch_size(SHARD_G)
    )
    local_table = source.read()
    glm_model = est.fit(local_table)
    (scored,) = glm_model.transform(local_table)
    preds = np.asarray(scored.col("pred"), dtype=np.float64)
    print(
        "XFORM " + " ".join(f"{v:.0f}" for v in preds[:32]),
        flush=True,
    )

    knn = (
        Knn().set_feature_cols(SHARD_FEATURES).set_label_col("label")
        .set_prediction_col("knnp").set_k(3).set_shard_model_data(True)
        .fit(local_table)
    )
    (kscored,) = knn.transform(local_table)
    kpreds = np.asarray(kscored.col("knnp"), dtype=np.float64)
    print(
        "XFORMKNN " + " ".join(f"{v:.0f}" for v in kpreds[:32]),
        flush=True,
    )

    # 2-D (data x model) mesh ACROSS PROCESSES: the global mesh shards the
    # feature dimension over 'model' while each process feeds its own data
    # rows; model-axis params place via global_put (every process holds
    # the full vector, materializes its slice).  Digests must match the
    # parent's single-process fits on the same-shaped mesh.
    from flink_ml_tpu.parallel.mesh import create_mesh

    mesh2d = create_mesh({"data": 2 * num_processes, "model": 2})
    MLEnvironmentFactory.get_default().set_mesh(mesh2d)
    try:
        w_d2, b_d2 = fit_shard_table(source.read())
        print(
            "FITD2D " + " ".join(
                f"{v:.9e}" for v in list(w_d2) + [b_d2]
            ),
            flush=True,
        )
        w_s2, b_s2 = fit_sparse_shard_table(sparse_table)
        digest = [float(np.sum(w_s2)), float(np.sum(w_s2 * w_s2))]
        probe = [float(v) for v in w_s2[:8]]
        print(
            "FITS2D " + " ".join(
                f"{v:.9e}" for v in digest + probe + [b_s2]
            ),
            flush=True,
        )
        w_h2, b_h2 = fit_sparse_shard_table(sparse_table, hot_k=16)
        digest = [float(np.sum(w_h2)), float(np.sum(w_h2 * w_h2))]
        probe = [float(v) for v in w_h2[:8]]
        print(
            "FITH2D " + " ".join(
                f"{v:.9e}" for v in digest + probe + [b_h2]
            ),
            flush=True,
        )
        # the full formulation matrix's last corner: hot/cold +
        # out-of-core + 2-D mesh + multi-process (agree_sum'd counts feed
        # the model_size-aware plan; the streamed 2-D chunk program masks
        # to shard ownership; model-axis params ride global_put)
        w_ho2, b_ho2 = fit_sparse_shard_table(
            ChunkedTable(
                CollectionSource(
                    list(zip(svecs, sy)), sparse_shard_schema()
                ),
                chunk_rows=64,
            ),
            hot_k=16,
        )
        digest = [float(np.sum(w_ho2)), float(np.sum(w_ho2 * w_ho2))]
        probe = [float(v) for v in w_ho2[:8]]
        print(
            "FITH2DOOC " + " ".join(
                f"{v:.9e}" for v in digest + probe + [b_ho2]
            ),
            flush=True,
        )
    finally:
        MLEnvironmentFactory.get_default().set_mesh(mesh)

    # KMeans OUT-OF-CORE across processes: the reservoir pass doubles as
    # the row count for the agreed per-epoch block count, the init pool
    # allgathers, and Lloyd accumulators psum across the process boundary
    # block by block
    cents_o, cost_o = fit_kmeans_shard_table(
        ChunkedTable(source, chunk_rows=64)
    )
    digest = [float(np.sum(cents_o)), float(np.sum(cents_o * cents_o)),
              cost_o]
    probe = [float(v) for v in cents_o[0]]
    print(
        "FITKMOOC " + " ".join(f"{v:.9e}" for v in digest + probe),
        flush=True,
    )

shutdown_distributed()
