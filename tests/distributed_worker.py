"""Worker for the two-process jax.distributed smoke test (test_distributed.py).

Run as: python distributed_worker.py <process_id> <num_processes> <port>

Each process owns 4 virtual CPU devices; after ``initialize_distributed`` the
global mesh spans 8 devices across both OS processes and a jitted global sum
exercises one cross-process (DCN-path) collective.  This is the multi-host
bring-up the reference delegates to Flink's runtime (flink-ml-lib/pom.xml:40-58
provided deps; job/task managers over TCP), realized as a jax.distributed
control plane + XLA collective data plane.
"""

import os
import sys

process_id = int(sys.argv[1])
num_processes = int(sys.argv[2])
port = sys.argv[3]

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=4"
)

import jax

# Some environments pre-import jax at interpreter startup (see conftest.py), so
# the platform must be forced via config, not env vars.
jax.config.update("jax_platforms", "cpu")
# CPU cross-process collectives need a backend; gloo is the in-tree one.
jax.config.update("jax_cpu_collectives_implementation", "gloo")

import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from flink_ml_tpu.parallel.mesh import default_mesh, initialize_distributed, shutdown_distributed

initialize_distributed(
    coordinator_address=f"localhost:{port}",
    num_processes=num_processes,
    process_id=process_id,
)

assert jax.process_count() == num_processes, jax.process_count()
assert len(jax.local_devices()) == 4, jax.local_devices()
assert len(jax.devices()) == 4 * num_processes, jax.devices()

mesh = default_mesh()  # spans all global devices on the 'data' axis

# Each process contributes its own rows; the global array is sharded over the
# full mesh, so the jitted sum must reduce across the process boundary.
local_rows = np.arange(4, dtype=np.float32) + 4.0 * process_id
garr = jax.make_array_from_process_local_data(
    NamedSharding(mesh, P("data")), local_rows, global_shape=(4 * num_processes,)
)

total = jax.jit(jnp.sum, out_shardings=NamedSharding(mesh, P()))(garr)
print(f"RESULT {float(total)}", flush=True)

shutdown_distributed()
