"""Live telemetry plane tests (ISSUE 10): OpenMetrics rendering + the
strict parser, the embedded endpoint (liveness vs. reason-coded
readiness, statusz), the ModelServer lifecycle wiring, and the SLO
burn-rate monitor (gauges, flight breach dumps, readiness feed)."""

import json
import threading
import urllib.request
from urllib.error import HTTPError, URLError

import numpy as np
import pytest

from flink_ml_tpu import obs
from flink_ml_tpu.fault import pressure
from flink_ml_tpu.obs import flight, slo, telemetry
from flink_ml_tpu.obs.telemetry import (
    TelemetryServer,
    family_name,
    parse_openmetrics,
    render_openmetrics,
)
from flink_ml_tpu.serve.breaker import breaker, reset_breakers


@pytest.fixture(autouse=True)
def _telemetry_isolated(monkeypatch, tmp_path):
    """Every test starts with a clean registry, no breakers, no pressure
    state, no registered telemetry sources, and flight dumps routed to a
    throwaway dir — the plane is process-global by design."""
    monkeypatch.setenv("FMT_FLIGHT_DIR", str(tmp_path / "flight"))
    monkeypatch.delenv("FMT_TELEMETRY_PORT", raising=False)
    obs.enable()
    obs.reset()
    flight.reset()
    reset_breakers()
    pressure.reset_states()
    yield
    telemetry.stop()
    obs.disable()
    obs.reset()
    flight.reset()
    reset_breakers()
    pressure.reset_states()
    # a test that leaked a source must not poison the next test's probe
    with telemetry._SOURCES_LOCK:
        telemetry._READINESS_SOURCES.clear()
        telemetry._STATUS_SOURCES.clear()
        telemetry._HISTOGRAM_SOURCES.clear()


def _get(server, path):
    try:
        with urllib.request.urlopen(server.url(path), timeout=10) as r:
            return r.status, r.read().decode()
    except HTTPError as exc:
        return exc.code, exc.read().decode()


@pytest.fixture()
def endpoint():
    server = TelemetryServer(port=0).start()
    yield server
    server.stop()


class TestOpenMetricsRendering:
    def test_counter_gauge_summary_families(self):
        obs.counter_add("c.a", 5)
        obs.gauge_set("g.x", 7.5)
        obs.observe("t.step", 0.25)
        obs.observe("t.step", 0.75)
        text = render_openmetrics()
        lines = text.splitlines()
        assert "# TYPE fmt_c_a counter" in lines
        assert "fmt_c_a_total 5" in lines
        assert "# TYPE fmt_g_x gauge" in lines
        assert "fmt_g_x 7.5" in lines
        assert "# TYPE fmt_t_step summary" in lines
        assert 'fmt_t_step{quantile="0.5"} 0.25' in lines
        assert 'fmt_t_step{quantile="0.9"} 0.75' in lines
        assert 'fmt_t_step{quantile="0.99"} 0.75' in lines
        assert "fmt_t_step_count 2" in lines
        assert "fmt_t_step_sum 1" in lines
        assert lines[-1] == "# EOF"
        assert text.endswith("\n")

    def test_name_sanitization(self):
        # fused-plan breaker gauges carry brackets and plus signs
        obs.gauge_set("serve.breaker_state.FusedPlan[A+B]", 1.0)
        text = render_openmetrics()
        assert "fmt_serve_breaker_state_FusedPlan_A_B_ 1" in text
        parse_openmetrics(text)  # and the result is still valid

    def test_total_suffix_never_doubles(self):
        # OpenMetrics reserves _total for the counter SAMPLE: a registry
        # name already ending in _total must not render fam_total_total
        obs.counter_add("rows_total", 3)
        text = render_openmetrics()
        assert "# TYPE fmt_rows counter" in text
        assert "fmt_rows_total 3" in text
        assert "_total_total" not in text

    def test_renders_and_parses_roundtrip(self):
        obs.counter_add("serving.requests", 42)
        obs.counter_add("serving.shed.queue_full", 2)
        obs.gauge_set("pressure.cap.serving.batch", 128)
        for i in range(20):
            obs.observe("serving.request_latency_ms", float(i))
        samples = parse_openmetrics(render_openmetrics())
        assert samples[family_name("serving.requests") + "_total"] == 42
        assert samples[family_name("pressure.cap.serving.batch")] == 128
        fam = family_name("serving.request_latency_ms")
        assert samples[fam + "_count"] == 20
        assert samples[fam + "_sum"] == float(sum(range(20)))
        assert samples[f'{fam}{{quantile="0.9"}}'] >= \
            samples[f'{fam}{{quantile="0.5"}}']

    def test_empty_registry_is_valid(self):
        obs.reset()
        assert parse_openmetrics(render_openmetrics()) == {}


class TestOpenMetricsParser:
    def test_rejects_missing_eof(self):
        with pytest.raises(ValueError, match="EOF"):
            parse_openmetrics("# TYPE a counter\na_total 1\n")

    def test_rejects_sample_without_family(self):
        with pytest.raises(ValueError, match="before any"):
            parse_openmetrics("a_total 1\n# EOF\n")

    def test_rejects_sample_of_wrong_family(self):
        bad = "# TYPE a counter\nb_total 1\n# EOF\n"
        with pytest.raises(ValueError, match="does not belong"):
            parse_openmetrics(bad)

    def test_rejects_gauge_with_total_suffix(self):
        bad = "# TYPE a gauge\na_total 1\n# EOF\n"
        with pytest.raises(ValueError, match="does not belong"):
            parse_openmetrics(bad)

    def test_rejects_duplicate_family(self):
        bad = "# TYPE a counter\na_total 1\n# TYPE a counter\n# EOF\n"
        with pytest.raises(ValueError, match="duplicate family"):
            parse_openmetrics(bad)

    def test_rejects_malformed_sample(self):
        bad = "# TYPE a counter\na_total one\n# EOF\n"
        with pytest.raises(ValueError, match="malformed"):
            parse_openmetrics(bad)


class TestTelemetryServer:
    def test_off_by_default(self, monkeypatch):
        monkeypatch.delenv("FMT_TELEMETRY_PORT", raising=False)
        assert telemetry.env_port() is None
        assert telemetry.start() is None  # module-level: a quiet no-op
        with pytest.raises(ValueError, match="not configured"):
            TelemetryServer()

    def test_env_port_parsing(self, monkeypatch):
        monkeypatch.setenv("FMT_TELEMETRY_PORT", "0")
        assert telemetry.env_port() == 0
        monkeypatch.setenv("FMT_TELEMETRY_PORT", "9464")
        assert telemetry.env_port() == 9464
        monkeypatch.setenv("FMT_TELEMETRY_PORT", "nope")
        assert telemetry.env_port() is None

    def test_healthz_liveness(self, endpoint):
        status, body = _get(endpoint, "/healthz")
        assert status == 200
        payload = json.loads(body)
        assert payload["ok"] is True and payload["uptime_s"] >= 0

    def test_metrics_serves_the_registry(self, endpoint):
        obs.counter_add("c.scraped", 7)
        status, body = _get(endpoint, "/metrics")
        assert status == 200
        samples = parse_openmetrics(body)
        assert samples[family_name("c.scraped") + "_total"] == 7

    def test_unknown_path_404(self, endpoint):
        status, body = _get(endpoint, "/nope")
        assert status == 404
        assert "/metrics" in body  # the 404 names the real paths

    def test_readyz_ok_when_clean(self, endpoint):
        status, body = _get(endpoint, "/readyz")
        assert status == 200
        assert json.loads(body) == {"ready": True, "reasons": []}

    def test_readyz_503_on_open_breaker_and_recovers(self, endpoint):
        b = breaker("TelemetryTestMapper")
        for _ in range(3):
            b.record_failure()
        status, body = _get(endpoint, "/readyz")
        assert status == 503
        payload = json.loads(body)
        assert payload["ready"] is False
        (reason,) = payload["reasons"]
        assert reason["reason"] == "breaker_open"
        assert "TelemetryTestMapper" in reason["detail"]
        reset_breakers()
        status, _ = _get(endpoint, "/readyz")
        assert status == 200

    def test_readyz_503_on_pressure_cap_below_floor(self, endpoint):
        # shrink to cap=2, under the default floor of 8
        pressure.state("test.surface").shrink(4, floor=1)
        status, body = _get(endpoint, "/readyz")
        assert status == 503
        (reason,) = json.loads(body)["reasons"]
        assert reason["reason"] == "memory_pressure"
        assert "test.surface" in reason["detail"]
        pressure.reset_states()
        status, _ = _get(endpoint, "/readyz")
        assert status == 200

    def test_readyz_ignores_pressure_cap_above_floor(self, endpoint):
        pressure.state("test.surface").shrink(512, floor=1)  # cap=256
        status, _ = _get(endpoint, "/readyz")
        assert status == 200

    def test_registered_source_feeds_readyz(self, endpoint):
        reasons = [{"reason": "custom_drain", "detail": "draining"}]
        source = lambda: reasons  # noqa: E731
        telemetry.register_readiness(source)
        try:
            status, body = _get(endpoint, "/readyz")
            assert status == 503
            assert json.loads(body)["reasons"] == reasons
        finally:
            telemetry.unregister_readiness(source)
        status, _ = _get(endpoint, "/readyz")
        assert status == 200

    def test_broken_source_fails_closed(self, endpoint):
        def broken():
            raise RuntimeError("probe bug")

        telemetry.register_readiness(broken)
        try:
            status, body = _get(endpoint, "/readyz")
            assert status == 503
            (reason,) = json.loads(body)["reasons"]
            assert reason["reason"] == "probe_error"
        finally:
            telemetry.unregister_readiness(broken)

    def test_statusz_snapshot(self, endpoint):
        breaker("StatuszMapper")  # registered, closed
        pressure.state("s.x").shrink(64, floor=1)
        flight.record("test.event", detail="statusz")
        key = telemetry.register_status("custom", lambda: {"k": "v"})
        try:
            status, body = _get(endpoint, "/statusz")
            assert status == 200
            st = json.loads(body)
            assert st["breakers"] == {"StatuszMapper": 0.0}
            assert st["pressure_caps"] == {"s.x": 32}
            assert st["uptime_s"] >= 0
            assert st["custom"] == {"k": "v"}
            assert any(e["kind"] == "test.event" for e in st["flight_tail"])
        finally:
            telemetry.unregister_status(key)

    def test_stop_is_idempotent_and_frees_the_port(self):
        server = TelemetryServer(port=0).start()
        port = server.port
        server.stop()
        server.stop()
        # the port is genuinely free: a new listener can take it
        server2 = TelemetryServer(port=port).start()
        try:
            assert server2.port == port
        finally:
            server2.stop()

    def test_module_singleton(self, monkeypatch):
        monkeypatch.setenv("FMT_TELEMETRY_PORT", "0")
        first = telemetry.start()
        assert first is not None and first.running
        assert telemetry.start() is first  # idempotent
        assert telemetry.active_server() is first
        telemetry.stop()
        assert telemetry.active_server() is None


def _tiny_model(n=256, dim=5, seed=0):
    from flink_ml_tpu.api.pipeline import Pipeline
    from flink_ml_tpu.lib import LogisticRegression
    from flink_ml_tpu.lib.feature import StandardScaler
    from flink_ml_tpu.table.schema import DataTypes, Schema
    from flink_ml_tpu.table.table import Table

    rng = np.random.RandomState(seed)
    X = rng.randn(n, dim).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float64)
    t = Table.from_columns(
        Schema.of(("features", DataTypes.DENSE_VECTOR),
                  ("label", "double")),
        {"features": X, "label": y},
    )
    model = Pipeline([
        StandardScaler().set_selected_col("features"),
        LogisticRegression().set_vector_col("features")
        .set_label_col("label").set_prediction_col("p")
        .set_learning_rate(0.5).set_max_iter(2),
    ]).fit(t)
    return model, t


class TestModelServerWiring:
    def test_no_telemetry_without_opt_in(self, monkeypatch):
        from flink_ml_tpu.serving import ModelServer

        monkeypatch.delenv("FMT_TELEMETRY_PORT", raising=False)
        model, table = _tiny_model()
        with ModelServer(model, max_wait_ms=1.0) as server:
            assert server.telemetry is None

    def test_lifecycle_scrape_status_and_teardown(self):
        from flink_ml_tpu.serving import ModelServer

        model, table = _tiny_model()
        server = ModelServer(model, version="v1", max_wait_ms=1.0,
                             telemetry_port=0)
        try:
            assert server.telemetry is not None and server.telemetry.port
            server.predict(table.slice_rows(0, 8), timeout=60)
            status, body = _get(server.telemetry, "/metrics")
            assert status == 200
            samples = parse_openmetrics(body)
            assert samples[
                family_name("serving.requests") + "_total"] >= 1
            status, body = _get(server.telemetry, "/statusz")
            st = json.loads(body)
            assert st["server"]["active_version"] == "v1"
            assert st["server"]["running"] is True
            assert "slo" in st  # the monitor came up with the server
            url = server.telemetry.url("/healthz")
        finally:
            server.shutdown()
        assert server.telemetry is None
        with pytest.raises((URLError, OSError)):
            urllib.request.urlopen(url, timeout=2)

    def test_env_port_arms_the_server(self, monkeypatch):
        from flink_ml_tpu.serving import ModelServer

        monkeypatch.setenv("FMT_TELEMETRY_PORT", "0")
        model, _ = _tiny_model()
        with ModelServer(model, max_wait_ms=1.0) as server:
            assert server.telemetry is not None
            status, _ = _get(server.telemetry, "/healthz")
            assert status == 200

    def test_readyz_queue_saturated_on_paused_server(self):
        from flink_ml_tpu.serving import ModelServer

        model, table = _tiny_model()
        server = ModelServer(model, max_batch=16, queue_cap=16,
                             max_wait_ms=1.0, telemetry_port=0,
                             start=False)
        try:
            futs = [server.submit(table.slice_rows(i * 8, (i + 1) * 8))
                    for i in range(2)]  # 16 of 16: saturated
            status, body = _get(server.telemetry, "/readyz")
            assert status == 503
            reasons = {r["reason"]
                       for r in json.loads(body)["reasons"]}
            assert "queue_saturated" in reasons
            server.start()
            for f in futs:
                f.result(60)
            status, _ = _get(server.telemetry, "/readyz")
            assert status == 200
        finally:
            server.shutdown()

    def test_readyz_deploy_in_progress(self):
        from flink_ml_tpu.serving import ModelServer

        model, table = _tiny_model()
        model2, _ = _tiny_model(seed=1)
        server = ModelServer(model, version="v1", max_wait_ms=1.0,
                             telemetry_port=0)
        in_deploy = threading.Event()
        release = threading.Event()
        observed = {}

        class GatedModel:
            """Stands in for a slow-warming deploy: transform blocks
            until the test has probed /readyz mid-deploy."""

            stages = model2.stages

            def transform(self, table):
                in_deploy.set()
                release.wait(30)
                return model2.transform(table)

        def deploy():
            server.deploy(GatedModel(), "v2",
                          warmup=table.slice_rows(0, 4))

        t = threading.Thread(target=deploy)
        try:
            t.start()
            assert in_deploy.wait(30)
            status, body = _get(server.telemetry, "/readyz")
            observed["status"], observed["body"] = status, body
        finally:
            release.set()
            t.join(30)
        assert observed["status"] == 503, observed
        reasons = {r["reason"]
                   for r in json.loads(observed["body"])["reasons"]}
        assert "deploy_in_progress" in reasons
        try:
            assert server.active_version == "v2"
            status, _ = _get(server.telemetry, "/readyz")
            assert status == 200
        finally:
            server.shutdown()

    def test_bind_conflict_warns_and_keeps_serving(self):
        from flink_ml_tpu.serving import ModelServer

        blocker = TelemetryServer(port=0).start()
        model, table = _tiny_model()
        try:
            with pytest.warns(RuntimeWarning, match="failed to bind"):
                server = ModelServer(model, max_wait_ms=1.0,
                                     telemetry_port=blocker.port)
            try:
                assert server.telemetry is None
                res = server.predict(table.slice_rows(0, 4), timeout=60)
                assert res.table.num_rows() == 4  # traffic unharmed
            finally:
                server.shutdown()
        finally:
            blocker.stop()


class TestSLOMonitor:
    def test_error_ratio_burn_math(self):
        mon = slo.SLOMonitor(window=60, err_ratio=0.01, p99_ms=0,
                             min_arrivals=5)
        obs.counter_add("serving.requests", 90)
        obs.counter_add("serving.shed", 10)
        res = mon.sample_once()
        verdict = res[slo.ERROR_SLO]
        # 10 bad of 100 arrivals against a 1% budget: 10x burn
        assert verdict["burning"] and verdict["burn_rate"] == \
            pytest.approx(10.0)
        assert verdict["bad"] == 10 and verdict["total"] == 100
        gauges = obs.registry().snapshot()["gauges"]
        assert gauges["slo.burning.shed_error_ratio"] == 1.0
        assert gauges["slo.burn_rate.shed_error_ratio"] == \
            pytest.approx(10.0)
        assert mon.burning() == {slo.ERROR_SLO: pytest.approx(10.0)}

    def test_latency_burn_judges_window_samples(self):
        mon = slo.SLOMonitor(window=60, err_ratio=0, p99_ms=5.0,
                             min_arrivals=10)
        for _ in range(18):
            obs.observe("serving.request_latency_ms", 1.0)
        for _ in range(2):
            obs.observe("serving.request_latency_ms", 50.0)
        res = mon.sample_once()
        verdict = res[slo.LATENCY_SLO]
        # 2 of 20 over target against the 1% p99 budget: 10x burn
        assert verdict["burning"] and verdict["burn_rate"] == \
            pytest.approx(10.0)
        # only NEW observations are judged next window
        for _ in range(20):
            obs.observe("serving.request_latency_ms", 1.0)
        res = mon.sample_once()
        assert not res[slo.LATENCY_SLO]["burning"]
        gauges = obs.registry().snapshot()["gauges"]
        assert gauges["slo.burning.serving_p99_ms"] == 0.0

    def test_small_windows_are_skipped_not_judged(self):
        mon = slo.SLOMonitor(window=60, err_ratio=0.01, p99_ms=0,
                             min_arrivals=10)
        obs.counter_add("serving.shed", 3)  # 3 arrivals, all shed
        assert mon.sample_once() == {}
        assert mon.burning() == {}

    def test_burning_slo_clears_on_a_quiet_window(self):
        """min_arrivals gates ENTERING a breach, never exiting: once
        /readyz degrades the balancer stops routing, so the quiet
        window that follows must clear the burn — not skip it and pin
        the replica unready forever."""
        mon = slo.SLOMonitor(window=60, err_ratio=0.01, p99_ms=5.0,
                             min_arrivals=10)
        obs.counter_add("serving.requests", 50)
        obs.counter_add("serving.shed", 50)
        for _ in range(10):
            obs.observe("serving.request_latency_ms", 50.0)
        mon.sample_once()
        assert set(mon.burning()) == {slo.ERROR_SLO, slo.LATENCY_SLO}
        # a sub-minimum window of CONTINUED bad traffic keeps the error
        # SLO burning; the latency SLO saw nothing this window and clears
        obs.counter_add("serving.shed", 3)
        res = mon.sample_once()
        assert res[slo.ERROR_SLO]["burning"]
        assert not res[slo.LATENCY_SLO]["burning"]
        assert set(mon.burning()) == {slo.ERROR_SLO}
        # the full drought window (zero arrivals): the error SLO recovers
        res = mon.sample_once()
        assert not res[slo.ERROR_SLO]["burning"]
        assert mon.burning() == {}
        gauges = obs.registry().snapshot()["gauges"]
        assert gauges["slo.burning.shed_error_ratio"] == 0.0
        assert gauges["slo.burning.serving_p99_ms"] == 0.0

    def test_disabled_targets_never_judge(self):
        mon = slo.SLOMonitor(window=60, err_ratio=0, p99_ms=0,
                             min_arrivals=1)
        assert not mon.armed()
        obs.counter_add("serving.shed", 100)
        assert mon.sample_once() == {}

    def test_breach_dumps_black_box_with_named_header(self, tmp_path,
                                                      monkeypatch):
        monkeypatch.setenv("FMT_FLIGHT_DIR", str(tmp_path))
        monkeypatch.setenv("FMT_FLIGHT_MIN_S", "30")
        flight.reset()
        flight.record("context.event")  # the ring has history to dump
        mon = slo.SLOMonitor(window=60, err_ratio=0.01, p99_ms=0,
                             min_arrivals=5)
        obs.counter_add("serving.requests", 50)
        obs.counter_add("serving.shed", 50)
        res = mon.sample_once()
        path = flight.last_dump_path()
        assert path and str(tmp_path) in path and "slo_breach" in path
        header = json.loads(open(path).readline())
        assert header["reason"] == "slo_breach"
        assert header["slo"] == slo.ERROR_SLO
        assert header["burn_rate"] == round(
            res[slo.ERROR_SLO]["burn_rate"], 4)
        # a second breach inside FMT_FLIGHT_MIN_S is rate-limited: the
        # breach is re-recorded in the ring but no new black box lands
        obs.counter_add("serving.requests", 50)
        obs.counter_add("serving.shed", 50)
        mon.sample_once()
        assert flight.last_dump_path() == path
        breaches = [e for e in flight.events()
                    if e["kind"] == "slo.breach"]
        assert len(breaches) == 2

    def test_recovery_records_and_clears(self):
        mon = slo.SLOMonitor(window=60, err_ratio=0.01, p99_ms=0,
                             min_arrivals=5)
        obs.counter_add("serving.requests", 50)
        obs.counter_add("serving.shed", 50)
        mon.sample_once()
        assert mon.burning()
        obs.counter_add("serving.requests", 10_000)
        res = mon.sample_once()
        assert not res[slo.ERROR_SLO]["burning"]
        assert mon.burning() == {}
        assert any(e["kind"] == "slo.recovered"
                   for e in flight.events())

    def test_registry_reset_between_samples_is_not_a_burn(self):
        mon = slo.SLOMonitor(window=60, err_ratio=0.5, p99_ms=0,
                             min_arrivals=5)
        obs.counter_add("serving.requests", 100)
        mon.sample_once()
        obs.reset()  # totals shrink: deltas must re-anchor, not go negative
        obs.counter_add("serving.requests", 20)
        res = mon.sample_once()
        assert not res[slo.ERROR_SLO]["burning"]

    def test_burning_slo_feeds_readyz(self, endpoint):
        mon = slo.SLOMonitor(window=60, err_ratio=0.01, p99_ms=0,
                             min_arrivals=5).start()
        try:
            obs.counter_add("serving.requests", 50)
            obs.counter_add("serving.shed", 50)
            mon.sample_once()
            status, body = _get(endpoint, "/readyz")
            assert status == 503
            (reason,) = json.loads(body)["reasons"]
            assert reason["reason"] == "slo_burning"
            assert slo.ERROR_SLO in reason["detail"]
        finally:
            mon.stop()
        status, _ = _get(endpoint, "/readyz")
        assert status == 200  # stop() unplugs the readiness source

    def test_sampling_thread_runs_and_stops(self):
        mon = slo.SLOMonitor(window=0.02, err_ratio=0.01, p99_ms=0,
                             min_arrivals=5).start()
        try:
            obs.counter_add("serving.requests", 50)
            obs.counter_add("serving.shed", 50)
            deadline = threading.Event()
            for _ in range(100):
                if mon.burning():
                    break
                deadline.wait(0.02)
            assert mon.burning(), "the sampler thread never judged"
        finally:
            mon.stop()
        assert mon._thread is None


class TestFlightDumpExtra:
    def test_extra_fields_land_in_header(self, tmp_path):
        flight.record("some.event")
        path = flight.dump("unit_test", directory=str(tmp_path),
                           force=True, extra={"slo": "x",
                                              "burn_rate": 2.5})
        header = json.loads(open(path).readline())
        assert header["slo"] == "x" and header["burn_rate"] == 2.5
        assert header["reason"] == "unit_test"

    def test_extra_never_overrides_core_fields(self, tmp_path):
        flight.record("some.event")
        path = flight.dump("unit_test", directory=str(tmp_path),
                           force=True, extra={"reason": "spoofed"})
        header = json.loads(open(path).readline())
        assert header["reason"] == "unit_test"

    def test_extra_is_redacted(self, tmp_path):
        flight.record("some.event")
        path = flight.dump("unit_test", directory=str(tmp_path),
                           force=True, extra={"api_key": "sk-123"})
        header = json.loads(open(path).readline())
        assert header["api_key"] == "<redacted>"
