"""Native ingestion parity: the C++ CSV/libsvm readers must agree exactly
with the pure-Python fallbacks through the real table sources."""

import os

import numpy as np
import pytest

from flink_ml_tpu import native
from flink_ml_tpu.table.schema import Schema
from flink_ml_tpu.table.sources import CsvSource, LibSvmSource

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library not built"
)


@pytest.fixture
def csv_file(tmp_path):
    p = tmp_path / "data.csv"
    p.write_text(
        'x,y,name\n'
        '1.5,2,"alpha, ""quoted"""\n'
        '-3.25,4,beta\n'
        '0,0,\n'
    )
    return str(p)


@pytest.fixture
def libsvm_file(tmp_path):
    p = tmp_path / "data.svm"
    p.write_text(
        "1 1:0.5 3:2.0 7:1.25\n"
        "0 2:-1.5  # inline comment\n"
        "\n"
        "1 1:3.0 7:-0.5\n"
    )
    return str(p)


def _python_fallback(fn):
    """Run fn with the native path disabled (fresh binding state)."""
    os.environ["FLINK_ML_TPU_NO_NATIVE"] = "1"
    # reset the lazy-loader state so the env var takes effect
    native._tried, saved = False, native._lib
    native._lib = None
    try:
        return fn()
    finally:
        del os.environ["FLINK_ML_TPU_NO_NATIVE"]
        native._tried = True
        native._lib = saved


class TestCsvParity:
    def test_rows_match_python(self, csv_file):
        schema = Schema.of(("x", "double"), ("y", "long"), ("name", "string"))
        src = CsvSource(csv_file, schema, skip_header=True)
        native_rows = src.read().to_rows()
        python_rows = _python_fallback(lambda: src.read().to_rows())
        assert len(native_rows) == len(python_rows) == 3
        for a, b in zip(native_rows, python_rows):
            assert a == b
        assert native_rows[0][2] == 'alpha, "quoted"'

    def test_arity_mismatch_raises(self, tmp_path):
        p = tmp_path / "bad.csv"
        p.write_text("1,2\n3\n")
        schema = Schema.of(("x", "double"), ("y", "double"))
        with pytest.raises(ValueError, match="fields"):
            CsvSource(str(p), schema).read()


class TestLibSvmParity:
    def test_rows_match_python(self, libsvm_file):
        src = LibSvmSource(libsvm_file)
        t_native = src.read()
        t_python = _python_fallback(lambda: src.read())
        np.testing.assert_array_equal(t_native.col("label"), t_python.col("label"))
        for a, b in zip(t_native.col("features"), t_python.col("features")):
            assert a.size() == b.size()
            np.testing.assert_array_equal(a.indices, b.indices)
            np.testing.assert_allclose(a.vals, b.vals)

    def test_values(self, libsvm_file):
        t = LibSvmSource(libsvm_file).read()
        assert t.num_rows() == 3
        v0 = t.col("features")[0]
        assert list(v0.indices) == [0, 2, 6]
        np.testing.assert_allclose(v0.vals, [0.5, 2.0, 1.25])
        assert v0.size() == 7  # max index + 1, 1-based input

    def test_n_features_pins_dim(self, libsvm_file):
        t = LibSvmSource(libsvm_file, n_features=100).read()
        assert t.col("features")[0].size() == 100

    def test_malformed_raises(self, tmp_path):
        p = tmp_path / "bad.svm"
        p.write_text("1 notanindex:2\n")
        with pytest.raises(ValueError):
            LibSvmSource(str(p)).read()


class TestControlByteFallback:
    def test_quoted_control_bytes_fall_back_to_python(self, tmp_path):
        """A 0x1F byte inside a quoted cell is legal CSV; the native
        transport can't represent it, so the source must fall back."""
        p = tmp_path / "ctl.csv"
        p.write_bytes(b'x,name\n1.5,"a\x1fb"\n')
        schema = Schema.of(("x", "double"), ("name", "string"))
        rows = CsvSource(str(p), schema, skip_header=True).read().to_rows()
        assert rows == [(1.5, "a\x1fb")]
        assert native.read_csv(str(p), ",", False, 2) is None
