"""Native ingestion parity: the C++ CSV/libsvm readers must agree exactly
with the pure-Python fallbacks through the real table sources."""

import os

import numpy as np
import pytest

from flink_ml_tpu import native
from flink_ml_tpu.table.schema import Schema
from flink_ml_tpu.table.sources import CsvSource, LibSvmSource

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library not built"
)


@pytest.fixture
def csv_file(tmp_path):
    p = tmp_path / "data.csv"
    p.write_text(
        'x,y,name\n'
        '1.5,2,"alpha, ""quoted"""\n'
        '-3.25,4,beta\n'
        '0,0,\n'
    )
    return str(p)


@pytest.fixture
def libsvm_file(tmp_path):
    p = tmp_path / "data.svm"
    p.write_text(
        "1 1:0.5 3:2.0 7:1.25\n"
        "0 2:-1.5  # inline comment\n"
        "\n"
        "1 1:3.0 7:-0.5\n"
    )
    return str(p)


def _python_fallback(fn):
    """Run fn with the native path disabled (fresh binding state)."""
    os.environ["FLINK_ML_TPU_NO_NATIVE"] = "1"
    # reset the lazy-loader state so the env var takes effect
    native._tried, saved = False, native._lib
    native._lib = None
    try:
        return fn()
    finally:
        del os.environ["FLINK_ML_TPU_NO_NATIVE"]
        native._tried = True
        native._lib = saved


class TestCsvParity:
    def test_rows_match_python(self, csv_file):
        schema = Schema.of(("x", "double"), ("y", "long"), ("name", "string"))
        src = CsvSource(csv_file, schema, skip_header=True)
        native_rows = src.read().to_rows()
        python_rows = _python_fallback(lambda: src.read().to_rows())
        assert len(native_rows) == len(python_rows) == 3
        for a, b in zip(native_rows, python_rows):
            assert a == b
        assert native_rows[0][2] == 'alpha, "quoted"'

    def test_arity_mismatch_raises(self, tmp_path):
        p = tmp_path / "bad.csv"
        p.write_text("1,2\n3\n")
        schema = Schema.of(("x", "double"), ("y", "double"))
        with pytest.raises(ValueError, match="fields"):
            CsvSource(str(p), schema).read()


class TestLibSvmParity:
    def test_rows_match_python(self, libsvm_file):
        src = LibSvmSource(libsvm_file)
        t_native = src.read()
        t_python = _python_fallback(lambda: src.read())
        np.testing.assert_array_equal(t_native.col("label"), t_python.col("label"))
        for a, b in zip(t_native.col("features"), t_python.col("features")):
            assert a.size() == b.size()
            np.testing.assert_array_equal(a.indices, b.indices)
            np.testing.assert_allclose(a.vals, b.vals)

    def test_values(self, libsvm_file):
        t = LibSvmSource(libsvm_file).read()
        assert t.num_rows() == 3
        v0 = t.col("features")[0]
        assert list(v0.indices) == [0, 2, 6]
        np.testing.assert_allclose(v0.vals, [0.5, 2.0, 1.25])
        assert v0.size() == 7  # max index + 1, 1-based input

    def test_n_features_pins_dim(self, libsvm_file):
        t = LibSvmSource(libsvm_file, n_features=100).read()
        assert t.col("features")[0].size() == 100

    def test_malformed_raises(self, tmp_path):
        p = tmp_path / "bad.svm"
        p.write_text("1 notanindex:2\n")
        with pytest.raises(ValueError):
            LibSvmSource(str(p)).read()


class TestControlByteFallback:
    def test_quoted_control_bytes_fall_back_to_python(self, tmp_path):
        """A 0x1F byte inside a quoted cell is legal CSV; the native
        transport can't represent it, so the source must fall back."""
        p = tmp_path / "ctl.csv"
        p.write_bytes(b'x,name\n1.5,"a\x1fb"\n')
        schema = Schema.of(("x", "double"), ("name", "string"))
        rows = CsvSource(str(p), schema, skip_header=True).read().to_rows()
        assert rows == [(1.5, "a\x1fb")]
        assert native.read_csv(str(p), ",", False, 2) is None


class TestNativeChunkedReaders:
    """The streaming handles must deliver the same rows in the same order
    as read(), in bounded chunks."""

    def test_csv_doubles_chunks_match_read(self, tmp_path):
        rng = np.random.RandomState(0)
        data = rng.randn(997, 4)
        data[5, 2] = np.nan
        path = tmp_path / "n.csv"
        np.savetxt(path, data, delimiter=",", fmt="%.17g")
        schema = Schema.of(*[(f"c{i}", "double") for i in range(4)])
        src = CsvSource(str(path), schema)
        whole = src.read()
        chunks = list(src.read_chunks(100))
        assert all(c.num_rows() <= 100 for c in chunks)
        assert sum(c.num_rows() for c in chunks) == 997
        streamed = np.concatenate(
            [np.stack([c.col(f"c{i}") for i in range(4)], axis=1) for c in chunks]
        )
        ref = np.stack([whole.col(f"c{i}") for i in range(4)], axis=1)
        np.testing.assert_array_equal(streamed, ref)

    def test_csv_quoted_crlf_header(self, tmp_path):
        path = tmp_path / "q.csv"
        path.write_bytes(b'a,b\r\n"1.5",2\r\n"-2.25",\r\n3,4\r\n')
        schema = Schema.of(("a", "double"), ("b", "double"))
        chunks = list(CsvSource(str(path), schema, skip_header=True).read_chunks(2))
        got = np.concatenate(
            [np.stack([c.col("a"), c.col("b")], axis=1) for c in chunks]
        )
        np.testing.assert_array_equal(
            got, [[1.5, 2.0], [-2.25, np.nan], [3.0, 4.0]]
        )

    def test_csv_fallback_resumes_pure_parser(self, tmp_path, monkeypatch):
        """A cell the native strtod rejects but Python's float() accepts
        ('1_000') triggers mid-stream fallback with no row lost or doubled."""
        path = tmp_path / "f.csv"
        lines = [f"{i},{i * 2}" for i in range(50)]
        lines[30] = "1_000,60"
        path.write_text("\n".join(lines) + "\n")
        schema = Schema.of(("a", "double"), ("b", "double"))
        chunks = list(CsvSource(str(path), schema).read_chunks(7))
        a = np.concatenate([np.asarray(c.col("a")) for c in chunks])
        expected = np.arange(50.0)
        expected[30] = 1000.0
        np.testing.assert_array_equal(a, expected)

    def test_libsvm_chunks_match_read(self, tmp_path):
        rng = np.random.RandomState(1)
        path = tmp_path / "n.svm"
        with open(path, "w") as f:
            for i in range(333):
                idx = np.sort(rng.choice(50, 4, replace=False))
                pairs = " ".join(f"{j + 1}:{rng.randn():.9g}" for j in idx)
                f.write(f"{i % 2} {pairs}\n")
        src = LibSvmSource(str(path), n_features=50)
        whole = src.read()
        chunks = list(src.read_chunks(64))
        assert sum(c.num_rows() for c in chunks) == 333
        assert all(c.num_rows() <= 64 for c in chunks)
        whole_rows = whole.to_rows()
        streamed_rows = [r for c in chunks for r in c.to_rows()]
        assert len(whole_rows) == len(streamed_rows)
        for (l1, v1), (l2, v2) in zip(whole_rows, streamed_rows):
            assert l1 == l2
            np.testing.assert_array_equal(v1.indices, v2.indices)
            np.testing.assert_array_equal(v1.vals, v2.vals)

    def test_python_fallback_forced_matches_native(self, tmp_path, monkeypatch):
        rng = np.random.RandomState(2)
        data = rng.randn(200, 3)
        path = tmp_path / "p.csv"
        np.savetxt(path, data, delimiter=",", fmt="%.17g")
        schema = Schema.of(*[(f"c{i}", "double") for i in range(3)])
        native_chunks = list(CsvSource(str(path), schema).read_chunks(33))
        monkeypatch.setenv("FLINK_ML_TPU_NO_NATIVE", "1")
        monkeypatch.setattr(native, "_tried", False)
        monkeypatch.setattr(native, "_lib", None)
        pure_chunks = list(CsvSource(str(path), schema).read_chunks(33))
        monkeypatch.setattr(native, "_tried", False)
        monkeypatch.setattr(native, "_lib", None)
        assert len(native_chunks) == len(pure_chunks)
        for cn, cp in zip(native_chunks, pure_chunks):
            for c in schema.field_names:
                np.testing.assert_array_equal(
                    np.asarray(cn.col(c)), np.asarray(cp.col(c))
                )

    def test_hex_and_nan_payload_route_to_fallback_error(self, tmp_path):
        """strtod-only forms (hex floats, nan(payload)) must not silently
        parse: the stream falls back to the pure parser, which raises the
        same error read() raises."""
        path = tmp_path / "h.csv"
        path.write_text("1.0,2.0\n0x10,3.0\n")
        schema = Schema.of(("a", "double"), ("b", "double"))
        src = CsvSource(str(path), schema)
        with pytest.raises(ValueError):
            src.read()
        with pytest.raises(ValueError):
            list(src.read_chunks(10))

    def test_blank_first_line_consumed_as_header(self, tmp_path):
        """Pure csv.reader treats physical row 0 as the header even when
        blank; the native stream must match (same rows, same errors)."""
        path = tmp_path / "bh.csv"
        path.write_bytes(b"\na,b\n1,2\n")
        schema = Schema.of(("a", "double"), ("b", "double"))
        src = CsvSource(str(path), schema, skip_header=True)
        with pytest.raises(ValueError):
            src.read()  # 'a' is a data row once the blank header is skipped
        with pytest.raises(ValueError):
            list(src.read_chunks(10))

    def test_out_of_range_index_raises_like_pure_path(self, tmp_path):
        path = tmp_path / "oor.svm"
        path.write_text("1 7:2.0\n")
        src = LibSvmSource(str(path), n_features=3)
        with pytest.raises(ValueError, match="out of range|declared size"):
            list(src.read_chunks(10))

    def test_stream_generators_free_eof_buffers(self, tmp_path):
        """Exhausting the streams must not leak the EOF call's buffers
        (smoke: run many iterations; correctness asserted by valgrind-less
        proxy — the wrappers call fml_free on the n==0 path)."""
        path = tmp_path / "t.csv"
        path.write_text("1.0,2.0\n")
        schema = Schema.of(("a", "double"), ("b", "double"))
        for _ in range(50):
            assert sum(c.num_rows() for c in CsvSource(str(path), schema).read_chunks(4)) == 1
