"""Feature-dimension (model-axis) sharding: sparse training over a
('data','model') mesh must match the 1-D data-parallel result exactly."""

import jax
import jax.numpy as jnp
import numpy as np

from flink_ml_tpu.lib.common import (
    pack_sparse_minibatches,
    train_glm_sparse,
)
from flink_ml_tpu.ops.vector import SparseVector
from flink_ml_tpu.parallel.mesh import create_mesh, default_mesh


def sparse_rows(n=200, dim=24, nnz=4, seed=0):
    rng = np.random.RandomState(seed)
    true_w = rng.randn(dim)
    vecs, ys = [], []
    for _ in range(n):
        idx = np.sort(rng.choice(dim, nnz, replace=False))
        val = rng.randn(nnz)
        x = np.zeros(dim)
        x[idx] = val
        vecs.append(SparseVector(dim, idx.astype(np.int64), val))
        ys.append(float((x @ true_w) > 0))
    return vecs, np.asarray(ys)


def train(mesh, n_dev_data, kind="logistic", max_iter=20, dim=None, vecs=None, ys=None):
    sstack = pack_sparse_minibatches(vecs, ys, n_dev_data, global_batch_size=64, dim=dim)
    w0 = jnp.zeros((sstack.dim,), jnp.float32)
    b0 = jnp.zeros((), jnp.float32)
    return train_glm_sparse(
        (w0, b0), sstack, kind, mesh,
        learning_rate=0.5, max_iter=max_iter,
    )


class TestFeatureSharding:
    def test_2d_matches_1d(self):
        vecs, ys = sparse_rows()
        r1 = train(default_mesh(), 8, vecs=vecs, ys=ys)
        mesh2 = create_mesh({"data": 2, "model": 4})
        r2 = train(mesh2, 2, vecs=vecs, ys=ys)
        # different data-sharding changes minibatch grouping; use the same
        # grouping for an exact check: data axis 2 in both runs
        mesh1x2 = create_mesh({"data": 2, "model": 1}, devices=jax.devices()[:2])
        r1b = train(mesh1x2, 2, vecs=vecs, ys=ys)
        np.testing.assert_allclose(r2.params[0], r1b.params[0], rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(r2.params[1], r1b.params[1], rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(r2.losses, r1b.losses, rtol=1e-5)
        assert r1.epochs == r2.epochs == 20

    def test_dim_padding_to_model_axis(self):
        # dim=25 not divisible by model=4 -> padded internally, result trimmed
        vecs, ys = sparse_rows(dim=25)
        mesh2 = create_mesh({"data": 2, "model": 4})
        r = train(mesh2, 2, dim=25, vecs=vecs, ys=ys)
        assert r.params[0].shape == (25,)

    def test_squared_loss_2d(self):
        rng = np.random.RandomState(1)
        dim = 16
        true_w = rng.randn(dim)
        vecs, ys = [], []
        for _ in range(160):
            idx = np.sort(rng.choice(dim, 3, replace=False))
            val = rng.randn(3)
            x = np.zeros(dim)
            x[idx] = val
            vecs.append(SparseVector(dim, idx.astype(np.int64), val))
            ys.append(x @ true_w)
        ys = np.asarray(ys)
        mesh2 = create_mesh({"data": 4, "model": 2})
        r2 = train(mesh2, 4, kind="squared", max_iter=200, vecs=vecs, ys=ys)
        mesh1 = create_mesh({"data": 4, "model": 1}, devices=jax.devices()[:4])
        r1 = train(mesh1, 4, kind="squared", max_iter=200, vecs=vecs, ys=ys)
        np.testing.assert_allclose(r2.params[0], r1.params[0], rtol=1e-4, atol=1e-5)
