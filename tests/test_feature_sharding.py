"""Feature-dimension (model-axis) sharding: sparse AND dense training over a
('data','model') mesh must match the 1-D data-parallel result."""

import jax
import jax.numpy as jnp
import numpy as np

from flink_ml_tpu.lib.common import (
    pack_minibatches,
    pack_sparse_minibatches,
    train_glm,
    train_glm_dense_2d,
    train_glm_sparse,
)
from flink_ml_tpu.ops.vector import SparseVector
from flink_ml_tpu.parallel.mesh import create_mesh, default_mesh


def sparse_rows(n=200, dim=24, nnz=4, seed=0):
    rng = np.random.RandomState(seed)
    true_w = rng.randn(dim)
    vecs, ys = [], []
    for _ in range(n):
        idx = np.sort(rng.choice(dim, nnz, replace=False))
        val = rng.randn(nnz)
        x = np.zeros(dim)
        x[idx] = val
        vecs.append(SparseVector(dim, idx.astype(np.int64), val))
        ys.append(float((x @ true_w) > 0))
    return vecs, np.asarray(ys)


def train(mesh, n_dev_data, kind="logistic", max_iter=20, dim=None, vecs=None, ys=None):
    sstack = pack_sparse_minibatches(vecs, ys, n_dev_data, global_batch_size=64, dim=dim)
    w0 = jnp.zeros((sstack.dim,), jnp.float32)
    b0 = jnp.zeros((), jnp.float32)
    return train_glm_sparse(
        (w0, b0), sstack, kind, mesh,
        learning_rate=0.5, max_iter=max_iter,
    )


class TestFeatureSharding:
    def test_2d_matches_1d(self):
        vecs, ys = sparse_rows()
        r1 = train(default_mesh(), 8, vecs=vecs, ys=ys)
        mesh2 = create_mesh({"data": 2, "model": 4})
        r2 = train(mesh2, 2, vecs=vecs, ys=ys)
        # different data-sharding changes minibatch grouping; use the same
        # grouping for an exact check: data axis 2 in both runs
        mesh1x2 = create_mesh({"data": 2, "model": 1}, devices=jax.devices()[:2])
        r1b = train(mesh1x2, 2, vecs=vecs, ys=ys)
        np.testing.assert_allclose(r2.params[0], r1b.params[0], rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(r2.params[1], r1b.params[1], rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(r2.losses, r1b.losses, rtol=1e-5)
        assert r1.epochs == r2.epochs == 20

    def test_dim_padding_to_model_axis(self):
        # dim=25 not divisible by model=4 -> padded internally, result trimmed
        vecs, ys = sparse_rows(dim=25)
        mesh2 = create_mesh({"data": 2, "model": 4})
        r = train(mesh2, 2, dim=25, vecs=vecs, ys=ys)
        assert r.params[0].shape == (25,)

    def test_dense_2d_matches_1d(self):
        """VERDICT r3 item 5: the dense feature-sharded fused path against
        the replicated fused path at identical minibatch grouping.  The two
        differ only in contraction grouping (per-shard partial matvecs +
        psum vs one full-width matvec), so agreement is ulp-level f32, not
        bitwise."""
        from flink_ml_tpu.lib.classification import _log_loss_grads

        rng = np.random.RandomState(3)
        n, d = 256, 24
        X = rng.randn(n, d)
        ys = (X @ rng.randn(d) > 0).astype(np.float64)
        stack = pack_minibatches(X, ys, 2, global_batch_size=64)
        w0 = jnp.zeros((d,), jnp.float32)
        b0 = jnp.zeros((), jnp.float32)

        mesh2d = create_mesh({"data": 2, "model": 4})
        r2 = train_glm_dense_2d(
            (w0, b0), stack, "logistic", mesh2d,
            learning_rate=0.5, max_iter=20,
        )
        mesh1d = create_mesh({"data": 2, "model": 1}, devices=jax.devices()[:2])
        r1 = train_glm(
            (w0, b0), stack, _log_loss_grads(True), mesh1d,
            learning_rate=0.5, max_iter=20,
        )
        np.testing.assert_allclose(r2.params[0], r1.params[0], rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(r2.params[1], r1.params[1], rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(r2.losses, r1.losses, rtol=1e-5)
        assert r2.epochs == r1.epochs == 20

    def test_dense_2d_dim_padding(self):
        rng = np.random.RandomState(4)
        n, d = 128, 13  # not divisible by model=4 -> padded, trimmed back
        X = rng.randn(n, d)
        ys = (X @ rng.randn(d) > 0).astype(np.float64)
        stack = pack_minibatches(X, ys, 2, global_batch_size=32)
        r = train_glm_dense_2d(
            (jnp.zeros((d,), jnp.float32), jnp.zeros((), jnp.float32)),
            stack, "logistic", create_mesh({"data": 2, "model": 4}),
            learning_rate=0.5, max_iter=10,
        )
        assert r.params[0].shape == (d,)
        assert np.all(np.isfinite(r.params[0]))

    def test_dense_2d_checkpoint_resume(self, tmp_path):
        from flink_ml_tpu.iteration.checkpoint import CheckpointConfig

        rng = np.random.RandomState(5)
        X = rng.randn(128, 16)
        ys = (X @ rng.randn(16) > 0).astype(np.float64)
        stack = pack_minibatches(X, ys, 2, global_batch_size=32)
        mesh = create_mesh({"data": 2, "model": 4})
        p0 = (jnp.zeros((16,), jnp.float32), jnp.zeros((), jnp.float32))

        full = train_glm_dense_2d(
            (jnp.copy(p0[0]), jnp.copy(p0[1])), stack, "logistic", mesh,
            learning_rate=0.5, max_iter=12,
        )
        cfg = CheckpointConfig(directory=str(tmp_path / "ck"), every_n_epochs=5)
        chunked = train_glm_dense_2d(
            (jnp.copy(p0[0]), jnp.copy(p0[1])), stack, "logistic", mesh,
            learning_rate=0.5, max_iter=12, checkpoint=cfg,
        )
        np.testing.assert_allclose(chunked.params[0], full.params[0],
                                   rtol=1e-6, atol=1e-7)
        assert chunked.epochs == full.epochs == 12

    def test_estimator_routes_dense_2d(self):
        """LogisticRegression.fit under a ('data','model') env mesh takes the
        feature-sharded path and matches the replicated fit."""
        from flink_ml_tpu.lib import LogisticRegression
        from flink_ml_tpu.table.schema import DataTypes, Schema
        from flink_ml_tpu.table.table import Table
        from flink_ml_tpu.utils.environment import MLEnvironmentFactory

        rng = np.random.RandomState(6)
        X = rng.randn(300, 20)
        ys = (X @ rng.randn(20) > 0).astype(np.float64)
        schema = Schema.of(("features", DataTypes.DENSE_VECTOR), ("label", "double"))
        t = Table.from_columns(schema, {"features": X, "label": ys})

        def fit(mesh):
            env = MLEnvironmentFactory.get_default()
            old = env.get_mesh()
            env.set_mesh(mesh)
            try:
                model = (
                    LogisticRegression().set_vector_col("features")
                    .set_label_col("label").set_prediction_col("pred")
                    .set_learning_rate(0.5).set_max_iter(15)
                    .set_global_batch_size(64).fit(t)
                )
                (mt,) = model.get_model_data()
                return np.asarray(mt.col("coefficients")[0].to_dense().values)
            finally:
                env.set_mesh(old)

        w2d = fit(create_mesh({"data": 2, "model": 4}))
        w1d = fit(create_mesh({"data": 2, "model": 1}, devices=jax.devices()[:2]))
        np.testing.assert_allclose(w2d, w1d, rtol=1e-5, atol=1e-6)

    def test_squared_loss_2d(self):
        rng = np.random.RandomState(1)
        dim = 16
        true_w = rng.randn(dim)
        vecs, ys = [], []
        for _ in range(160):
            idx = np.sort(rng.choice(dim, 3, replace=False))
            val = rng.randn(3)
            x = np.zeros(dim)
            x[idx] = val
            vecs.append(SparseVector(dim, idx.astype(np.int64), val))
            ys.append(x @ true_w)
        ys = np.asarray(ys)
        mesh2 = create_mesh({"data": 4, "model": 2})
        r2 = train(mesh2, 4, kind="squared", max_iter=200, vecs=vecs, ys=ys)
        mesh1 = create_mesh({"data": 4, "model": 1}, devices=jax.devices()[:4])
        r1 = train(mesh1, 4, kind="squared", max_iter=200, vecs=vecs, ys=ys)
        np.testing.assert_allclose(r2.params[0], r1.params[0], rtol=1e-4, atol=1e-5)
