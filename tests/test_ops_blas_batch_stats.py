"""BLAS-surface, batch-tier, and statistics tests — parity with BLASTest.java
(golden values + size-check failures) and MultivariateGaussianTest.java
(incl. the degenerate singular-covariance case), plus CsrBatch device math
checked against dense references."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from flink_ml_tpu.ops import (
    CsrBatch,
    DenseMatrix,
    DenseVector,
    MultivariateGaussian,
    SparseVector,
    blas,
    dense_batch,
)


class TestBlas:
    def test_asum_axpy_scal_dot(self):
        x = DenseVector([1, -2, 3])
        assert blas.asum(x) == 6.0
        y = DenseVector([1, 1, 1])
        blas.axpy(2.0, x, y)
        assert y.values.tolist() == [3, -3, 7]
        blas.scal(0.5, y)
        assert y.values.tolist() == [1.5, -1.5, 3.5]
        assert blas.dot(DenseVector([1, 2]), DenseVector([3, 4])) == 11.0

    def test_sparse_axpy_dot(self):
        y = DenseVector([0, 0, 0])
        blas.axpy(3.0, SparseVector(3, [1], [2.0]), y)
        assert y.values.tolist() == [0, 6, 0]
        assert blas.dot(SparseVector(3, [2], [2.0]), DenseVector([1, 1, 5])) == 10.0

    def test_gemm_golden(self):
        a = DenseMatrix([[1, 2], [3, 4]])
        b = DenseMatrix([[5, 6], [7, 8]])
        c = DenseMatrix([[1, 1], [1, 1]])
        blas.gemm(1.0, a, False, b, False, 1.0, c)
        assert c.data.tolist() == [[20, 23], [44, 51]]

    def test_gemm_transposes(self):
        a = DenseMatrix([[1, 2, 3], [4, 5, 6]])  # 2x3
        b = DenseMatrix([[1, 0], [0, 1], [1, 1]])  # 3x2
        c = DenseMatrix.zeros(3, 3)
        blas.gemm(1.0, a, True, b, True, 0.0, c)  # (3x2)@(2x3)
        expect = a.data.T @ b.data.T
        assert np.allclose(c.data, expect)

    def test_gemm_size_check(self):
        with pytest.raises(ValueError):
            blas.gemm(1.0, DenseMatrix.ones(2, 3), False, DenseMatrix.ones(2, 3), False,
                      0.0, DenseMatrix.zeros(2, 3))

    def test_gemv_dense_sparse(self):
        a = DenseMatrix([[1, 2, 3], [4, 5, 6]])
        y = DenseVector([1, 1])
        blas.gemv(1.0, a, False, DenseVector([1, 0, 1]), 2.0, y)
        assert y.values.tolist() == [6, 12]
        y2 = DenseVector.zeros(2)
        blas.gemv(1.0, a, False, SparseVector(3, [0, 2], [1.0, 1.0]), 0.0, y2)
        assert y2.values.tolist() == [4, 10]

    def test_gemv_transpose(self):
        a = DenseMatrix([[1, 2], [3, 4], [5, 6]])
        y = DenseVector.zeros(2)
        blas.gemv(1.0, a, True, DenseVector([1, 1, 1]), 0.0, y)
        assert y.values.tolist() == [9, 12]

    def test_gemv_size_check(self):
        with pytest.raises(ValueError):
            blas.gemv(1.0, DenseMatrix.ones(2, 3), False, DenseVector([1, 1]), 0.0,
                      DenseVector.zeros(2))


class TestBatchTier:
    def test_dense_batch_packs_mixed_rows(self):
        rows = [DenseVector([1, 2, 0]), SparseVector(3, [2], [5.0])]
        b = dense_batch(rows)
        assert b.tolist() == [[1, 2, 0], [0, 0, 5]]

    def _random_csr(self, rng, n_rows=16, n_cols=32, density=0.2):
        vecs = []
        for _ in range(n_rows):
            nnz = max(1, int(density * n_cols))
            idx = rng.choice(n_cols, size=nnz, replace=False)
            vecs.append(SparseVector(n_cols, idx, rng.standard_normal(nnz)))
        return vecs

    def test_csr_matvec_matches_dense(self):
        rng = np.random.default_rng(0)
        vecs = self._random_csr(rng)
        batch = CsrBatch.from_vectors(vecs, n_cols=32, pad_multiple=64)
        dense = dense_batch(vecs, 32)
        w = rng.standard_normal(32)
        np.testing.assert_allclose(np.asarray(batch.matvec(jnp.asarray(w, jnp.float32))),
                                   dense @ w, rtol=1e-4)

    def test_csr_matmul_rmatvec_match_dense(self):
        rng = np.random.default_rng(1)
        vecs = self._random_csr(rng, n_rows=8, n_cols=16)
        batch = CsrBatch.from_vectors(vecs, n_cols=16, pad_multiple=32)
        dense = dense_batch(vecs, 16)
        w = rng.standard_normal((16, 4))
        np.testing.assert_allclose(np.asarray(batch.matmul(jnp.asarray(w, jnp.float32))),
                                   dense @ w, rtol=1e-4)
        y = rng.standard_normal(8)
        np.testing.assert_allclose(np.asarray(batch.rmatvec(jnp.asarray(y, jnp.float32))),
                                   dense.T @ y, rtol=1e-4)

    def test_csr_to_dense_and_norms(self):
        vecs = [SparseVector(4, [0, 3], [1.0, 2.0]), SparseVector(4, [1], [3.0])]
        batch = CsrBatch.from_vectors(vecs, n_cols=4, pad_multiple=8)
        assert np.asarray(batch.to_dense()).tolist() == [[1, 0, 0, 2], [0, 3, 0, 0]]
        assert np.asarray(batch.row_norms_l2_square()).tolist() == [5.0, 9.0]

    def test_csr_is_jittable_pytree(self):
        vecs = [SparseVector(4, [1], [2.0]), SparseVector(4, [2], [3.0])]
        batch = CsrBatch.from_vectors(vecs, n_cols=4, pad_multiple=8)

        @jax.jit
        def f(b, w):
            return b.matvec(w)

        out = f(batch, jnp.ones(4, jnp.float32))
        assert np.asarray(out).tolist() == [2.0, 3.0]

    def test_pad_rows_contribute_nothing(self):
        # rmatvec must ignore pad slots even with non-trivial y
        vecs = [SparseVector(3, [0], [1.0])]
        batch = CsrBatch.from_vectors(vecs, n_cols=3, pad_multiple=16)
        out = np.asarray(batch.rmatvec(jnp.full((1,), 7.0, jnp.float32)))
        assert out.tolist() == [7.0, 0.0, 0.0]


class TestMultivariateGaussian:
    def test_pdf_matches_scipy_formula(self):
        mean = np.array([0.0, 0.0])
        cov = np.array([[2.0, 0.3], [0.3, 1.0]])
        g = MultivariateGaussian(mean, cov)
        x = np.array([0.5, -0.2])
        # closed form
        inv = np.linalg.inv(cov)
        expect = np.exp(-0.5 * x @ inv @ x) / (2 * np.pi * np.sqrt(np.linalg.det(cov)))
        assert np.isclose(g.pdf(DenseVector(x)), expect, rtol=1e-10)

    def test_degenerate_covariance_pseudo(self):
        # rank-1 covariance: density defined on the support via pseudo-determinant
        # (reference MultivariateGaussianTest degenerate case, tol 1e-5)
        mean = np.zeros(2)
        cov = np.array([[1.0, 1.0], [1.0, 1.0]])
        g = MultivariateGaussian(mean, cov)
        val = g.pdf(DenseVector([1.0, 1.0]))
        # reference keeps the full k in (2*pi)^(-k/2) and uses the pseudo-det (=2);
        # quadratic form along the support direction is 1
        expect = np.exp(-0.5 * 1.0) / (2 * np.pi * np.sqrt(2.0))
        assert np.isclose(val, expect, atol=1e-5)
        # off-support direction gets no penalty (pseudo-inverse null space)
        assert np.isclose(g.logpdf(DenseVector([1.0, -1.0])), g.logpdf(DenseVector([0.0, 0.0])))

    def test_batch_matches_single(self):
        rng = np.random.default_rng(2)
        mean = rng.standard_normal(3)
        a = rng.standard_normal((3, 3))
        g = MultivariateGaussian(mean, a @ a.T + np.eye(3))
        xs = rng.standard_normal((5, 3))
        singles = [g.logpdf(x) for x in xs]
        np.testing.assert_allclose(g.logpdf_batch(xs), singles, rtol=1e-12)
