"""Convergence + pipeline-integration tests for the GLM estimators.

Tier (4)/(5) of the translated test strategy (SURVEY.md §4): end-to-end fit
on fixed seeds with accuracy/parameter-recovery assertions, running psum-based
training on the virtual 8-device CPU mesh.
"""

import os

import numpy as np

from flink_ml_tpu.api.core import load_stage
from flink_ml_tpu.api.pipeline import Pipeline
from flink_ml_tpu.lib import (
    LinearRegression,
    LinearRegressionModel,
    LogisticRegression,
)
from flink_ml_tpu.ops.vector import DenseVector
from flink_ml_tpu.table.schema import DataTypes, Schema
from flink_ml_tpu.table.table import Table


def linreg_data(n=200, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, 3)
    true_w = np.array([2.0, -1.0, 0.5])
    y = X @ true_w + 3.0 + 0.01 * rng.randn(n)
    schema = Schema.of(
        ("f0", "double"), ("f1", "double"), ("f2", "double"), ("label", "double")
    )
    t = Table.from_columns(
        schema, {"f0": X[:, 0], "f1": X[:, 1], "f2": X[:, 2], "label": y}
    )
    return t, true_w


def logreg_data(n=400, seed=1):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, 4)
    true_w = np.array([1.5, -2.0, 1.0, 0.0])
    logits = X @ true_w - 0.5
    y = (logits + 0.3 * rng.randn(n) > 0).astype(np.float64)
    vectors = [DenseVector(row) for row in X]
    schema = Schema.of(("features", DataTypes.DENSE_VECTOR), ("label", "double"))
    return Table.from_columns(schema, {"features": vectors, "label": y})


class TestLinearRegression:
    def test_recovers_coefficients_full_batch(self):
        t, true_w = linreg_data()
        est = (
            LinearRegression()
            .set_feature_cols(["f0", "f1", "f2"])
            .set_label_col("label")
            .set_prediction_col("pred")
            .set_learning_rate(0.1)
            .set_max_iter(200)
        )
        model = est.fit(t)
        np.testing.assert_allclose(model.coefficients(), true_w, atol=0.05)
        assert abs(model.intercept() - 3.0) < 0.05

    def test_minibatch_sgd_converges(self):
        t, true_w = linreg_data()
        model = (
            LinearRegression()
            .set_feature_cols(["f0", "f1", "f2"])
            .set_label_col("label")
            .set_prediction_col("pred")
            .set_learning_rate(0.05)
            .set_global_batch_size(64)
            .set_max_iter(150)
            .fit(t)
        )
        np.testing.assert_allclose(model.coefficients(), true_w, atol=0.1)

    def test_transform_schema_and_values(self):
        t, _ = linreg_data(50)
        model = (
            LinearRegression()
            .set_feature_cols(["f0", "f1", "f2"])
            .set_label_col("label")
            .set_prediction_col("pred")
            .set_max_iter(100)
            .fit(t)
        )
        (out,) = model.transform(t)
        assert out.schema.field_names == ["f0", "f1", "f2", "label", "pred"]
        resid = np.asarray(out.col("pred")) - np.asarray(t.col("label"))
        assert np.sqrt(np.mean(resid**2)) < 0.2

    def test_tol_early_stop(self):
        t, _ = linreg_data()
        model = (
            LinearRegression()
            .set_feature_cols(["f0", "f1", "f2"])
            .set_label_col("label")
            .set_prediction_col("pred")
            .set_learning_rate(0.2)
            .set_max_iter(500)
            .set_tol(1e-6)
            .fit(t)
        )
        assert model.train_epochs_ < 500

    def test_save_load_roundtrip(self, tmp_path):
        t, _ = linreg_data(50)
        model = (
            LinearRegression()
            .set_feature_cols(["f0", "f1", "f2"])
            .set_label_col("label")
            .set_prediction_col("pred")
            .set_max_iter(50)
            .fit(t)
        )
        path = os.path.join(tmp_path, "lrm")
        model.save(path)
        loaded = load_stage(path)
        assert isinstance(loaded, LinearRegressionModel)
        np.testing.assert_allclose(loaded.coefficients(), model.coefficients())
        (out,) = loaded.transform(t)
        (orig,) = model.transform(t)
        np.testing.assert_allclose(out.col("pred"), orig.col("pred"))

    def test_no_intercept(self):
        t, true_w = linreg_data()
        model = (
            LinearRegression()
            .set_feature_cols(["f0", "f1", "f2"])
            .set_label_col("label")
            .set_prediction_col("pred")
            .set_with_intercept(False)
            .set_max_iter(100)
            .fit(t)
        )
        assert model.intercept() == 0.0


class TestLogisticRegression:
    def test_accuracy_on_separable_data(self):
        t = logreg_data()
        model = (
            LogisticRegression()
            .set_vector_col("features")
            .set_label_col("label")
            .set_prediction_col("pred")
            .set_prediction_detail_col("prob")
            .set_learning_rate(0.5)
            .set_max_iter(150)
            .fit(t)
        )
        (out,) = model.transform(t)
        acc = np.mean(np.asarray(out.col("pred")) == np.asarray(t.col("label")))
        assert acc > 0.93
        probs = np.asarray(out.col("prob"))
        assert np.all((probs >= 0) & (probs <= 1))
        # prob and hard label agree
        np.testing.assert_array_equal(probs > 0.5, np.asarray(out.col("pred")) == 1.0)

    def test_auc_parity_with_numpy_reference(self):
        """AUC of the device-trained model matches a plain-numpy full-batch GD
        implementation of the same optimization (the 'identical AUC' criterion
        of the north star, BASELINE.md)."""
        t = logreg_data(300, seed=7)
        lr, iters = 0.5, 120
        model = (
            LogisticRegression()
            .set_vector_col("features")
            .set_label_col("label")
            .set_prediction_col("pred")
            .set_learning_rate(lr)
            .set_max_iter(iters)
            .fit(t)
        )
        X = t.features_dense("features")
        y = np.asarray(t.col("label"), dtype=np.float64)

        w = np.zeros(4)
        b = 0.0
        for _ in range(iters):
            p = 1 / (1 + np.exp(-(X @ w + b)))
            err = p - y
            w -= lr * (X.T @ err) / len(y)
            b -= lr * err.sum() / len(y)

        def auc(scores):
            order = np.argsort(scores)
            ranks = np.empty(len(scores))
            ranks[order] = np.arange(1, len(scores) + 1)
            pos = y == 1
            n_pos, n_neg = pos.sum(), (~pos).sum()
            return (ranks[pos].sum() - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg)

        auc_np = auc(X @ w + b)
        auc_tpu = auc(model.predict_proba(t))
        assert abs(auc_np - auc_tpu) < 1e-3

    def test_pipeline_integration(self):
        """Estimator inside a Pipeline: fit chains into a PipelineModel."""
        t = logreg_data(200, seed=3)
        est = (
            LogisticRegression()
            .set_vector_col("features")
            .set_label_col("label")
            .set_prediction_col("pred")
            .set_max_iter(80)
            .set_learning_rate(0.5)
        )
        pipeline = Pipeline([est])
        pmodel = pipeline.fit(t)
        (out,) = pmodel.transform(t)
        acc = np.mean(np.asarray(out.col("pred")) == np.asarray(t.col("label")))
        assert acc > 0.9


class TestTrainMetrics:
    def test_fused_fit_records_throughput(self):
        t, _ = linreg_data(100)
        from flink_ml_tpu.lib import LinearRegression

        model = (LinearRegression().set_feature_cols(["f0", "f1", "f2"])
                 .set_label_col("label").set_prediction_col("p")
                 .set_learning_rate(0.05).set_max_iter(7).fit(t))
        s = model.train_metrics_.summary(skip_warmup=0)
        assert s["total_samples"] == 7 * 100
        assert s["samples_per_sec"] > 0
