"""Vector/matrix kernel tests — golden-value parity with the reference's
DenseVectorTest, SparseVectorTest, DenseMatrixTest, MatVecOpTest, VectorUtilTest."""

import numpy as np
import pytest

from flink_ml_tpu.ops import (
    DenseMatrix,
    DenseVector,
    SparseVector,
    matvec,
    parse_vector,
    vector_to_string,
)
from flink_ml_tpu.ops.codec import parse_dense, parse_sparse


class TestDenseVector:
    def test_factories(self):
        assert DenseVector.ones(3).values.tolist() == [1, 1, 1]
        assert DenseVector.zeros(2).values.tolist() == [0, 0]
        assert DenseVector.rand(4).size() == 4

    def test_norms(self):
        v = DenseVector([3.0, -4.0])
        assert v.norm_l1() == 7.0
        assert v.norm_l2() == 5.0
        assert v.norm_l2_square() == 25.0
        assert v.norm_inf() == 4.0

    def test_plus_minus_dot(self):
        a, b = DenseVector([1, 2, 3]), DenseVector([4, 5, 6])
        assert a.plus(b).values.tolist() == [5, 7, 9]
        assert b.minus(a).values.tolist() == [3, 3, 3]
        assert a.dot(b) == 32.0
        with pytest.raises(ValueError):
            a.dot(DenseVector([1, 2]))

    def test_inplace(self):
        v = DenseVector([1, 2])
        v.plus_equal(DenseVector([1, 1]))
        assert v.values.tolist() == [2, 3]
        v.minus_equal(DenseVector([1, 1]))
        assert v.values.tolist() == [1, 2]
        v.plus_scale_equal(DenseVector([2, 2]), 0.5)
        assert v.values.tolist() == [2, 3]
        v.scale_equal(2.0)
        assert v.values.tolist() == [4, 6]

    def test_prefix_append_slice(self):
        v = DenseVector([1, 2])
        assert v.prefix(0).values.tolist() == [0, 1, 2]
        assert v.append(3).values.tolist() == [1, 2, 3]
        assert v.slice([1]).values.tolist() == [2]

    def test_normalize_standardize(self):
        v = DenseVector([3, 4])
        v.normalize(2)
        assert np.allclose(v.values, [0.6, 0.8])
        w = DenseVector([1, 3])
        w.standardize(2.0, 1.0)
        assert w.values.tolist() == [-1, 1]

    def test_outer(self):
        m = DenseVector([1, 2]).outer(DenseVector([3, 4, 5]))
        assert m.data.tolist() == [[3, 4, 5], [6, 8, 10]]

    def test_iterator(self):
        assert list(DenseVector([5, 6]).iterator()) == [(0, 5.0), (1, 6.0)]


class TestSparseVector:
    def test_ctor_sorts_and_merges(self):
        v = SparseVector(5, [3, 1, 3], [1.0, 2.0, 4.0])
        assert v.indices.tolist() == [1, 3]
        assert v.vals.tolist() == [2.0, 5.0]

    def test_out_of_range_raises(self):
        with pytest.raises(ValueError):
            SparseVector(2, [0, 5], [1.0, 1.0])

    def test_get_set_add(self):
        v = SparseVector(6, [1, 4], [1.0, 2.0])
        assert v.get(4) == 2.0
        assert v.get(0) == 0.0
        v.set(2, 9.0)
        assert v.get(2) == 9.0
        v.add(4, 1.0)
        assert v.get(4) == 3.0
        assert v.indices.tolist() == [1, 2, 4]

    def test_sparse_sparse_dot(self):
        a = SparseVector(8, [0, 3, 5], [1.0, 2.0, 3.0])
        b = SparseVector(8, [3, 5, 7], [4.0, 5.0, 6.0])
        assert a.dot(b) == 2 * 4 + 3 * 5

    def test_sparse_dense_ops(self):
        s = SparseVector(3, [1], [2.0])
        d = DenseVector([1, 1, 1])
        assert s.plus(d).values.tolist() == [1, 3, 1]
        assert s.dot(d) == 2.0
        assert s.minus(d).values.tolist() == [-1, 1, -1]
        assert d.plus(s).values.tolist() == [1, 3, 1]

    def test_to_dense_and_unknown_size(self):
        v = SparseVector(-1, [2], [7.0])
        assert v.to_dense().values.tolist() == [0, 0, 7]
        assert v.size() == -1

    def test_remove_zero_values(self):
        v = SparseVector(4, [0, 2], [0.0, 5.0])
        v.remove_zero_values()
        assert v.indices.tolist() == [2]

    def test_prefix_append(self):
        v = SparseVector(3, [1], [5.0])
        p = v.prefix(9.0)
        assert p.size() == 4 and p.get(0) == 9.0 and p.get(2) == 5.0
        a = v.append(8.0)
        assert a.size() == 4 and a.get(3) == 8.0

    def test_outer(self):
        v = SparseVector(2, [1], [2.0])
        m = v.outer()
        assert m.data.tolist() == [[0, 0], [0, 4]]


class TestDenseMatrix:
    def test_factories(self):
        assert DenseMatrix.eye(2).data.tolist() == [[1, 0], [0, 1]]
        assert DenseMatrix.ones(1, 2).data.tolist() == [[1, 1]]
        assert DenseMatrix.rand_symmetric(3).is_symmetric()

    def test_multiplies_matrix(self):
        a = DenseMatrix([[1, 2], [3, 4]])
        b = DenseMatrix([[5, 6], [7, 8]])
        assert a.multiplies(b).data.tolist() == [[19, 22], [43, 50]]
        with pytest.raises(ValueError):
            a.multiplies(DenseMatrix.ones(3, 3))

    def test_multiplies_vector(self):
        a = DenseMatrix([[1, 2], [3, 4]])
        assert a.multiplies(DenseVector([1, 1])).values.tolist() == [3, 7]
        assert a.multiplies(SparseVector(2, [1], [2.0])).values.tolist() == [4, 8]

    def test_submatrix_rows(self):
        a = DenseMatrix([[1, 2, 3], [4, 5, 6], [7, 8, 9]])
        assert a.select_rows([0, 2]).data.tolist() == [[1, 2, 3], [7, 8, 9]]
        assert a.get_sub_matrix(0, 2, 1, 3).data.tolist() == [[2, 3], [5, 6]]

    def test_transpose_scale_sum(self):
        a = DenseMatrix([[1, 2], [3, 4]])
        assert a.transpose().data.tolist() == [[1, 3], [2, 4]]
        assert a.scale(2).data.tolist() == [[2, 4], [6, 8]]
        assert a.sum() == 10.0


class TestMatVecOp:
    def test_sum_diffs(self):
        a, b = DenseVector([1, 2]), DenseVector([3, 0])
        assert matvec.sum_abs_diff(a, b) == 4.0
        assert matvec.sum_squared_diff(a, b) == 8.0
        s = SparseVector(2, [0], [1.0])
        assert matvec.sum_abs_diff(s, b) == 2 + 0

    def test_apply(self):
        v = matvec.apply(DenseVector([1, -2]), func=abs)
        assert v.values.tolist() == [1, 2]
        z = matvec.apply(DenseVector([1, 2]), DenseVector([3, 4]), func=lambda x, y: x * y)
        assert z.values.tolist() == [3, 8]
        s = matvec.apply(SparseVector(3, [1], [-4.0]), func=abs)
        assert isinstance(s, SparseVector) and s.vals.tolist() == [4.0]

    def test_apply_sum(self):
        assert matvec.apply_sum(DenseVector([1, 2]), DenseVector([1, 1]),
                                func=lambda x, y: (x - y) ** 2) == 1.0


class TestCodec:
    def test_dense_round_trip(self):
        v = parse_dense("1 2 -3.5")
        assert v.values.tolist() == [1, 2, -3.5]
        assert parse_dense(vector_to_string(v)) == v

    def test_dense_commas(self):
        assert parse_dense("1, 2, 3").values.tolist() == [1, 2, 3]

    def test_sparse_round_trip(self):
        v = parse_sparse("0:1 2:3")
        assert v.indices.tolist() == [0, 2] and v.vals.tolist() == [1, 3]
        assert parse_sparse(vector_to_string(v)) == v

    def test_sized_sparse(self):
        v = parse_sparse("$4$0:1 2:3")
        assert v.size() == 4
        assert vector_to_string(v).startswith("$4$")
        assert parse_vector(vector_to_string(v)) == v

    def test_parse_sniffs_format(self):
        assert isinstance(parse_vector("1 2 3"), DenseVector)
        assert isinstance(parse_vector("0:1 2:3"), SparseVector)
        assert isinstance(parse_vector("$4$0:1"), SparseVector)
        assert parse_vector("").size() == 0

    def test_parse_errors(self):
        with pytest.raises(ValueError):
            parse_dense("1 x 3")
        with pytest.raises(ValueError):
            parse_sparse("$4 0:1")
