"""Fused pipeline inference (common/fused.py) — plan grouping, parity,
fallback, and the batched-apply output sink.

The fusion contract under test: a PipelineModel transform over kernel-
capable stages issues exactly ONE device dispatch per batch per fused run
(`pipeline.fused_dispatches`), with bit-identical discrete predictions and
float scores inside accumulation tolerance of the per-stage path; anything
the planner cannot fuse — a kernel-less mapper, an incompatible column
flow, a tripped per-plan breaker — transparently splits the plan and
serves exactly as the staged path.
"""

import warnings

import numpy as np
import pytest

from flink_ml_tpu import fault, obs, serve
from flink_ml_tpu.api.core import Transformer
from flink_ml_tpu.api.pipeline import Pipeline, PipelineModel
from flink_ml_tpu.common import fused
from flink_ml_tpu.common.mapper import ColumnSink
from flink_ml_tpu.lib import (
    KMeans,
    Knn,
    LinearRegression,
    LogisticRegression,
)
from flink_ml_tpu.lib.encoding import OneHotEncoder, StringIndexer
from flink_ml_tpu.lib.feature import MinMaxScaler, StandardScaler
from flink_ml_tpu.serve import quarantine
from flink_ml_tpu.table.schema import DataTypes, Schema
from flink_ml_tpu.table.table import Table
from flink_ml_tpu.utils.environment import MLEnvironmentFactory

N, D = 1024, 6
SCHEMA = Schema.of(("features", DataTypes.DENSE_VECTOR), ("label", "double"))


@pytest.fixture
def dense_table():
    rng = np.random.RandomState(7)
    X = (2.0 * rng.randn(N, D) + 1.0).astype(np.float32)
    w = rng.randn(D).astype(np.float32)
    y = ((X - 1.0) @ w > 0).astype(np.float64)
    return Table.from_columns(SCHEMA, {"features": X, "label": y})


@pytest.fixture
def obs_on():
    obs.enable()
    obs.reset()
    yield
    obs.reset()
    obs.disable()


@pytest.fixture
def batch_size():
    """Force multi-batch transforms (N=1024 -> 4 batches of 256)."""
    env = MLEnvironmentFactory.get_default()
    old = env.default_batch_size
    env.default_batch_size = 256
    yield 256
    env.default_batch_size = old


def _transform(model, table, fuse, monkeypatch):
    monkeypatch.setenv("FMT_FUSE_TRANSFORM", "1" if fuse else "0")
    (out,) = model.transform(table)
    return out


def _assert_parity(staged, fused_t, discrete_cols=(), float_cols=()):
    assert staged.schema == fused_t.schema
    for col in discrete_cols:
        np.testing.assert_array_equal(
            np.asarray(staged.col(col), dtype=np.float64),
            np.asarray(fused_t.col(col), dtype=np.float64),
            err_msg=col,
        )
    for col in float_cols:
        np.testing.assert_allclose(
            np.asarray(staged.features_dense(col), dtype=np.float64)
            if DataTypes.is_vector(staged.schema.type_of(col))
            else np.asarray(staged.col(col), dtype=np.float64),
            np.asarray(fused_t.features_dense(col), dtype=np.float64)
            if DataTypes.is_vector(fused_t.schema.type_of(col))
            else np.asarray(fused_t.col(col), dtype=np.float64),
            rtol=1e-5, atol=1e-7, err_msg=col,
        )


class TestFusionParity:
    def test_scaler_scaler_logreg_one_dispatch_per_batch(
        self, dense_table, obs_on, batch_size, monkeypatch
    ):
        """The acceptance shape: a >=3-stage pipeline fuses to exactly one
        dispatch per batch, discrete predictions bit-identical."""
        model = Pipeline([
            StandardScaler().set_selected_col("features"),
            MinMaxScaler().set_selected_col("features"),
            LogisticRegression().set_vector_col("features")
            .set_label_col("label").set_prediction_col("pred")
            .set_prediction_detail_col("proba").set_max_iter(3)
            .set_learning_rate(0.5),
        ]).fit(dense_table)
        staged = _transform(model, dense_table, False, monkeypatch)
        obs.reset()
        fused_t = _transform(model, dense_table, True, monkeypatch)
        c = obs.registry().snapshot()["counters"]
        n_batches = -(-N // batch_size)
        assert c.get("pipeline.fused_dispatches") == n_batches
        assert c.get("pipeline.fused_rows") == N
        assert obs.registry().snapshot()["gauges"][
            "pipeline.fusion_ratio"] == 1.0
        _assert_parity(staged, fused_t,
                       discrete_cols=["pred"],
                       float_cols=["proba", "features", "label"])

    def test_linreg_kmeans_family_parity(self, dense_table, monkeypatch):
        model = Pipeline([
            StandardScaler().set_selected_col("features")
            .set_output_col("scaled"),
            LinearRegression().set_vector_col("scaled")
            .set_label_col("label").set_prediction_col("reg")
            .set_reserved_cols(["scaled", "label"]).set_max_iter(3),
        ]).fit(dense_table)
        staged = _transform(model, dense_table, False, monkeypatch)
        fused_t = _transform(model, dense_table, True, monkeypatch)
        _assert_parity(staged, fused_t, float_cols=["reg", "scaled"])

        km = Pipeline([
            StandardScaler().set_selected_col("features")
            .set_output_col("scaled"),
            KMeans().set_vector_col("scaled").set_k(4)
            .set_prediction_col("cluster").set_prediction_detail_col("dist")
            .set_max_iter(3),
        ]).fit(dense_table)
        staged = _transform(km, dense_table, False, monkeypatch)
        fused_t = _transform(km, dense_table, True, monkeypatch)
        _assert_parity(staged, fused_t, discrete_cols=["cluster"],
                       float_cols=["dist"])

    def test_knn_after_scaler_parity(self, dense_table, obs_on, monkeypatch):
        model = Pipeline([
            StandardScaler().set_selected_col("features"),
            Knn().set_vector_col("features").set_label_col("label")
            .set_k(3).set_prediction_col("p"),
        ]).fit(dense_table)
        staged = _transform(model, dense_table, False, monkeypatch)
        obs.reset()
        fused_t = _transform(model, dense_table, True, monkeypatch)
        assert obs.registry().snapshot()["counters"][
            "pipeline.fused_dispatches"] == 1
        _assert_parity(staged, fused_t, discrete_cols=["p"])

    def test_categorical_chain_host_kernels_fuse(self, obs_on, monkeypatch):
        """indexer -> encoder -> sparse LR: the host lookups join the run
        as pre-kernels; the whole 3-stage chain is one dispatch."""
        rng = np.random.RandomState(3)
        cats = np.array(["a", "b", "c", "d"])
        schema = Schema.of(("c1", DataTypes.STRING),
                           ("c2", DataTypes.STRING), ("label", "double"))
        t = Table.from_columns(schema, {
            "c1": cats[rng.randint(0, 4, N)],
            "c2": cats[rng.randint(0, 3, N)],
            "label": (rng.rand(N) > 0.5).astype(np.float64),
        })
        model = Pipeline([
            StringIndexer().set_selected_cols(["c1", "c2"])
            .set_output_cols(["i1", "i2"]),
            OneHotEncoder().set_selected_cols(["i1", "i2"])
            .set_output_col("feat"),
            LogisticRegression().set_vector_col("feat")
            .set_label_col("label").set_prediction_col("pred")
            .set_num_features(7).set_max_iter(3),
        ]).fit(t)
        staged = _transform(model, t, False, monkeypatch)
        obs.reset()
        fused_t = _transform(model, t, True, monkeypatch)
        c = obs.registry().snapshot()["counters"]
        assert c.get("pipeline.fused_dispatches") == 1
        _assert_parity(staged, fused_t,
                       discrete_cols=["pred", "i1", "i2"])

    def test_inplace_overwrite_skips_dead_fetch(self, dense_table,
                                                monkeypatch):
        """scaler -> scaler both writing 'features' in place: the first
        scaler's matrix is overwritten mid-run and must not be fetched."""
        model = Pipeline([
            StandardScaler().set_selected_col("features"),
            MinMaxScaler().set_selected_col("features"),
        ]).fit(dense_table)
        monkeypatch.setenv("FMT_FUSE_TRANSFORM", "1")
        run = fused._run_for(
            model, model.stages, 0, dense_table.schema, None
        )
        assert run is not None
        assert [ds.fetch for ds in run.device_stages] == [False, True]
        staged = _transform(model, dense_table, False, monkeypatch)
        fused_t = _transform(model, dense_table, True, monkeypatch)
        _assert_parity(staged, fused_t, float_cols=["features"])


class TestPlanSplitting:
    def test_kernel_less_stage_splits_plan(self, dense_table, obs_on,
                                           monkeypatch):
        class Doubler(Transformer):
            def transform(self, *inputs):
                (t,) = inputs
                X = np.asarray(t.features_dense("features"),
                               np.float32) * 2.0
                return (t.with_column(
                    "features", DataTypes.DENSE_VECTOR, X),)

        sc1 = StandardScaler().set_selected_col("features").fit(dense_table)
        sc2 = MinMaxScaler().set_selected_col("features").fit(dense_table)
        lr = (
            LogisticRegression().set_vector_col("features")
            .set_label_col("label").set_prediction_col("pred")
            .set_max_iter(3).fit(dense_table)
        )
        model = PipelineModel([sc1, sc2, Doubler(), sc2, lr])
        staged = _transform(model, dense_table, False, monkeypatch)
        obs.reset()
        fused_t = _transform(model, dense_table, True, monkeypatch)
        c = obs.registry().snapshot()["counters"]
        # [sc1, sc2] fuse, Doubler serves staged, [sc2, lr] fuse -> 2 runs
        assert c.get("pipeline.fused_dispatches") == 2
        assert obs.registry().snapshot()["gauges"][
            "pipeline.fusion_ratio"] == pytest.approx(4 / 5)
        _assert_parity(staged, fused_t, discrete_cols=["pred"],
                       float_cols=["features"])

    def test_custom_scorer_without_finalize_never_fuses(self, dense_table,
                                                        obs_on, monkeypatch):
        """A LinearScoreMapper subclass overriding map_batch but not the
        fused finalize must stay on the per-stage path (fusing it would
        silently serve the base scorer's columns)."""
        from flink_ml_tpu.lib.glm import LinearScoreMapper
        from flink_ml_tpu.lib.regression import LinearRegressionModel

        class OddModel(LinearRegressionModel):
            def _make_mapper(self, data_schema):
                model = self

                class _Odd(LinearScoreMapper):
                    def output_cols(self):
                        return [model.get_prediction_col()], ["double"]

                    def map_batch(self, batch):
                        s = self._scores(batch)
                        return {model.get_prediction_col(): np.asarray(
                            s * 3.0, dtype=np.float64)}

                return _Odd(self, data_schema)

        base = (
            LinearRegression().set_vector_col("features")
            .set_label_col("label").set_prediction_col("odd")
            .set_max_iter(2).fit(dense_table)
        )
        odd = OddModel()
        odd.get_params().merge(base.get_params())
        odd.set_model_data(*base.get_model_data())
        sc = StandardScaler().set_selected_col("features").fit(dense_table)
        model = PipelineModel([sc, odd])
        staged = _transform(model, dense_table, False, monkeypatch)
        obs.reset()
        fused_t = _transform(model, dense_table, True, monkeypatch)
        c = obs.registry().snapshot()["counters"]
        assert c.get("pipeline.fused_dispatches") is None  # no fusable run
        _assert_parity(staged, fused_t, float_cols=["odd"])

    def test_single_stage_and_knob_off_stay_staged(self, dense_table,
                                                   obs_on, monkeypatch):
        sc = StandardScaler().set_selected_col("features").fit(dense_table)
        lr = (
            LogisticRegression().set_vector_col("features")
            .set_label_col("label").set_prediction_col("pred")
            .set_max_iter(2).fit(dense_table)
        )
        PipelineModel([sc]).transform(dense_table)
        assert "pipeline.fused_dispatches" not in (
            obs.registry().snapshot()["counters"]
        )
        monkeypatch.setenv("FMT_FUSE_TRANSFORM", "0")
        PipelineModel([sc, lr]).transform(dense_table)
        assert "pipeline.fused_dispatches" not in (
            obs.registry().snapshot()["counters"]
        )


class TestFusedQuarantine:
    def test_offsets_survive_fused_batching(self, dense_table, obs_on,
                                            batch_size, monkeypatch):
        """Bad rows quarantined at plan entry carry their ORIGINAL feed
        offsets (here: rows 5 and 700, landing in different batches) and
        the survivors serve exactly as a staged transform's survivors."""
        model = Pipeline([
            StandardScaler().set_selected_col("features"),
            KMeans().set_vector_col("features").set_k(4)
            .set_prediction_col("cluster").set_max_iter(2),
        ]).fit(dense_table)
        X = np.asarray(dense_table.features_dense("features")).copy()
        X[5, 0] = np.nan
        X[700, 2] = np.inf
        bad = Table.from_columns(SCHEMA, {
            "features": X, "label": dense_table.col("label")})
        quarantine.reset()
        fused_t = _transform(model, bad, True, monkeypatch)
        assert fused_t.num_rows() == N - 2
        qt = quarantine.quarantine_table("StandardScalerModel")
        assert qt is not None
        rows = sorted(int(r) for r in qt.col(quarantine.QUARANTINE_ROW_COL))
        assert rows == [5, 700]
        assert set(qt.col(quarantine.QUARANTINE_REASON_COL)) == {"nan_inf"}
        quarantine.reset()
        staged = _transform(model, bad, False, monkeypatch)
        quarantine.reset()
        _assert_parity(staged, fused_t, discrete_cols=["cluster"],
                       float_cols=["features"])

    def test_second_validator_offsets_map_to_original_feed(self, obs_on,
                                                           monkeypatch):
        """Two device stages validating DIFFERENT host columns: rows the
        second validator flags were renumbered by the first validator's
        filtering — its side-table must still carry original feed rows."""
        rng = np.random.RandomState(9)
        f = rng.randn(N, 4).astype(np.float32)
        g = rng.randn(N, 4).astype(np.float32)
        schema = Schema.of(("f", DataTypes.DENSE_VECTOR),
                           ("g", DataTypes.DENSE_VECTOR),
                           ("label", "double"))
        y = (g[:, 0] > 0).astype(np.float64)
        clean = Table.from_columns(schema, {"f": f, "g": g, "label": y})
        model = Pipeline([
            KMeans().set_vector_col("f").set_k(3)
            .set_prediction_col("cluster").set_max_iter(2),
            LogisticRegression().set_vector_col("g").set_label_col("label")
            .set_prediction_col("pred").set_max_iter(2),
        ]).fit(clean)
        fbad, gbad = f.copy(), g.copy()
        fbad[3, 0] = np.nan   # validator 1 (KMeans on 'f') flags row 3
        gbad[4, 1] = np.inf   # validator 2 (LR on 'g') flags feed row 4 —
        bad = Table.from_columns(schema, {  # local index 3 after filtering
            "f": fbad, "g": gbad, "label": y})
        quarantine.reset()
        out = _transform(model, bad, True, monkeypatch)
        assert out.num_rows() == N - 2
        km = quarantine.quarantine_table("KMeansModel")
        lr = quarantine.quarantine_table("LogisticRegressionModel")
        assert [int(r) for r in km.col(quarantine.QUARANTINE_ROW_COL)] == [3]
        assert [int(r) for r in lr.col(quarantine.QUARANTINE_ROW_COL)] == [4]
        quarantine.reset()

    def test_all_rows_quarantined_batch_serves_empty(self, dense_table,
                                                     batch_size,
                                                     monkeypatch):
        X = np.asarray(dense_table.features_dense("features")).copy()
        X[:batch_size] = np.nan  # the whole first batch
        bad = Table.from_columns(SCHEMA, {
            "features": X, "label": dense_table.col("label")})
        model = Pipeline([
            StandardScaler().set_selected_col("features"),
            MinMaxScaler().set_selected_col("features"),
        ]).fit(dense_table)
        quarantine.reset()
        fused_t = _transform(model, bad, True, monkeypatch)
        assert fused_t.num_rows() == N - batch_size
        quarantine.reset()


class TestFusedBreaker:
    def test_breaker_open_degrades_to_per_stage(self, dense_table, obs_on,
                                                monkeypatch):
        monkeypatch.setenv("FMT_SERVE_BREAKER_THRESHOLD", "2")
        monkeypatch.setenv("FMT_RETRY_ATTEMPTS", "2")
        monkeypatch.setenv("FMT_RETRY_BASE_S", "0.001")
        model = Pipeline([
            StandardScaler().set_selected_col("features"),
            KMeans().set_vector_col("features").set_k(4)
            .set_prediction_col("cluster").set_max_iter(2),
        ]).fit(dense_table)
        ref = _transform(model, dense_table, False, monkeypatch)
        serve.reset_breakers()
        obs.reset()
        fault.configure("serve.dispatch@1+", seed=0)
        try:
            with warnings.catch_warnings():
                warnings.simplefilter("ignore", RuntimeWarning)
                _transform(model, dense_table, True, monkeypatch)
                out = _transform(model, dense_table, True, monkeypatch)
        finally:
            fault.configure(None)
        c = obs.registry().snapshot()["counters"]
        plan_names = [k for k in c if k.startswith(
            "serve.fallbacks.FusedPlan[")]
        assert plan_names, c
        assert c.get("pipeline.plan_fallback_batches", 0) >= 1
        assert serve.breaker(
            plan_names[0][len("serve.fallbacks."):]).state == 1.0
        # the degraded plan's per-stage path bottomed out in each mapper's
        # CPU fallback (the fault is sticky): discrete predictions exact
        _assert_parity(ref, out, discrete_cols=["cluster"],
                       float_cols=["features"])
        serve.reset_breakers()


class TestBatchedApplySink:
    """Satellite: Mapper.apply preallocates output columns and reuses the
    input table's buffers for reserved cols instead of parts+concat."""

    def test_batched_apply_matches_single_batch(self, dense_table):
        model = (
            LogisticRegression().set_vector_col("features")
            .set_label_col("label").set_prediction_col("pred")
            .set_prediction_detail_col("proba").set_max_iter(2)
            .fit(dense_table)
        )
        mapper = model.loaded_mapper(dense_table.schema)
        whole = mapper.apply(dense_table)
        batched = mapper.apply(dense_table, batch_size=100)
        assert whole.schema == batched.schema
        np.testing.assert_array_equal(
            np.asarray(whole.col("pred")), np.asarray(batched.col("pred")))
        np.testing.assert_allclose(
            np.asarray(whole.col("proba")),
            np.asarray(batched.col("proba")), rtol=1e-6)
        # reserved columns ride the INPUT buffers — no per-batch copies
        assert batched.col("label") is dense_table.col("label")

    def test_batched_apply_with_quarantined_rows(self, dense_table):
        model = (
            KMeans().set_vector_col("features").set_k(3)
            .set_prediction_col("cluster").set_max_iter(2)
            .fit(dense_table)
        )
        X = np.asarray(dense_table.features_dense("features")).copy()
        X[17, 0] = np.nan
        X[400, 1] = np.inf
        bad = Table.from_columns(SCHEMA, {
            "features": X, "label": dense_table.col("label")})
        mapper = model.loaded_mapper(bad.schema)
        quarantine.reset()
        batched = mapper.apply(bad, batch_size=128)
        quarantine.reset()
        whole = mapper.apply(bad)
        quarantine.reset()
        assert batched.num_rows() == N - 2 == whole.num_rows()
        np.testing.assert_array_equal(
            np.asarray(whole.col("cluster")),
            np.asarray(batched.col("cluster")))
        np.testing.assert_array_equal(
            np.asarray(whole.col("label")), np.asarray(batched.col("label")))

    def test_batched_csr_output_column(self):
        """OneHotEncoder's CSR output concatenates across batches."""
        rng = np.random.RandomState(5)
        schema = Schema.of(("i1", "double"), ("label", "double"))
        t = Table.from_columns(schema, {
            "i1": rng.randint(0, 4, 500).astype(np.float64),
            "label": np.zeros(500),
        })
        model = (
            OneHotEncoder().set_selected_cols(["i1"])
            .set_output_col("feat").fit(t)
        )
        mapper = model.loaded_mapper(t.schema)
        whole = mapper.apply(t)
        batched = mapper.apply(t, batch_size=64)
        a = whole.features_dense("feat")
        b = batched.features_dense("feat")
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_column_sink_object_rows(self):
        sink = ColumnSink(["v"], [DataTypes.STRING], 5)
        sink.append({"v": ["a", "b"]}, 2)
        sink.append({"v": ["c"]}, 1)
        out = sink.columns()["v"]
        assert list(out) == ["a", "b", "c"]

    def test_column_sink_missing_col_raises(self):
        sink = ColumnSink(["v"], ["double"], 3)
        with pytest.raises(ValueError, match="did not produce"):
            sink.append({}, 2)


class TestReapHoisting:
    """Satellite: one slab-pool reap per PipelineModel.transform (and per
    plan entry), not one per stage; none at all on empty tables."""

    def _count_reaps(self, monkeypatch):
        from flink_ml_tpu.table import slab_pool

        calls = []
        orig = slab_pool.SlabPool.reap
        monkeypatch.setattr(
            slab_pool.SlabPool, "reap",
            lambda self: calls.append(1) or orig(self),
        )
        return calls

    def test_pipeline_transform_reaps_once(self, dense_table, monkeypatch):
        model = Pipeline([
            StandardScaler().set_selected_col("features"),
            MinMaxScaler().set_selected_col("features"),
            LogisticRegression().set_vector_col("features")
            .set_label_col("label").set_prediction_col("pred")
            .set_max_iter(2),
        ]).fit(dense_table)
        for fuse in ("0", "1"):
            monkeypatch.setenv("FMT_FUSE_TRANSFORM", fuse)
            calls = self._count_reaps(monkeypatch)
            model.transform(dense_table)
            assert len(calls) == 1, (fuse, len(calls))

    def test_standalone_apply_still_reaps(self, dense_table, monkeypatch):
        model = (
            StandardScaler().set_selected_col("features").fit(dense_table)
        )
        calls = self._count_reaps(monkeypatch)
        model.transform(dense_table)
        assert len(calls) == 1

    def test_empty_table_apply_skips_reap(self, dense_table, monkeypatch):
        model = (
            StandardScaler().set_selected_col("features").fit(dense_table)
        )
        empty = dense_table.slice_rows(0, 0)
        calls = self._count_reaps(monkeypatch)
        mapper = model.loaded_mapper(dense_table.schema)
        mapper.apply(empty)
        assert len(calls) == 0


class TestMeshSharding:
    """SPMD fused serving over the virtual 8-device mesh (ISSUE 15)."""

    def _mesh(self):
        from flink_ml_tpu.utils.environment import MLEnvironmentFactory

        return MLEnvironmentFactory.get_default().get_mesh()

    def test_try_place_pads_ragged_rows_to_row_multiple(self):
        """Red test (ISSUE 15 satellite): a ``P('data')`` placement of a
        batch whose row count does not divide the mesh's data axis used
        to raise out of ``_try_place`` — now it pads with zero (masked)
        rows instead, so every fused surface survives ragged batches."""
        import jax

        mesh = self._mesh()
        n_dev = jax.device_count()
        assert n_dev == 8
        a = np.arange(10 * 4, dtype=np.float32).reshape(10, 4)
        placed = fused._try_place(a, mesh, n_dev)
        assert placed.shape[0] == 16  # padded up to the axis multiple
        np.testing.assert_array_equal(np.asarray(placed)[:10], a)
        np.testing.assert_array_equal(
            np.asarray(placed)[10:], np.zeros((6, 4), np.float32))

    def test_sparse_csr_plan_shards_over_the_mesh(self, obs_on,
                                                  monkeypatch):
        """The segment-CSR fused path no longer takes the single-device
        bypass: an indexer -> encoder -> sparse-LR chain dispatches ONE
        shard_map program per batch with staged-parity outputs."""
        rng = np.random.RandomState(3)
        n = 1000  # pads to the 1024 rung: 24 weight-0 pad rows
        cats = list(rng.choice(["a", "b", "c", "d"], size=n))
        y = (np.asarray(cats) == "a").astype(np.float64)
        t = Table.from_columns(
            Schema.of(("c1", "string"), ("label", "double")),
            {"c1": cats, "label": y},
        )
        model = Pipeline([
            StringIndexer().set_selected_cols(["c1"])
            .set_output_cols(["i1"]),
            OneHotEncoder().set_selected_cols(["i1"])
            .set_output_col("feat"),
            LogisticRegression().set_vector_col("feat")
            .set_label_col("label").set_prediction_col("pred")
            .set_learning_rate(0.5).set_max_iter(3),
        ]).fit(t)
        fused.reset_mesh_stats()
        fused_t = _transform(model, t, True, monkeypatch)
        counters = obs.registry().snapshot()["counters"]
        assert counters.get("fused.shard_map_dispatches", 0) >= 1
        assert counters.get("pipeline.fused_dispatches", 0) >= 1
        assert counters.get("fused.padded_rows", 0) == 24
        status = fused.mesh_status()
        assert status["devices"] == 8
        assert sum(int(r) for r in status["device_rows"].values()) == n
        staged = _transform(model, t, False, monkeypatch)
        _assert_parity(staged, fused_t, discrete_cols=["pred"])

    def test_serve_mesh_off_restores_single_device_dispatch(
            self, dense_table, obs_on, monkeypatch):
        """FMT_SERVE_MESH=0 is the escape hatch: same answers, zero
        shard_map dispatches."""
        model = Pipeline([
            StandardScaler().set_selected_col("features"),
            MinMaxScaler().set_selected_col("features"),
        ]).fit(dense_table)
        monkeypatch.setenv("FMT_SERVE_MESH", "0")
        off_out = _transform(model, dense_table, True, monkeypatch)
        counters = obs.registry().snapshot()["counters"]
        assert counters.get("fused.shard_map_dispatches", 0) == 0
        monkeypatch.delenv("FMT_SERVE_MESH")
        on_out = _transform(model, dense_table, True, monkeypatch)
        counters = obs.registry().snapshot()["counters"]
        assert counters.get("fused.shard_map_dispatches", 0) >= 1
        _assert_parity(off_out, on_out, float_cols=["features"])

    def test_bisection_subranges_below_row_multiple_pad_and_mask(
            self, obs_on, monkeypatch):
        """``_bisected_batch`` halving can leave a trailing sub-range
        smaller than (or not divisible by) the mesh width — those
        ranges pad-and-mask through the ladder and the result is
        bit-identical to the unpressured fused run."""
        rng = np.random.RandomState(11)
        n = 180  # ceilings below force sub-ranges of 40/20 rows on 8 devs
        X = (2.0 * rng.randn(n, D) + 1.0).astype(np.float32)
        w = rng.randn(D).astype(np.float32)
        y = ((X - 1.0) @ w > 0).astype(np.float64)
        t = Table.from_columns(SCHEMA, {"features": X, "label": y})
        model = Pipeline([
            StandardScaler().set_selected_col("features"),
            LogisticRegression().set_vector_col("features")
            .set_label_col("label").set_prediction_col("pred")
            .set_prediction_detail_col("proba")
            .set_learning_rate(0.5).set_max_iter(3),
        ]).fit(t)
        from flink_ml_tpu.fault import pressure

        pressure.reset_states()
        clean = _transform(model, t, True, monkeypatch)
        fault.configure("fault.oom>64", seed=0)
        try:
            pressured = _transform(model, t, True, monkeypatch)
        finally:
            fault.configure(None)
        counters = obs.registry().snapshot()["counters"]
        assert counters.get("pressure.bisections", 0) >= 1
        _assert_parity(clean, pressured, discrete_cols=["pred"],
                       float_cols=["proba"])
        # the surface's cap is per-device-denominated: its GLOBAL limit
        # over the 8 shards recovered to at most the injected ceiling
        caps = {k: v for k, v in pressure.current_caps().items()
                if k.startswith("FusedPlan[")}
        assert caps, pressure.current_caps()
        assert all(v * 8 <= 64 for v in caps.values()), caps
        pressure.reset_states()
