"""Multi-tenant model multiplexing (ISSUE 20) — tenant-keyed registry,
same-family mux coalescing, per-tenant quota, LRU residency, and the
cross-tenant isolation contract.

The contract under test: every tenant's served rows are BIT-IDENTICAL to
serving that tenant's model solo — coalescing across tenants, the
stacked-param mux dispatch, eviction and fault-in are all invisible to
the caller — while per-tenant accounting (requests/sheds/evictions/
cold-loads) makes noisy neighbors visible and ``FMT_TENANT_QUOTA_ROWS``
makes them sheddable.
"""

import gc

import numpy as np
import pytest

from flink_ml_tpu import obs
from flink_ml_tpu.api.pipeline import Pipeline
from flink_ml_tpu.common import fused
from flink_ml_tpu.lib import LogisticRegression
from flink_ml_tpu.lib.feature import MinMaxScaler, StandardScaler
from flink_ml_tpu.serve import quarantine
from flink_ml_tpu.serving import ModelServer, ServerOverloadedError
from flink_ml_tpu.serving.errors import SHED_TENANT_QUOTA
from flink_ml_tpu.serving.tenants import (
    DEFAULT_TENANT,
    TENANT_KEY_MAX,
    validate_tenant_key,
)
from flink_ml_tpu.table import slab_pool
from flink_ml_tpu.table.schema import DataTypes, Schema
from flink_ml_tpu.table.table import Table

N, D = 256, 5
SCHEMA = Schema.of(("features", DataTypes.DENSE_VECTOR), ("label", "double"))
WAIT = 30

rng = np.random.RandomState(7)
_X = (2.0 * rng.randn(N, D) + 1.0).astype(np.float32)
_W = rng.randn(D).astype(np.float32)
_Y = ((_X - 1.0) @ _W > 0).astype(np.float64)


@pytest.fixture(scope="module")
def dense_table():
    return Table.from_columns(SCHEMA, {"features": _X, "label": _Y})


def _fit(seed):
    """One family member: same pipeline structure, different params."""
    r = np.random.RandomState(seed)
    X = (2.0 * r.randn(N, D) + 1.0).astype(np.float32)
    y = ((X - 1.0) @ _W > 0).astype(np.float64)
    t = Table.from_columns(SCHEMA, {"features": X, "label": y})
    return Pipeline([
        StandardScaler().set_selected_col("features"),
        MinMaxScaler().set_selected_col("features"),
        LogisticRegression().set_vector_col("features")
        .set_label_col("label").set_prediction_col("pred")
        .set_prediction_detail_col("proba").set_max_iter(3)
        .set_learning_rate(0.5),
    ]).fit(t)


@pytest.fixture(scope="module")
def default_model():
    return _fit(1)


@pytest.fixture(scope="module")
def tenant_models():
    return {f"t{i}": _fit(10 + i) for i in range(4)}


@pytest.fixture
def obs_on():
    obs.enable()
    obs.reset()
    obs.flight.reset()
    yield
    obs.reset()
    obs.flight.reset()
    obs.disable()


def _solo(model, table):
    out = model.transform(table)
    (out,) = out if isinstance(out, tuple) else (out,)
    return out


def _assert_served_equal(got: Table, want: Table):
    np.testing.assert_array_equal(
        np.asarray(got.col("pred"), dtype=np.float64),
        np.asarray(want.col("pred"), dtype=np.float64), err_msg="pred")
    # float scores: accumulation tolerance (the mux gathers stacked
    # params, which reassociates the dot product), discrete outputs exact
    np.testing.assert_allclose(
        np.asarray(got.col("proba"), dtype=np.float64),
        np.asarray(want.col("proba"), dtype=np.float64),
        rtol=1e-5, atol=1e-6, err_msg="proba")


# -- tenant key admission (satellite: malformed-key red test) -----------------


class TestTenantKeys:
    @pytest.mark.parametrize("bad", [
        "", "-leading-dash", ".hidden", "has space", "slash/key",
        "semi;colon", "a" * (TENANT_KEY_MAX + 1), "\x00nul", "é-accent",
    ])
    def test_malformed_keys_raise_value_error(self, bad):
        with pytest.raises(ValueError):
            validate_tenant_key(bad)

    @pytest.mark.parametrize("bad", [None, 7, b"bytes"])
    def test_non_string_keys_raise_value_error(self, bad):
        with pytest.raises(ValueError):
            validate_tenant_key(bad)

    @pytest.mark.parametrize("ok", [
        "t0", "Tenant-1", "acme.prod", "a", "0", "x" * TENANT_KEY_MAX,
    ])
    def test_well_formed_keys_pass(self, ok):
        assert validate_tenant_key(ok) == ok

    def test_malformed_key_rejected_at_the_submit_door(self, default_model,
                                                       dense_table):
        server = ModelServer(default_model, start=False)
        try:
            with pytest.raises(ValueError, match="malformed tenant key"):
                server.submit(dense_table.slice_rows(0, 2),
                              tenant="no/slashes")
        finally:
            server.shutdown(drain=False)

    def test_unknown_tenant_rejected_at_the_submit_door(self, default_model,
                                                        dense_table):
        server = ModelServer(default_model, start=False)
        try:
            with pytest.raises(ValueError, match="unknown tenant"):
                server.submit(dense_table.slice_rows(0, 2), tenant="ghost")
        finally:
            server.shutdown(drain=False)

    def test_default_tenant_cannot_be_registered(self, default_model):
        server = ModelServer(default_model, start=False)
        try:
            with pytest.raises(ValueError, match="deploy"):
                server.register_tenant(DEFAULT_TENANT, default_model)
        finally:
            server.shutdown(drain=False)


# -- cross-tenant isolation: parity vs solo serving ---------------------------


class TestTenantParity:
    def test_coalesced_tenants_match_solo_bit_for_bit(self, default_model,
                                                      tenant_models,
                                                      dense_table, obs_on):
        """Interleaved traffic from 4 same-family tenants in one burst:
        every response must equal a solo transform of that tenant's model
        over exactly the caller's rows."""
        solo = {t: _solo(m, dense_table)
                for t, m in tenant_models.items()}
        with ModelServer(default_model, max_batch=1024,
                         max_wait_ms=50) as server:
            for t, m in tenant_models.items():
                server.register_tenant(t, m)
            futs, lo = [], 0
            for rep in range(4):
                for t in tenant_models:
                    futs.append((t, lo, server.submit(
                        dense_table.slice_rows(lo, lo + 16), tenant=t)))
                    lo += 16
            for t, lo_, f in futs:
                res = f.result(WAIT)
                _assert_served_equal(
                    res.table, solo[t].slice_rows(lo_, lo_ + 16))
                assert res.version.startswith(t + ":")
        c = obs.registry().snapshot()["counters"]
        assert c.get("serving.tenant.requests", 0) == 16

    def test_mux_quarantine_offsets_stay_request_local(self, default_model,
                                                       tenant_models,
                                                       dense_table, obs_on):
        """Two tenants coalesced, tenant B ships a NaN row: B sees
        ``nan_inf`` at ITS local offset, A sees clean rows — exactly the
        solo-serving side-tables."""
        t_a, t_b = "t0", "t1"
        a_req = dense_table.slice_rows(0, 3)
        Xb = np.asarray(dense_table.features_dense("features")[3:6]).copy()
        Xb[1, 0] = np.nan
        b_req = Table.from_columns(SCHEMA, {
            "features": Xb, "label": dense_table.col("label")[3:6]})
        quarantine.reset()
        server = ModelServer(default_model, max_batch=64, max_wait_ms=20,
                             start=False)
        try:
            server.register_tenant(t_a, tenant_models[t_a])
            server.register_tenant(t_b, tenant_models[t_b])
            # warm the family tokens so the NEXT batch coalesces the two
            # tenants (a tenant's first serve runs solo by design)
            server.start()
            server.predict(dense_table.slice_rows(0, 2), tenant=t_a,
                           timeout=WAIT)
            server.predict(dense_table.slice_rows(0, 2), tenant=t_b,
                           timeout=WAIT)
            fa = server.submit(a_req, tenant=t_a)
            fb = server.submit(b_req, tenant=t_b)
            ra, rb = fa.result(WAIT), fb.result(WAIT)
        finally:
            server.shutdown()
        assert ra.num_rows == 3 and ra.num_quarantined == 0
        assert rb.num_rows == 2 and rb.num_quarantined == 1
        (q,) = rb.quarantine.values()
        assert list(q.col(quarantine.QUARANTINE_REASON_COL)) == ["nan_inf"]
        assert [int(r) for r in q.col(quarantine.QUARANTINE_ROW_COL)] == [1]
        quarantine.reset()
        solo_b = _solo(tenant_models[t_b], b_req)
        quarantine.reset()
        _assert_served_equal(ra.table, _solo(tenant_models[t_a], a_req))
        _assert_served_equal(rb.table, solo_b)

    def test_eviction_then_fault_in_preserves_parity(self, default_model,
                                                     dense_table, tmp_path,
                                                     monkeypatch, obs_on):
        """A tenant evicted by the residency cap must serve IDENTICALLY
        after faulting back in from its artifact."""
        monkeypatch.setenv("FMT_TENANT_MAX_RESIDENT", "1")
        models = {f"p{i}": _fit(30 + i) for i in range(3)}
        for t, m in models.items():
            m.save(str(tmp_path / t))
        solo = {t: _solo(m, dense_table) for t, m in models.items()}
        slab_pool.reset_pool()
        try:
            with ModelServer(default_model, max_wait_ms=10) as server:
                for t in models:
                    server.register_tenant(t, str(tmp_path / t))
                for round_ in range(2):
                    for t in models:  # each resolve evicts the previous
                        res = server.predict(dense_table.slice_rows(0, 8),
                                             tenant=t, timeout=WAIT)
                        _assert_served_equal(
                            res.table, solo[t].slice_rows(0, 8))
            c = obs.registry().snapshot()["counters"]
            assert c.get("serving.tenant.evictions", 0) >= 2
            # round 2 re-faulted models the cap displaced in round 1
            assert c.get("serving.tenant.cold_loads", 0) >= 4
        finally:
            slab_pool.reset_pool()


# -- family-shared compile economics (satellite 1) ----------------------------


class TestCompileFlatness:
    def test_compile_ledger_flat_across_50_tenants_of_one_family(
            self, default_model, dense_table, obs_on, monkeypatch):
        """50 tenants of ONE pipeline family serve through one server:
        the compile ledger must grow by at most a handful of shape rungs
        — never per tenant."""
        # a warm-artifact store left active by an earlier path-deploy
        # test would satisfy solo dispatches from disk and bypass the
        # family-fn cache whose economics this test asserts
        monkeypatch.setenv("FMT_WARMSTART", "0")
        tenants = {f"f{i}": _fit(100 + i) for i in range(50)}
        with ModelServer(default_model, max_batch=1024,
                         max_wait_ms=20) as server:
            for t, m in tenants.items():
                server.register_tenant(t, m)
            # warm round: each tenant's first serve learns its family
            # token (and may compile the family's shape rungs once)
            for t in tenants:
                server.predict(dense_table.slice_rows(0, 4), tenant=t,
                               timeout=WAIT)
            seen_after_warm = len(fused._COMPILE_SEEN)
            futs = [server.submit(dense_table.slice_rows(0, 4), tenant=t)
                    for t in tenants]
            for f in futs:
                f.result(WAIT)
            growth = len(fused._COMPILE_SEEN) - seen_after_warm
        # the coalesced round may mint a few NEW tenant-count rungs
        # (mux:plan@t2, @t4, ...) but NOTHING proportional to 50 tenants
        assert growth <= 8, growth
        c = obs.registry().snapshot()["counters"]
        # tenants shared jit executables through the family cache
        assert c.get("fused.family_fn_hits", 0) > 0

    def test_mux_coalesces_many_tenants_into_few_dispatches(
            self, default_model, tenant_models, dense_table, obs_on):
        with ModelServer(default_model, max_batch=1024,
                         max_wait_ms=50) as server:
            for t, m in tenant_models.items():
                server.register_tenant(t, m)
            for t in tenant_models:  # warm family tokens
                server.predict(dense_table.slice_rows(0, 4), tenant=t,
                               timeout=WAIT)
            futs, lo = [], 0
            for rep in range(4):
                for t in tenant_models:
                    futs.append(server.submit(
                        dense_table.slice_rows(lo, lo + 8), tenant=t))
                    lo += 8
            for f in futs:
                f.result(WAIT)
        c = obs.registry().snapshot()["counters"]
        assert c.get("serving.mux.dispatches", 0) >= 1
        # strictly more tenants coalesced than dispatches = real fusion
        assert (c.get("serving.mux.tenants_coalesced", 0)
                > c.get("serving.mux.dispatches", 0))
        assert c.get("serving.mux_fallbacks", 0) == 0


# -- per-tenant quota at the admission door -----------------------------------


class TestTenantQuota:
    def test_quota_sheds_reason_coded_and_spares_other_tenants(
            self, default_model, tenant_models, dense_table, monkeypatch,
            obs_on):
        monkeypatch.setenv("FMT_TENANT_QUOTA_ROWS", "8")
        server = ModelServer(default_model, start=False)
        try:
            server.register_tenant("t0", tenant_models["t0"])
            server.register_tenant("t1", tenant_models["t1"])
            server.submit(dense_table.slice_rows(0, 8), tenant="t0")
            with pytest.raises(ServerOverloadedError) as err:
                server.submit(dense_table.slice_rows(0, 4), tenant="t0")
            assert err.value.reason == SHED_TENANT_QUOTA
            # the noisy neighbor's quota is NOT the quiet one's problem
            server.submit(dense_table.slice_rows(0, 8), tenant="t1")
            server.submit(dense_table.slice_rows(0, 4))  # default tenant
        finally:
            server.shutdown(drain=False)
        c = obs.registry().snapshot()["counters"]
        assert c.get("serving.tenant.sheds", 0) == 1

    def test_quota_releases_as_the_queue_drains(self, default_model,
                                                tenant_models, dense_table,
                                                monkeypatch, obs_on):
        monkeypatch.setenv("FMT_TENANT_QUOTA_ROWS", "8")
        with ModelServer(default_model, max_wait_ms=5) as server:
            server.register_tenant("t0", tenant_models["t0"])
            for _ in range(3):  # served sequentially: quota never trips
                server.predict(dense_table.slice_rows(0, 8), tenant="t0",
                               timeout=WAIT)
        c = obs.registry().snapshot()["counters"]
        assert c.get("serving.tenant.sheds", 0) == 0


# -- per-tenant observability (satellite 3) -----------------------------------


class TestTenantObservability:
    def test_statusz_tenant_table_and_counters(self, default_model,
                                               tenant_models, dense_table,
                                               obs_on):
        with ModelServer(default_model, max_wait_ms=5) as server:
            server.register_tenant("t0", tenant_models["t0"])
            server.register_tenant("t1", tenant_models["t1"])
            for _ in range(3):
                server.predict(dense_table.slice_rows(0, 4), tenant="t0",
                               timeout=WAIT)
            server.predict(dense_table.slice_rows(0, 4), tenant="t1",
                           timeout=WAIT)
            server.predict(dense_table.slice_rows(0, 4), timeout=WAIT)
            status = server._telemetry_status()
        tenants = status["tenants"]
        assert tenants["tenants"] >= 3  # t0, t1, the implicit default
        top = {row["tenant"]: row for row in tenants["top"]}
        assert top["t0"]["requests"] == 3
        assert top["t1"]["requests"] == 1
        assert top[DEFAULT_TENANT]["requests"] == 1
        assert top["t0"]["cold_loads"] == 1
        c = obs.registry().snapshot()["counters"]
        assert c.get("serving.tenant.requests", 0) == 5
        assert c.get("serving.tenant.cold_loads", 0) == 2

    def test_flight_events_carry_tenant_and_reason(self, default_model,
                                                   dense_table, tmp_path,
                                                   monkeypatch, obs_on):
        monkeypatch.setenv("FMT_TENANT_MAX_RESIDENT", "1")
        m0, m1 = _fit(60), _fit(61)
        m0.save(str(tmp_path / "e0"))
        m1.save(str(tmp_path / "e1"))
        slab_pool.reset_pool()
        try:
            with ModelServer(default_model, max_wait_ms=5) as server:
                server.register_tenant("e0", str(tmp_path / "e0"))
                server.register_tenant("e1", str(tmp_path / "e1"))
                server.predict(dense_table.slice_rows(0, 4), tenant="e0",
                               timeout=WAIT)
                server.predict(dense_table.slice_rows(0, 4), tenant="e1",
                               timeout=WAIT)
            events = [e for e in obs.flight.events()
                      if e.get("kind") == "serving.tenant.evicted"]
            assert events, "no eviction flight events recorded"
            assert events[0]["tenant"] == "e0"
            assert events[0]["reason"] == "resident_cap"
            loads = [e for e in obs.flight.events()
                     if e.get("kind") == "serving.tenant.cold_load"]
            assert {e["tenant"] for e in loads} == {"e0", "e1"}
        finally:
            slab_pool.reset_pool()


# -- the slab-pool pin invariant at the eviction boundary (satellite 2) -------


class TestPoolPinInvariantAtEviction:
    def test_budget_displacement_skips_pinned_without_double_count(self):
        """LRU displacement under a tight budget must SKIP a pinned slab
        — and once the pin releases, the pool's byte accounting must show
        no trace of the displaced-entry bookkeeping (no double count)."""
        pool = slab_pool.SlabPool(budget_bytes=100)
        v = pool.get_or_build("pinned", lambda: np.zeros(10, np.float32))
        with pool.pinned(v):
            pool.get_or_build("a", lambda: np.zeros(10, np.float32))
            pool.get_or_build("b", lambda: np.zeros(10, np.float32))
            # budget is 100 B with 120 B live: the pinned slab stays
            assert pool.get_or_build("pinned", lambda: "rebuilt") is v
        pool.get_or_build("c", lambda: np.zeros(10, np.float32))
        assert pool.bytes <= 100  # accounting converged after release

    def test_dead_while_pinned_is_reaped_after_release(self):
        """RED test for the double-count: a source buffer GC'd while its
        entry is pinned must NOT leave a permanently unreapable entry
        squatting the budget — the drain after the pin releases reclaims
        it and the bytes."""
        pool = slab_pool.SlabPool(budget_bytes=1 << 20)
        X = np.zeros(100, np.float32)
        refs: list = []
        key = ("t", slab_pool.array_token(X, refs))
        v = pool.get_or_build(key, lambda: np.zeros(100, np.float32),
                              refs=refs)
        base = pool.bytes
        with pool.pinned(v):
            del X
            gc.collect()
            # a drain while pinned must honor the pin invariant
            pool.get_or_build("other", lambda: np.zeros(100, np.float32))
            assert pool.bytes == base + 400  # dead entry still counted
        # first pool touch after release: the dead entry reaps
        pool.get_or_build("probe", lambda: np.zeros(100, np.float32))
        assert pool.bytes == base + 400  # dead 400 left, probe 400 in
        assert pool.get_or_build(key, lambda: "rebuilt",
                                 refs=[]) == "rebuilt"
