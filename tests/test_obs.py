"""Unified run-telemetry subsystem tests (ISSUE 1): registry semantics,
off-by-default zero-cost hooks, RunReport JSONL persistence, and the
BASELINE.json diff CLI — plus the hot-path wiring (a tiny fit with obs on
must leave a parseable report with the compile/steady split recorded)."""

import json
import os

import numpy as np
import pytest

from flink_ml_tpu import obs
from flink_ml_tpu.obs.report import diff_against_baseline, main as report_main


@pytest.fixture(autouse=True)
def _obs_isolated():
    """Every test starts disabled with a clean registry and leaves no
    global state behind (obs is process-wide by design)."""
    import flink_ml_tpu.obs.report as _report_mod

    obs.disable()
    obs.reset()
    _report_mod._PREV_FIT_SNAPSHOT = {"counters": {}, "timings": {}}
    yield
    obs.disable()
    obs.reset()
    _report_mod._PREV_FIT_SNAPSHOT = {"counters": {}, "timings": {}}


class TestRegistry:
    def test_counters_gauges_timings_roundtrip(self):
        obs.enable()
        obs.counter_add("c.a")
        obs.counter_add("c.a", 4)
        obs.gauge_set("g.x", 7.5)
        obs.observe("t.step", 0.25)
        obs.observe("t.step", 0.75)
        snap = obs.registry().snapshot()
        assert snap["counters"]["c.a"] == 5
        assert snap["gauges"]["g.x"] == 7.5
        t = snap["timings"]["t.step"]
        assert t["count"] == 2
        assert t["total_s"] == pytest.approx(1.0)
        assert t["min_s"] == pytest.approx(0.25)
        assert t["max_s"] == pytest.approx(0.75)
        assert t["mean_s"] == pytest.approx(0.5)
        obs.reset()
        assert obs.registry().snapshot() == {
            "counters": {}, "gauges": {}, "timings": {}
        }

    def test_disabled_hooks_record_nothing(self):
        assert not obs.enabled()
        obs.counter_add("c.off")
        obs.gauge_set("g.off", 1.0)
        obs.observe("t.off", 1.0)
        with obs.phase("p.off"):
            pass
        snap = obs.registry().snapshot()
        assert snap == {"counters": {}, "gauges": {}, "timings": {}}

    def test_phase_nesting_builds_paths(self):
        obs.enable()
        with obs.phase("fit"):
            with obs.phase("pack_csr"):
                pass
            with obs.phase("pack_csr"):
                pass
        snap = obs.registry().snapshot()
        assert snap["timings"]["phase.fit"]["count"] == 1
        assert snap["timings"]["phase.fit/pack_csr"]["count"] == 2

    def test_phased_decorator(self):
        calls = []

        @obs.phased("work")
        def work(x):
            calls.append(x)
            return x * 2

        assert work(3) == 6  # disabled: plain passthrough
        obs.enable()
        assert work(4) == 8
        snap = obs.registry().snapshot()
        assert snap["timings"]["phase.work"]["count"] == 1
        assert calls == [3, 4]

    def test_snapshot_is_json_serializable(self):
        obs.enable()
        obs.counter_add("c", 2)
        obs.observe("t", 0.1)
        obs.gauge_set("g", 3.0)
        json.dumps(obs.registry().snapshot())

    def test_timingstat_exporter_fields(self):
        """ISSUE 10: to_dict carries the monotonic count/sum_s and the
        p90 an OpenMetrics summary wants, alongside the existing stats."""
        from flink_ml_tpu.obs.registry import TimingStat

        t = TimingStat()
        for v in range(10):
            t.observe(float(v))
        d = t.to_dict()
        assert d["count"] == 10
        assert d["sum_s"] == d["total_s"] == pytest.approx(45.0)
        # nearest-rank over 0..9: p50 -> 4, p90 -> 8, p99 -> 9
        assert d["p50_s"] == 4.0
        assert d["p90_s"] == 8.0
        assert d["p99_s"] == 9.0

    def test_timingstat_recent_is_the_newest_window(self):
        from flink_ml_tpu.obs.registry import TimingStat

        t = TimingStat()
        for i in range(5):
            t.observe(float(i))
        assert t.recent(3) == [2.0, 3.0, 4.0]
        assert t.recent(100) == [0.0, 1.0, 2.0, 3.0, 4.0]
        assert t.recent(0) == []
        # past the reservoir the ring wraps: recent() must still return
        # the newest-k in arrival order, not a rotated slice
        for i in range(5, t.RESERVOIR + 40):
            t.observe(float(i))
        want = [float(t.RESERVOIR + 40 - k) for k in range(4, 0, -1)]
        assert t.recent(4) == want

    def test_registry_timing_recent_accessor(self):
        obs.enable()
        for i in range(6):
            obs.observe("t.win", float(i))
        assert obs.registry().timing_recent("t.win", 2) == [4.0, 5.0]
        assert obs.registry().timing_recent("t.never", 2) == []


class TestRunReports:
    def test_write_and_load_roundtrip(self, tmp_path):
        obs.enable()
        obs.counter_add("train.epochs", 3)
        path = obs.fit_report(
            "UnitTestEstimator", shape="8x2", extra={"epochs": 3},
            directory=str(tmp_path),
        )
        assert path and os.path.exists(path)
        reports = obs.load_reports(str(tmp_path))
        assert len(reports) == 1
        r = reports[0]
        assert r["kind"] == "fit"
        assert r["name"] == "UnitTestEstimator"
        assert r["git_sha"]
        assert r["device"]["backend"]
        assert r["metrics"]["counters"]["train.epochs"] == 3
        assert r["extra"] == {"epochs": 3}

    def test_fit_reports_carry_per_fit_deltas(self, tmp_path):
        """A process running several fits must not attribute fit 1's
        counters to fit 2's report (the registry is cumulative; the
        reports are scoped)."""
        obs.enable()
        obs.counter_add("train.epochs", 5)
        obs.observe("train.dispatch", 1.0)
        obs.fit_report("FitA", directory=str(tmp_path))
        obs.counter_add("train.epochs", 2)
        obs.observe("train.dispatch", 0.25)
        obs.fit_report("FitB", directory=str(tmp_path))
        obs.fit_report("FitC", directory=str(tmp_path))  # nothing new
        a, b, c = obs.load_reports(str(tmp_path))
        assert a["metrics"]["counters"]["train.epochs"] == 5
        assert b["metrics"]["counters"]["train.epochs"] == 2
        assert b["metrics"]["timings"]["train.dispatch"] == {
            "count": 1, "total_s": 0.25, "mean_s": 0.25,
            # tail quantiles ride along (ISSUE 8, p90 since ISSUE 10):
            # window quantiles over the stat's recent reservoir, not
            # delta-exact accounting
            "p50_s": 0.25, "p90_s": 1.0, "p99_s": 1.0,
        }
        assert c["metrics"]["counters"] == {}
        assert c["metrics"]["timings"] == {}

    def test_fit_delta_survives_registry_reset(self, tmp_path):
        obs.enable()
        obs.counter_add("c", 10)
        obs.fit_report("A", directory=str(tmp_path))
        obs.reset()  # a new workload scope
        obs.counter_add("c", 3)
        obs.fit_report("B", directory=str(tmp_path))
        _, b = obs.load_reports(str(tmp_path))
        # a reset invalidates the previous totals: report the new value,
        # never a negative delta
        assert b["metrics"]["counters"]["c"] == 3

    def test_fit_delta_detects_reset_even_at_equal_totals(self, tmp_path):
        """bench_all's per-workload obs.reset() must not make a later
        workload's fit report drop counters whose post-reset totals land
        exactly on the pre-reset ones (one fused fit per workload is the
        COMMON case)."""
        obs.enable()
        obs.counter_add("train.fused_runs")
        obs.fit_report("A", directory=str(tmp_path))
        obs.reset()
        obs.counter_add("train.fused_runs")  # same total as before: 1
        obs.fit_report("B", directory=str(tmp_path))
        _, b = obs.load_reports(str(tmp_path))
        assert b["metrics"]["counters"]["train.fused_runs"] == 1

    def test_fit_report_noop_when_disabled(self, tmp_path):
        assert obs.fit_report("X", directory=str(tmp_path)) is None
        assert obs.load_reports(str(tmp_path)) == []

    def test_bench_report_records_the_record(self, tmp_path):
        obs.enable()
        obs.bench_report(
            {"metric": "m1", "value": 10.0, "unit": "rows/sec",
             "shape": "tiny"},
            directory=str(tmp_path),
        )
        (r,) = obs.load_reports(str(tmp_path))
        assert r["kind"] == "bench"
        assert r["name"] == "m1"
        assert r["extra"]["value"] == 10.0

    def test_tiny_fit_emits_parseable_report(self, tmp_path, monkeypatch):
        """The CI smoke contract: a fit with obs enabled writes one JSONL
        line carrying the registry snapshot with the dispatch/sync split
        and the program-build counter."""
        from flink_ml_tpu.lib import LogisticRegression
        from flink_ml_tpu.table.schema import DataTypes, Schema
        from flink_ml_tpu.table.table import Table

        monkeypatch.setenv("FMT_OBS_REPORTS", str(tmp_path))
        obs.enable()
        rng = np.random.RandomState(0)
        X = rng.randn(64, 4).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.float64)
        t = Table.from_columns(
            Schema.of(("features", DataTypes.DENSE_VECTOR),
                      ("label", "double")),
            {"features": X, "label": y},
        )
        model = (LogisticRegression().set_vector_col("features")
                 .set_label_col("label").set_prediction_col("p")
                 .set_max_iter(3).fit(t))
        assert model.train_epochs_ >= 1
        reports = obs.load_reports()
        fits = [r for r in reports if r["kind"] == "fit"]
        assert fits, "fit wrote no RunReport"
        r = fits[-1]
        assert r["name"] == "LogisticRegression"
        counters = r["metrics"]["counters"]
        assert counters.get("train.fused_runs", 0) >= 1
        assert counters.get("train.epochs", 0) >= 1
        timings = r["metrics"]["timings"]
        assert "train.dispatch" in timings and "train.sync" in timings
        assert r["step_summary"] is not None


def _baseline(tmp_path, measured):
    p = tmp_path / "BASELINE.json"
    p.write_text(json.dumps({"measured": measured}))
    return str(p)


def _reports(tmp_path, records):
    obs.enable()
    d = tmp_path / "reports"
    for rec in records:
        obs.bench_report(rec, directory=str(d))
    return str(d)


class TestBaselineDiff:
    def test_regression_improved_ok_and_missing(self, tmp_path):
        import jax

        backend = jax.default_backend()
        d = _reports(tmp_path, [
            {"metric": "a", "value": 80.0, "unit": "rows/sec"},
            {"metric": "b", "value": 100.0, "unit": "rows/sec"},
            {"metric": "c", "value": 130.0, "unit": "rows/sec"},
        ])
        rows = diff_against_baseline(
            obs.load_reports(d),
            {"measured": {
                "a": {"value": 100.0, "unit": "rows/sec", "backend": backend},
                "b": {"value": 100.0, "unit": "rows/sec", "backend": backend},
                "c": {"value": 100.0, "unit": "rows/sec", "backend": backend},
                "d": {"value": 1.0, "unit": "rows/sec", "backend": backend},
            }},
        )
        status = {r["metric"]: r["status"] for r in rows}
        assert status == {"a": "regression", "b": "ok", "c": "improved",
                          "d": "no-report"}

    def test_lower_is_better_direction_gates_latency_metrics(self, tmp_path):
        """The warm-fit gate (ISSUE 2): a baseline entry with
        direction='lower' flags a RISE as the regression — warm_over_cold
        drifting toward 1.0 must fail --check even though no '/sec' unit
        is involved."""
        import jax

        backend = jax.default_backend()
        d = _reports(tmp_path, [
            {"metric": "warm", "value": 0.7, "unit": "ratio"},
            {"metric": "fast", "value": 0.2, "unit": "ratio"},
            {"metric": "steady", "value": 0.52, "unit": "ratio"},
        ])
        base = {
            "value": 0.5, "unit": "ratio", "direction": "lower",
            "backend": backend,
        }
        rows = diff_against_baseline(
            obs.load_reports(d),
            {"measured": {"warm": dict(base), "fast": dict(base),
                          "steady": dict(base)}},
        )
        status = {r["metric"]: r["status"] for r in rows}
        assert status == {"warm": "regression", "fast": "improved",
                          "steady": "ok"}

    def test_zero_throughput_is_a_regression_not_no_value(self, tmp_path):
        import jax

        d = _reports(tmp_path, [
            {"metric": "a", "value": 0.0, "unit": "rows/sec"},
        ])
        (row,) = diff_against_baseline(
            obs.load_reports(d),
            {"measured": {"a": {"value": 100.0, "unit": "rows/sec",
                                "backend": jax.default_backend()}}},
        )
        # a collapse to zero is the worst regression; it must not slip
        # through the --check gate as "no-value"
        assert row["status"] == "regression" and row["ratio"] == 0.0

    def test_backend_scoping_skips_foreign_measurements(self, tmp_path):
        d = _reports(tmp_path, [
            {"metric": "a", "value": 1.0, "unit": "rows/sec"},
        ])
        (row,) = diff_against_baseline(
            obs.load_reports(d),
            {"measured": {"a": {"value": 1e9, "unit": "rows/sec",
                                "backend": "tpu"}}},
        )
        # a CPU-backend run never diffs against a TPU baseline
        assert row["status"] == "backend-mismatch"

    def test_latest_report_wins(self, tmp_path):
        import jax

        d = _reports(tmp_path, [
            {"metric": "a", "value": 10.0, "unit": "rows/sec"},
            {"metric": "a", "value": 100.0, "unit": "rows/sec"},
        ])
        (row,) = diff_against_baseline(
            obs.load_reports(d),
            {"measured": {"a": {"value": 100.0, "unit": "rows/sec",
                                "backend": jax.default_backend()}}},
        )
        assert row["status"] == "ok" and row["latest"] == 100.0

    def test_cli_check_exit_codes(self, tmp_path, capsys):
        import jax

        backend = jax.default_backend()
        d = _reports(tmp_path, [
            {"metric": "a", "value": 50.0, "unit": "rows/sec"},
        ])
        base_bad = _baseline(
            tmp_path, {"a": {"value": 100.0, "unit": "rows/sec",
                             "backend": backend}}
        )
        assert report_main(["--reports", d, "--baseline", base_bad,
                            "--check"]) == 1
        assert "regression" in capsys.readouterr().out
        # within the band -> exit 0
        base_ok = str(tmp_path / "ok.json")
        with open(base_ok, "w") as f:
            json.dump({"measured": {"a": {"value": 52.0, "unit": "rows/sec",
                                          "backend": backend}}}, f)
        assert report_main(["--reports", d, "--baseline", base_ok,
                            "--check"]) == 0

    def test_cli_check_fails_when_nothing_comparable(self, tmp_path, capsys):
        # baselines exist but no report matches (renamed metric / backend
        # drift): the gate must fail loudly, not stay green on nothing
        d = _reports(tmp_path, [
            {"metric": "renamed", "value": 5.0, "unit": "rows/sec"},
        ])
        base = _baseline(
            tmp_path, {"old-name": {"value": 5.0, "unit": "rows/sec",
                                    "backend": "cpu"}}
        )
        assert report_main(["--reports", d, "--baseline", base,
                            "--check"]) == 1
        assert "none were comparable" in capsys.readouterr().out
        # without --check it stays informational
        assert report_main(["--reports", d, "--baseline", base]) == 0

    def test_cli_empty_baseline_is_not_an_error(self, tmp_path, capsys):
        # reports exist; the baseline just has no measured section yet
        d = _reports(tmp_path, [
            {"metric": "m", "value": 1.0, "unit": "rows/sec"},
        ])
        base = _baseline(tmp_path, {})
        assert report_main(["--reports", d, "--baseline",
                            base, "--check"]) == 0
        assert "nothing to diff" in capsys.readouterr().out

    def test_cli_missing_reports_is_one_line_diagnostic(self, tmp_path,
                                                        capsys):
        """ISSUE 10 satellite: a missing or empty reports dir is an
        operator mistake — --check fails with ONE diagnostic line (no
        traceback, no silently-green diff)."""
        base = _baseline(tmp_path, {"a": {"value": 1.0,
                                          "unit": "rows/sec",
                                          "backend": "cpu"}})
        missing = str(tmp_path / "never_written")
        assert report_main(["--reports", missing, "--baseline", base,
                            "--check"]) == 1
        out = capsys.readouterr().out.strip()
        assert len(out.splitlines()) == 1
        assert "no RunReports" in out and missing in out
        # informational mode stays exit 0 (matching the empty-baseline
        # convention), but still prints the diagnostic
        assert report_main(["--reports", missing,
                            "--baseline", base]) == 0
        assert "no RunReports" in capsys.readouterr().out
        # --json keeps the machine-readable shape
        assert report_main(["--reports", missing, "--baseline", base,
                            "--check", "--json"]) == 1
        payload = json.loads(capsys.readouterr().out)
        assert payload["ok"] is False and "no RunReports" in payload["error"]

    def test_cli_last_bounds_the_diffed_reports(self, tmp_path, capsys):
        """--last N diffs only the newest N RunReports — the bound for
        an append-only runs.jsonl that has grown for months."""
        import jax

        backend = jax.default_backend()
        d = _reports(tmp_path, [
            {"metric": "a", "value": 100.0, "unit": "rows/sec"},
            {"metric": "b", "value": 100.0, "unit": "rows/sec"},
        ])
        base = _baseline(tmp_path, {
            "a": {"value": 100.0, "unit": "rows/sec", "backend": backend},
            "b": {"value": 100.0, "unit": "rows/sec", "backend": backend},
        })
        assert report_main(["--reports", d, "--baseline", base,
                            "--check"]) == 0
        capsys.readouterr()  # drain the unbounded run's output
        # bounded to the newest single report, metric a drops out
        report_main(["--reports", d, "--baseline", base, "--last", "1"])
        out = capsys.readouterr().out
        assert "no-report" in out
        rows = [line for line in out.splitlines()
                if line.startswith("a ")]
        assert rows and "no-report" in rows[0]


class _FakeDevice:
    def __init__(self, stats):
        self._stats = stats

    def memory_stats(self):
        return self._stats


class TestHbmGauges:
    """ISSUE 10 satellite: record_hbm_gauges was exercised nowhere in
    tier-1 (the CPU container's devices usually report no memory stats)
    — pin down both halves of its contract."""

    def test_gauges_appear_under_hbm_prefix(self, monkeypatch):
        import jax

        obs.enable()
        monkeypatch.setattr(jax, "local_devices", lambda: [
            _FakeDevice({"bytes_in_use": 10, "peak_bytes_in_use": 30,
                         "bytes_limit": 100}),
            _FakeDevice({"bytes_in_use": 20, "peak_bytes_in_use": 25,
                         "bytes_limit": 100}),
        ])
        obs.record_hbm_gauges()
        gauges = obs.registry().snapshot()["gauges"]
        # max over local devices, each key under hbm.*
        assert gauges["hbm.bytes_in_use"] == 20
        assert gauges["hbm.peak_bytes_in_use"] == 30
        assert gauges["hbm.bytes_limit"] == 100
        assert all(k.startswith("hbm.") for k in gauges)

    def test_custom_prefix(self, monkeypatch):
        import jax

        obs.enable()
        monkeypatch.setattr(jax, "local_devices", lambda: [
            _FakeDevice({"bytes_in_use": 7}),
        ])
        obs.record_hbm_gauges(prefix="post_spill")
        gauges = obs.registry().snapshot()["gauges"]
        assert gauges == {"post_spill.bytes_in_use": 7}

    def test_noop_when_backend_reports_no_stats(self, monkeypatch):
        import jax

        obs.enable()
        monkeypatch.setattr(jax, "local_devices", lambda: [
            _FakeDevice(None), _FakeDevice({})])
        obs.record_hbm_gauges()  # must not raise
        assert obs.registry().snapshot()["gauges"] == {}

    def test_partial_stats_record_what_exists(self, monkeypatch):
        import jax

        obs.enable()
        monkeypatch.setattr(jax, "local_devices", lambda: [
            _FakeDevice({"bytes_in_use": 5}),  # no peak / limit keys
        ])
        obs.record_hbm_gauges()
        assert obs.registry().snapshot()["gauges"] == {
            "hbm.bytes_in_use": 5}

    def test_real_cpu_backend_never_raises(self):
        obs.enable()
        obs.record_hbm_gauges()  # whatever this backend reports: no error
        gauges = obs.registry().snapshot()["gauges"]
        assert all(k.startswith("hbm.") for k in gauges)

    def test_disabled_is_a_noop(self, monkeypatch):
        import jax

        assert not obs.enabled()
        monkeypatch.setattr(jax, "local_devices", lambda: [
            _FakeDevice({"bytes_in_use": 10})])
        obs.record_hbm_gauges()
        assert obs.registry().snapshot()["gauges"] == {}


class TestHotPathWiring:
    def test_chunked_table_counts_parsed_chunks(self, tmp_path):
        from flink_ml_tpu.table.schema import DataTypes, Schema
        from flink_ml_tpu.table.sources import ChunkedTable, CsvSource

        p = tmp_path / "t.csv"
        p.write_text("".join(f"{i},{i % 2}\n" for i in range(10)))
        schema = Schema.of(("x", DataTypes.DOUBLE), ("label", "double"))
        chunked = ChunkedTable(CsvSource(str(p), schema), chunk_rows=4)
        list(chunked.chunks())  # disabled: no counts
        assert obs.registry().counter("source.chunks_parsed") == 0
        obs.enable()
        n = sum(t.num_rows() for t in chunked.chunks())
        assert n == 10
        assert obs.registry().counter("source.chunks_parsed") == 3
        assert obs.registry().counter("source.rows_parsed") == 10

    def test_pack_phase_recorded(self):
        from flink_ml_tpu.lib.common import pack_minibatches

        obs.enable()
        X = np.zeros((16, 3), dtype=np.float32)
        y = np.zeros((16,), dtype=np.float64)
        pack_minibatches(X, y, 1, 8)
        snap = obs.registry().snapshot()
        assert snap["timings"]["phase.pack_dense"]["count"] == 1
