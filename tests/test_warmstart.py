"""Cold-start resilience: compile-cache knob + warm-artifact store (ISSUE 18).

Covers the satellite-4 checklist for ``enable_compilation_cache`` /
``ensure_compilation_cache_for_backend`` (idempotency, ``=off`` opt-out,
CPU-defer heuristic, legacy-name fallback) and the tentpole warm-artifact
layer: AOT save/load round trip, torn-write / corrupt-entry / fingerprint
mismatch -> detected degrade to recompile (counter + flight event, never a
raise), bounded GC, fault-injection points, the fused lookup-before-compile
path, the ladder warmup in ``VersionManager.deploy``, and the replica spawn
env propagation.
"""

import os
import pickle

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from flink_ml_tpu import obs  # noqa: E402
from flink_ml_tpu.serve import integrity  # noqa: E402
from flink_ml_tpu.serving import warmstart  # noqa: E402
from flink_ml_tpu.utils import compile_cache, knobs  # noqa: E402


def _counters():
    return obs.registry().snapshot().get("counters", {})


@pytest.fixture(autouse=True)
def _obs_on():
    obs.enable()
    obs.reset()
    obs.flight.reset()
    yield


# -- compile-cache knob migration (satellite 1 + 4) ---------------------------


@pytest.fixture
def cache_state(monkeypatch):
    """Isolate the module-global idempotency latch and both env names."""
    old = compile_cache._enabled_dir
    compile_cache._enabled_dir = None
    monkeypatch.delenv("FMT_COMPILE_CACHE", raising=False)
    monkeypatch.delenv("FLINK_ML_TPU_COMPILE_CACHE", raising=False)
    yield monkeypatch
    compile_cache._enabled_dir = old
    try:
        jax.config.update("jax_compilation_cache_dir", None)
    except Exception:
        pass


class TestCompileCacheKnob:
    def test_knob_declared(self):
        names = {k.name for k in knobs.DECLARATIONS}
        assert "FMT_COMPILE_CACHE" in names
        assert "FMT_WARM_LADDER_MAX" in names
        assert "FMT_WARMSTART" in names
        assert "FMT_WARM_DIR" in names
        assert "FMT_WARM_CACHE_MB" in names

    def test_off_opt_out(self, cache_state):
        cache_state.setenv("FMT_COMPILE_CACHE", "off")
        assert compile_cache.enable_compilation_cache(backend_known=True) is None
        assert compile_cache.cache_dir() is None

    def test_legacy_name_fallback(self, cache_state, tmp_path):
        d = str(tmp_path / "xla_legacy")
        cache_state.setenv("FLINK_ML_TPU_COMPILE_CACHE", d)
        assert compile_cache.enable_compilation_cache(backend_known=True) == d

    def test_legacy_off_still_honored(self, cache_state):
        cache_state.setenv("FLINK_ML_TPU_COMPILE_CACHE", "off")
        assert compile_cache.enable_compilation_cache(backend_known=True) is None

    def test_fmt_name_wins_over_legacy(self, cache_state, tmp_path):
        a, b = str(tmp_path / "a"), str(tmp_path / "b")
        cache_state.setenv("FMT_COMPILE_CACHE", a)
        cache_state.setenv("FLINK_ML_TPU_COMPILE_CACHE", b)
        assert compile_cache.enable_compilation_cache(backend_known=True) == a

    def test_cpu_defer_without_env(self, cache_state):
        # jax_platforms is cpu under the test harness: default-on defers
        assert compile_cache.enable_compilation_cache() is None
        assert compile_cache.cache_dir() is None

    def test_env_dir_enables_even_on_cpu(self, cache_state, tmp_path):
        d = str(tmp_path / "xla_cpu_optin")
        cache_state.setenv("FMT_COMPILE_CACHE", d)
        assert compile_cache.enable_compilation_cache() == d

    def test_idempotent(self, cache_state, tmp_path):
        d = str(tmp_path / "xla")
        assert compile_cache.enable_compilation_cache(d, backend_known=True) == d
        # second call with the same dir is a no-op returning the same dir
        assert compile_cache.enable_compilation_cache(d, backend_known=True) == d
        assert compile_cache.cache_dir() == d

    def test_ensure_for_backend_cpu_noop(self, cache_state):
        assert compile_cache.ensure_compilation_cache_for_backend() is None

    def test_ensure_for_backend_off(self, cache_state):
        cache_state.setenv("FMT_COMPILE_CACHE", "off")
        assert compile_cache.ensure_compilation_cache_for_backend() is None


# -- warm-artifact store (tentpole) -------------------------------------------


def _tiny_compiled():
    x = jnp.arange(8, dtype=jnp.float32)
    s = jnp.float32(2.0)
    f = jax.jit(lambda a, b: a * b + 1.0)
    return f.lower(x, s).compile(), (x, s)


@pytest.fixture
def store(tmp_path):
    st = warmstart.WarmstartStore(str(tmp_path / "warm_aot"))
    yield st


class TestWarmstartStore:
    def test_save_load_roundtrip(self, store):
        compiled, args = _tiny_compiled()
        key = store.entry_key("scaler", 8, 1, "float32", extra="t0")
        assert store.save(key, compiled)
        loaded = store.load(key)
        assert loaded is not None
        np.testing.assert_array_equal(
            np.asarray(loaded(*args)), np.asarray(compiled(*args))
        )
        c = _counters()
        assert c.get("warmstart.saves", 0) >= 1
        assert c.get("warmstart.hits", 0) >= 1
        # entry file + CRC sidecar both on disk
        p = store.entry_path(key)
        assert os.path.exists(p)
        assert os.path.exists(integrity.commit_path(p))

    def test_missing_entry_is_miss(self, store):
        assert store.load(store.entry_key("nope", 1, 1, "float32")) is None
        c = _counters()
        assert c.get("warmstart.misses", 0) >= 1
        assert c.get("warmstart.degraded", 0) == 0

    def test_corrupt_entry_degrades_not_raises(self, store):
        compiled, _ = _tiny_compiled()
        key = store.entry_key("k", 8, 1, "float32")
        assert store.save(key, compiled)
        p = store.entry_path(key)
        raw = bytearray(open(p, "rb").read())
        raw[len(raw) // 2] ^= 0xFF
        with open(p, "wb") as f:
            f.write(bytes(raw))
        assert store.load(key) is None  # degrade, never a wrong answer
        c = _counters()
        assert c.get("warmstart.degraded", 0) >= 1
        assert c.get("warmstart.degraded.corrupt", 0) >= 1
        kinds = [e.get("kind") for e in obs.flight.events()]
        assert "warmstart.degraded" in kinds

    def test_torn_write_detected(self, store):
        compiled, _ = _tiny_compiled()
        key = store.entry_key("k", 8, 1, "float32")
        assert store.save(key, compiled)
        p = store.entry_path(key)
        # simulate a torn write: entry landed but the commit record did not
        os.remove(integrity.commit_path(p))
        assert store.load(key) is None
        assert _counters().get("warmstart.degraded.torn", 0) >= 1

    def test_truncated_entry_detected(self, store):
        compiled, _ = _tiny_compiled()
        key = store.entry_key("k", 8, 1, "float32")
        assert store.save(key, compiled)
        p = store.entry_path(key)
        with open(p, "r+b") as f:
            f.truncate(os.path.getsize(p) // 2)
        assert store.load(key) is None
        assert _counters().get("warmstart.degraded.corrupt", 0) >= 1

    def test_fingerprint_mismatch_degrades(self, store):
        key = store.entry_key("k", 8, 1, "float32")
        blob = pickle.dumps({
            "fmt": warmstart.ENTRY_FORMAT,
            "fingerprint": "0" * 12,
            "key": key,
            "payload": b"",
            "in_tree": None,
            "out_tree": None,
        })
        with integrity.AtomicFile(store.entry_path(key)) as f:
            f.write(blob)
        assert store.load(key) is None
        assert _counters().get("warmstart.degraded.fingerprint", 0) >= 1

    def test_gc_evicts_stale_fingerprints(self, store):
        compiled, _ = _tiny_compiled()
        key = store.entry_key("k", 8, 1, "float32")
        assert store.save(key, compiled)
        stale = os.path.join(store.root, "deadbeef0000")
        os.makedirs(stale, exist_ok=True)
        with open(os.path.join(stale, "old.aot"), "wb") as f:
            f.write(b"x" * 4096)
        evicted = store.gc(max_bytes=1024)
        assert evicted >= 1
        assert not os.path.exists(stale)  # stale fingerprints go first
        assert _counters().get("warmstart.gc_evictions", 0) >= 1

    def test_fault_injection_points(self, store):
        from flink_ml_tpu.fault import injection

        compiled, _ = _tiny_compiled()
        key = store.entry_key("k", 8, 1, "float32")
        injection.configure("warmstart.save@1")
        try:
            assert store.save(key, compiled) is False  # degraded, no raise
        finally:
            injection.reset()
        assert _counters().get("fault.injected.warmstart.save", 0) == 1

        assert store.save(key, compiled)
        injection.configure("warmstart.load@1")
        try:
            assert store.load(key) is None  # falls back to recompile
        finally:
            injection.reset()
        assert _counters().get("fault.injected.warmstart.load", 0) == 1

    def test_manifest_seal(self, store):
        compiled, _ = _tiny_compiled()
        k1 = store.entry_key("a", 8, 1, "float32")
        k2 = store.entry_key("b", 32, 1, "float32")
        store.save(k1, compiled)
        store.save(k2, compiled)
        mp = store.seal_manifest()
        assert mp and os.path.exists(mp)
        man = store.manifest()
        assert man["fingerprint"] == store.fingerprint
        assert set(man["entries"]) == {k1, k2}

    def test_concurrent_writer_tmp_is_unique(self, tmp_path):
        # last-writer-wins coordination relies on per-writer tmp names
        p = str(tmp_path / "e.aot")
        af = integrity.AtomicFile(p, unique_tmp=True)
        assert str(os.getpid()) in af._tmp


# -- fused lookup-before-compile ----------------------------------------------


def _fit_scaler_model(tmp_path):
    from flink_ml_tpu.api.pipeline import Pipeline
    from flink_ml_tpu.lib.feature import MinMaxScaler, StandardScaler
    from flink_ml_tpu.table.schema import DataTypes, Schema
    from flink_ml_tpu.table.table import Table

    rng = np.random.RandomState(7)
    X = rng.randn(64, 5).astype(np.float32)
    t = Table.from_columns(
        Schema.of(("features", DataTypes.DENSE_VECTOR)), {"features": X}
    )
    model = Pipeline([
        StandardScaler().set_selected_col("features"),
        MinMaxScaler().set_selected_col("features"),
    ]).fit(t)
    return model, t


class TestLookupBeforeCompile:
    def test_second_plan_hits_warm_artifact(self, tmp_path):
        model, t = _fit_scaler_model(tmp_path)
        warmstart.configure(str(tmp_path / "warm_aot"))
        try:
            out1 = model.transform(t)[0]
            assert _counters().get("warmstart.saves", 0) >= 1
            # a fresh plan (fresh FusedRun, as a respawned replica builds)
            # must load the persisted executable instead of compiling
            d = str(tmp_path / "m")
            model.save(d)
            from flink_ml_tpu.api.pipeline import PipelineModel

            obs.reset()
            m2 = PipelineModel.load(d)
            out2 = m2.transform(t)[0]
            c = _counters()
            assert c.get("warmstart.hits", 0) >= 1
            assert c.get("warmstart.compile_skips", 0) >= 1
            np.testing.assert_array_equal(
                np.asarray(out1.col("features"), dtype=np.float64),
                np.asarray(out2.col("features"), dtype=np.float64),
            )
        finally:
            warmstart.configure(None)

    def test_inactive_store_means_no_counters(self, tmp_path):
        model, t = _fit_scaler_model(tmp_path)
        assert warmstart.active() is None
        model.transform(t)[0].col("features")
        c = _counters()
        assert c.get("warmstart.saves", 0) == 0
        assert c.get("warmstart.hits", 0) == 0


# -- ladder warmup in deploy (satellite 3) ------------------------------------


class TestLadderWarmup:
    def test_deploy_walks_bounded_ladder(self, tmp_path, monkeypatch):
        from flink_ml_tpu.serving.versioning import VersionManager

        monkeypatch.setenv("FMT_WARM_LADDER_MAX", "3")
        model, t = _fit_scaler_model(tmp_path)
        warm = t.slice_rows(0, 8)
        warmstart.configure(str(tmp_path / "warm_aot"))
        try:
            vm = VersionManager()
            vm.deploy(model, "v1", warmup=warm)
            c = _counters()
            # rungs 1 and 32 beyond the 8-row live sample, bounded at 3
            assert c.get("serving.warm_ladder_rungs", 0) == 2
            # the sealed manifest is on disk after the swap
            assert warmstart.active().manifest()["entries"]
        finally:
            warmstart.configure(None)

    def test_ladder_disabled_at_zero(self, tmp_path, monkeypatch):
        from flink_ml_tpu.serving.versioning import VersionManager

        monkeypatch.setenv("FMT_WARM_LADDER_MAX", "0")
        model, t = _fit_scaler_model(tmp_path)
        warmstart.configure(str(tmp_path / "warm_aot"))
        try:
            vm = VersionManager()
            vm.deploy(model, "v1", warmup=t.slice_rows(0, 8))
            assert _counters().get("serving.warm_ladder_rungs", 0) == 0
        finally:
            warmstart.configure(None)


# -- replica spawn env propagation (satellite 2) ------------------------------


class TestSpawnEnvPropagation:
    def test_cache_dirs_ride_to_children(self, tmp_path, monkeypatch):
        from flink_ml_tpu.serving import replica as replica_mod

        monkeypatch.setattr(
            compile_cache, "_enabled_dir", str(tmp_path / "xla")
        )
        warmstart.configure(str(tmp_path / "warm_aot"))
        try:
            env = {}
            replica_mod._cache_env(env)
            assert env["FMT_COMPILE_CACHE"] == str(tmp_path / "xla")
            assert env["FMT_WARM_DIR"] == str(tmp_path / "warm_aot")
        finally:
            warmstart.configure(None)

    def test_noop_when_nothing_enabled(self, monkeypatch):
        from flink_ml_tpu.serving import replica as replica_mod

        monkeypatch.setattr(compile_cache, "_enabled_dir", None)
        assert warmstart.active() is None
        env = {}
        replica_mod._cache_env(env)
        assert "FMT_COMPILE_CACHE" not in env
        assert "FMT_WARM_DIR" not in env
