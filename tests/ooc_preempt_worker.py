"""Worker for the streamed out-of-core kill-and-resume test (ISSUE 3).

Run as: python ooc_preempt_worker.py <phase> <ckpt_dir>

Phase ``plain``: run a checkpointed streamed (out-of-core) dense fit to
completion and print the final parameters.  Phase ``crash``: the same fit,
but a real SIGTERM is delivered to the process MID-EPOCH (from a hook in
the chunk stream, so the timing is deterministic); the preemption guard
finishes the epoch, commits an emergency checkpoint, and exits cleanly
with code 0 — the worker never reaches the final print.  Phase ``resume``:
the same fit over the same checkpoint dir; the existing resume path
continues from the emergency snapshot to completion and prints the final
parameters, which the parent asserts are BIT-IDENTICAL to the ``plain``
run's (the distributed_resume_worker covers the resident path; this covers
the streamed engine the ROADMAP's Criteo-scale story depends on).
"""

import os
import sys

phase = sys.argv[1]
ckpt_dir = sys.argv[2]

os.environ.setdefault("FLINK_ML_TPU_COMPILE_CACHE", "off")
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=8"
)
os.environ.setdefault("JAX_ENABLE_X64", "1")

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import signal  # noqa: E402

import numpy as np  # noqa: E402

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from flink_ml_tpu.table.sources import ChunkedTable, CollectionSource  # noqa: E402

ROWS, DIM, CHUNK_ROWS = 256, 5, 64
N_CHUNKS = ROWS // CHUNK_ROWS


class SigtermMidEpoch(ChunkedTable):
    """Deliver a real SIGTERM to this process while the ``kill_at``-th
    chunk of the stream is being consumed — deterministically mid-epoch."""

    def __init__(self, source, chunk_rows, kill_at):
        super().__init__(source, chunk_rows)
        self._served = 0
        self._kill_at = kill_at

    def chunks(self):
        for t in super().chunks():
            self._served += 1
            if self._served == self._kill_at:
                os.kill(os.getpid(), signal.SIGTERM)
            yield t


def make_table():
    from flink_ml_tpu.table.schema import Schema

    rng = np.random.RandomState(11)
    X = rng.randn(ROWS, DIM)
    y = (X @ rng.randn(DIM) > 0).astype(np.float64)
    rows = [tuple(X[i]) + (y[i],) for i in range(ROWS)]
    schema = Schema(
        [f"f{i}" for i in range(DIM)] + ["label"], ["double"] * (DIM + 1)
    )
    source = CollectionSource(rows, schema)
    if phase == "crash":
        # chunk N_CHUNKS+2 is consumed mid-epoch-2: the guard must finish
        # the epoch, snapshot, and exit before epoch 3 dispatches
        return SigtermMidEpoch(source, CHUNK_ROWS, kill_at=N_CHUNKS + 2)
    return ChunkedTable(source, CHUNK_ROWS)


def fit(table):
    from flink_ml_tpu.lib import LogisticRegression

    est = (
        LogisticRegression()
        .set_feature_cols([f"f{i}" for i in range(DIM)])
        .set_label_col("label").set_prediction_col("pred")
        .set_learning_rate(0.5).set_max_iter(6)
        .set_global_batch_size(32)
        .set_checkpoint_dir(ckpt_dir).set_checkpoint_interval(1)
    )
    return est.fit(table)


model = fit(make_table())
w = model.coefficients()
b = model.intercept()
print(
    "PARAMS " + " ".join(f"{v:.17g}" for v in list(w) + [b]),
    flush=True,
)
