"""fmtlint (flink_ml_tpu.analysis): checker fixtures, baseline semantics,
the repo self-check, and the lock-discipline race its LOCK rules caught.

The fixture corpus lives in ``tests/fixtures/analysis/``: one bad and one
good module per checker family.  Bad modules must produce exactly their
advertised rule ids; good modules must produce none — both directions,
so a checker that goes blind AND a checker that starts screaming are
each a red test.
"""

import ast
import json
import os
import subprocess
import sys
import threading

import pytest

from flink_ml_tpu.analysis import (
    apply_baseline,
    load_baseline,
    load_project,
    run_checkers,
)
from flink_ml_tpu.analysis.checkers import CHECKERS, RULES
from flink_ml_tpu.analysis.core import REPO_ROOT, Module, Project, Suppression
from flink_ml_tpu.utils import knobs

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures", "analysis")


def run_on(*fixture_names):
    """Analyzer findings restricted to the named fixture files."""
    paths = [os.path.join(FIXTURES, n) for n in fixture_names]
    project, parse_findings = load_project(extra_paths=paths)
    assert not parse_findings
    wanted = {f"tests/fixtures/analysis/{n}" for n in fixture_names}
    return [f for f in run_checkers(project, CHECKERS) if f.file in wanted]


def synth_project(sources, docs=None):
    """A Project built from {rel_path: source} strings (no filesystem)."""
    modules = [Module(path="/" + rel, rel=rel, tree=ast.parse(src),
                      source=src)
               for rel, src in sources.items()]
    return Project("/", modules, docs or {"README.md": "", "BASELINE.md": ""})


class TestKnobsModule:
    def test_every_declaration_unique_and_typed(self):
        names = [k.name for k in knobs.DECLARATIONS]
        assert len(names) == len(set(names))
        assert all(k.type in ("bool", "int", "float", "str")
                   for k in knobs.DECLARATIONS)
        assert all(k.doc for k in knobs.DECLARATIONS)

    def test_bool_default_bias(self, monkeypatch):
        # default-off knobs turn on only for explicit truthy values
        monkeypatch.setenv("FMT_OBS", "garbage")
        assert knobs.knob_bool("FMT_OBS") is False
        monkeypatch.setenv("FMT_OBS", "on")
        assert knobs.knob_bool("FMT_OBS") is True
        # default-on knobs turn off only for explicit falsy values
        monkeypatch.setenv("FMT_GUARD", "garbage")
        assert knobs.knob_bool("FMT_GUARD") is True
        monkeypatch.setenv("FMT_GUARD", "off")
        assert knobs.knob_bool("FMT_GUARD") is False

    def test_numeric_knobs_degrade_to_default(self, monkeypatch):
        monkeypatch.setenv("FMT_RETRY_ATTEMPTS", "not-a-number")
        assert knobs.knob_int("FMT_RETRY_ATTEMPTS") == 3
        monkeypatch.setenv("FMT_SLO_WINDOW_S", "")
        assert knobs.knob_float("FMT_SLO_WINDOW_S") == 30.0
        monkeypatch.setenv("FMT_SERVING_MAX_BATCH", "64")
        assert knobs.knob_int("FMT_SERVING_MAX_BATCH") == 64

    def test_bool_knobs_strip_whitespace(self, monkeypatch):
        monkeypatch.setenv("FMT_DRIFT", "true ")
        assert knobs.knob_bool("FMT_DRIFT") is True
        monkeypatch.setenv("FMT_GUARD", " 0\n")
        assert knobs.knob_bool("FMT_GUARD") is False

    def test_int_knobs_accept_float_form(self, monkeypatch):
        # the serving sites historically parsed via int(_env_float(...))
        monkeypatch.setenv("FMT_SERVING_QUEUE_CAP", "8192.0")
        assert knobs.knob_int("FMT_SERVING_QUEUE_CAP") == 8192
        monkeypatch.setenv("FMT_SERVING_QUEUE_CAP", "1e4")
        assert knobs.knob_int("FMT_SERVING_QUEUE_CAP") == 10000

    def test_flight_events_default_matches_ring(self):
        from flink_ml_tpu.obs import flight

        assert knobs.knob_int("FMT_FLIGHT_EVENTS") == \
            flight._DEFAULT_CAPACITY == 512

    def test_undeclared_name_raises(self):
        with pytest.raises(KeyError, match="undeclared knob"):
            knobs.raw("FMT_DOES_NOT_EXIST")

    def test_str_knob_and_raw(self, monkeypatch):
        monkeypatch.delenv("FMT_TELEMETRY_HOST", raising=False)
        assert knobs.knob_str("FMT_TELEMETRY_HOST") == "127.0.0.1"
        assert knobs.raw("FMT_TELEMETRY_HOST") is None
        monkeypatch.setenv("FMT_TELEMETRY_HOST", "0.0.0.0")
        assert knobs.knob_str("FMT_TELEMETRY_HOST") == "0.0.0.0"


class TestJitPurity:
    def test_bad_fixture_fires_every_rule(self):
        findings = run_on("jit_bad.py")
        rules = {f.rule for f in findings}
        assert rules == {"JIT001", "JIT002", "JIT003"}
        messages = " | ".join(f.message for f in findings)
        assert "time.time()" in messages
        assert "print()" in messages
        assert "metric mutation obs.counter_add()" in messages
        assert "np.asarray()" in messages          # the fused closure
        assert "donate_argnames names 'missing'" in messages

    def test_good_fixture_is_clean(self):
        assert run_on("jit_good.py") == []

    def test_transitive_host_effect_attributed_to_root(self):
        findings = run_on("jit_bad.py")
        decorated = [f for f in findings
                     if "@jax.jit" in f.message and f.rule == "JIT001"]
        # the impure helper is one call deep from the decorated root
        assert decorated and all(f.symbol == "_impure_step"
                                 for f in decorated)


class TestLockDiscipline:
    def test_bad_fixture(self):
        findings = run_on("lock_bad.py")
        assert {(f.rule, f.symbol) for f in findings} == {
            ("LOCK002", "Racy.peek"), ("LOCK001", "Racy.reset")}

    def test_good_fixture_is_clean(self):
        assert run_on("lock_good.py") == []


class TestKnobChecker:
    def test_bad_fixture(self):
        findings = run_on("knob_bad.py")
        by_rule = {}
        for f in findings:
            by_rule.setdefault(f.rule, []).append(f.message)
        # .get + subscript + `from os import environ` + `from os import
        # getenv` — the aliased spellings must not evade the gate
        assert len(by_rule.pop("KNOB001")) == 4
        assert "FMT_NOT_A_REAL_KNOB" in by_rule.pop("KNOB002")[0]
        assert not by_rule

    def test_good_fixture_is_clean(self):
        assert run_on("knob_good.py") == []

    def test_dead_and_undocumented_knobs(self):
        knobs_src = (
            "def declare(*a): pass\n"
            "class Knob:\n"
            "    def __init__(self, *a): pass\n"
            'DECLARATIONS = (Knob("FMT_ALPHA", "1", "bool", "doc"),\n'
            '                Knob("FMT_BETA", "0", "bool", "doc"),\n'
            '                Knob("FMT_ALPHA", "1", "bool", "dup"))\n')
        reader = ("from flink_ml_tpu.utils import knobs\n"
                  'X = knobs.knob_bool("FMT_ALPHA")\n')
        project = synth_project(
            {"flink_ml_tpu/utils/knobs.py": knobs_src,
             "flink_ml_tpu/reader.py": reader},
            docs={"README.md": "`FMT_ALPHA` and `FMT_GONE`",
                  "BASELINE.md": ""})
        findings = run_checkers(project, CHECKERS)
        rules = {(f.rule, f.message.split("'")[1]) for f in findings
                 if f.rule.startswith("KNOB")}
        assert ("KNOB006", "FMT_ALPHA") in rules        # duplicate decl
        assert ("KNOB003", "FMT_BETA") in rules         # dead knob
        assert ("KNOB004", "FMT_BETA") in rules         # undocumented
        assert ("KNOB005", "FMT_GONE") in rules         # doc drift


class TestHygiene:
    def test_bad_fixture(self):
        findings = run_on("hygiene_bad.py")
        rules = sorted(f.rule for f in findings)
        assert rules == ["METRIC001", "METRIC002", "METRIC002",
                         "SCOPE001", "SCOPE001"]

    def test_good_fixture_is_clean(self):
        assert run_on("hygiene_good.py") == []


class TestBaseline:
    def test_missing_reason_is_meta_finding(self, tmp_path):
        path = tmp_path / "baseline.json"
        path.write_text(json.dumps({"suppressions": [
            {"rule": "LOCK002", "file": "x.py", "match": "y", "reason": " "},
        ]}))
        entries, findings = load_baseline(str(path))
        assert entries == []
        assert [f.rule for f in findings] == ["META001"]
        assert "written reason" in findings[0].message

    def test_non_object_entries_are_meta_findings_not_crashes(self, tmp_path):
        path = tmp_path / "baseline.json"
        path.write_text(json.dumps({"suppressions": [
            "oops",
            {"rule": "KNOB001", "file": "x.py", "match": "y",
             "reason": "a genuine reason that is long enough"},
        ]}))
        entries, findings = load_baseline(str(path))
        assert [e.rule for e in entries] == ["KNOB001"]
        assert [f.rule for f in findings] == ["META001"]
        path.write_text(json.dumps({"suppressions": "all of them"}))
        entries, findings = load_baseline(str(path))
        assert entries == [] and [f.rule for f in findings] == ["META001"]

    def test_match_suppresses_and_unused_reported(self):
        findings = run_on("lock_bad.py")
        entries = [
            Suppression("LOCK002", "tests/fixtures/analysis/lock_bad.py",
                        "'_count'", "fixture"),
            Suppression("LOCK001", "tests/fixtures/analysis/lock_bad.py",
                        "'_never_matches'", "stale"),
        ]
        kept, suppressed, unused = apply_baseline(findings, entries)
        assert [f.rule for f in suppressed] == ["LOCK002"]
        assert [f.rule for f in kept] == ["LOCK001"]
        assert [e.match for e in unused] == ["'_never_matches'"]

    def test_match_can_key_on_symbol(self):
        findings = run_on("lock_bad.py")
        entries = [Suppression(
            "LOCK002", "tests/fixtures/analysis/lock_bad.py",
            "(Racy.peek)", "symbol-keyed")]
        _kept, suppressed, _unused = apply_baseline(findings, entries)
        assert [f.symbol for f in suppressed] == ["Racy.peek"]

    def test_committed_baseline_reasons_are_substantive(self):
        entries, findings = load_baseline()
        assert not findings
        assert entries, "committed baseline should document its FPs"
        for entry in entries:
            assert len(entry.reason) > 40, (
                f"suppression {entry.rule}/{entry.match} needs a real "
                f"written reason, not a token")


class TestRepoSelfCheck:
    """The acceptance gate: clean at HEAD, red on a seeded violation."""

    def _kept(self, extra=()):
        project, parse_findings = load_project(extra_paths=extra)
        findings = parse_findings + run_checkers(project, CHECKERS)
        entries, meta = load_baseline()
        kept, _suppressed, _unused = apply_baseline(findings, entries)
        return kept + meta

    def test_repo_is_clean_at_head(self):
        kept = self._kept()
        assert kept == [], "\n".join(f.format() for f in kept)

    def test_seeded_violation_fails(self, tmp_path):
        bad = tmp_path / "seeded.py"
        bad.write_text(
            "import os\n"
            "import threading\n\n\n"
            "def read():\n"
            "    return os.environ.get('FMT_OBS')\n\n\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._n = 0\n\n"
            "    def inc(self):\n"
            "        with self._lock:\n"
            "            self._n += 1\n\n"
            "    def peek(self):\n"
            "        return self._n\n")
        kept = self._kept(extra=[str(bad)])
        assert {f.rule for f in kept} == {"KNOB001", "LOCK002"}

    def test_cli_check_exits_zero_on_repo(self):
        proc = subprocess.run(
            [sys.executable, "-m", "flink_ml_tpu.analysis", "--check",
             "--json", "--no-report"],
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=120)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        payload = json.loads(proc.stdout)
        assert payload["ok"] is True
        assert payload["findings"] == 0
        assert payload["files_scanned"] > 90
        assert payload["suppressed"] >= 1

    def test_cli_check_fails_on_seeded_package_violation(self):
        seeded = os.path.join(REPO_ROOT, "flink_ml_tpu",
                              "_fmtlint_seeded_violation.py")
        with open(seeded, "w") as fh:
            fh.write("import os\nBAD = os.environ.get('FMT_OBS')\n")
        try:
            proc = subprocess.run(
                [sys.executable, "-m", "flink_ml_tpu.analysis", "--check",
                 "--json", "--no-report"],
                cwd=REPO_ROOT, capture_output=True, text=True, timeout=120)
            assert proc.returncode == 1, proc.stdout + proc.stderr
            payload = json.loads(proc.stdout)
            assert payload["rules"].get("KNOB001") == 1
        finally:
            os.remove(seeded)

    def test_rule_table_documents_every_rule(self):
        emitted = set()
        for f in run_on("jit_bad.py", "lock_bad.py", "knob_bad.py",
                        "hygiene_bad.py"):
            emitted.add(f.rule)
        assert emitted <= set(RULES)
        for rule in ("JIT001", "JIT002", "JIT003", "LOCK001", "LOCK002",
                     "KNOB001", "KNOB002", "KNOB003", "KNOB004", "KNOB005",
                     "KNOB006", "SCOPE001", "METRIC001", "METRIC002",
                     "META001", "META002"):
            assert rule in RULES


class TestAnalysisReportLine:
    def test_check_report_follows_fmt_obs_reports(self, tmp_path,
                                                  monkeypatch):
        # the analyzer's report must land where obs --check will look
        from flink_ml_tpu.analysis.__main__ import default_report_dir
        from flink_ml_tpu.obs.report import reports_dir

        monkeypatch.setenv("FMT_OBS_REPORTS", str(tmp_path))
        assert default_report_dir() == str(tmp_path) == reports_dir()
        monkeypatch.delenv("FMT_OBS_REPORTS")
        assert default_report_dir() == os.path.join(REPO_ROOT, "reports")

    def test_obs_check_reads_analysis_report(self, tmp_path):
        from flink_ml_tpu.obs.report import analysis_summary

        payload = {"kind": "analysis", "ok": True, "findings": 0,
                   "suppressed": 4, "files_scanned": 98, "rules": {}}
        (tmp_path / "analysis.json").write_text(json.dumps(payload))
        got = analysis_summary(str(tmp_path))
        assert got == payload

    def test_absent_or_malformed_report_is_none(self, tmp_path):
        from flink_ml_tpu.obs.report import analysis_summary

        assert analysis_summary(str(tmp_path)) is None
        (tmp_path / "analysis.json").write_text("{not json")
        assert analysis_summary(str(tmp_path)) is None


class TestDriftRollRace:
    """The genuine LOCK finding fmtlint caught in DriftMonitor.roll():
    the persist decision was computed under the lock but *claimed*
    outside it, so two dispatcher threads rolling past the reference
    freeze together could both write the reference sidecar (and read
    ``_persist_path``/``_persisted`` bare while at it).  Red before the
    fix: ``save`` ran twice and the reference-complete flight event
    recorded twice."""

    def _frozen_monitor(self, monkeypatch, tmp_path):
        from flink_ml_tpu.obs import drift

        mon = drift.DriftMonitor(name="race", ref_target=1,
                                 persist_path=str(tmp_path / "ref.json"))
        mon._ref_in_rows = 1  # at target: the next roll freezes the ref

        entered = threading.Event()
        release = threading.Event()
        calls = []

        def slow_save(self, path):
            calls.append(path)
            entered.set()
            assert release.wait(5)

        monkeypatch.setattr(drift.DriftMonitor, "save", slow_save)
        return mon, entered, release, calls

    def test_concurrent_rolls_persist_once(self, monkeypatch, tmp_path):
        from flink_ml_tpu.obs import flight

        flight.reset()
        mon, entered, release, calls = self._frozen_monitor(
            monkeypatch, tmp_path)

        t = threading.Thread(target=mon.roll)
        t.start()
        assert entered.wait(5)   # thread A is mid-save, lock released
        mon.roll()               # thread B rolls through the same window
        # B must not have announced on A's behalf: A's save outcome is
        # still unknown, so an announce here would guess at `persisted`
        assert not [e for e in flight.events()
                    if e["kind"] == "drift.reference_complete"]
        release.set()
        t.join(5)
        assert not t.is_alive()

        assert len(calls) == 1, "double persist: the race fmtlint flagged"
        announces = [e for e in flight.events()
                     if e["kind"] == "drift.reference_complete"]
        assert len(announces) == 1
        assert announces[0]["persisted"] is True

    def test_failed_persist_announces_unpersisted(self, monkeypatch,
                                                  tmp_path):
        from flink_ml_tpu.obs import drift, flight

        flight.reset()
        mon = drift.DriftMonitor(name="race2", ref_target=1,
                                 persist_path=str(tmp_path / "ref.json"))
        mon._ref_in_rows = 1

        def failing_save(self, path):
            raise OSError("disk full")

        monkeypatch.setattr(drift.DriftMonitor, "save", failing_save)
        mon.roll()
        announces = [e for e in flight.events()
                     if e["kind"] == "drift.reference_complete"]
        assert len(announces) == 1
        assert announces[0]["persisted"] is False
        assert mon._persisted is False
