"""Pallas kernel numerics (interpret mode on the CPU test mesh) and
integration as a drop-in GradFn in the training harness."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flink_ml_tpu.ops.pallas_kernels import glm_grad, make_pallas_grad_fn


def data(n=300, d=28, seed=0):
    rng = np.random.RandomState(seed)
    x = jnp.asarray(rng.randn(n, d), jnp.float32)
    y = jnp.asarray((rng.randn(n) > 0), jnp.float32)
    w = jnp.asarray((rng.rand(n) > 0.1), jnp.float32)  # some zero weights
    wts = jnp.asarray(rng.randn(d), jnp.float32)
    b = jnp.asarray(0.3, jnp.float32)
    return x, y, w, wts, b


def _pallas_cpu_unavailable():
    """Capability probe: can this environment lower the Pallas kernel in
    interpret mode at all?  Legacy JAX builds reject kernel plumbing the
    kernels rely on (e.g. ``ShapeDtypeStruct(..., vma=...)`` predates
    the vma-aware API), which is an ENVIRONMENT limitation, not a
    regression in this repo — those runs should read as named skips in
    tier-1 output, not as 8 failures masking real breakage.  Returns the
    diagnostic string (None when the lowering works).

    Deliberately NARROW: only error signatures known to mean "this JAX
    build lacks the capability" skip — anything else propagates and
    fails collection loudly, because a regression in the kernel code
    itself must never read as an environment skip."""
    try:
        glm_grad(*data(n=8, d=4), interpret=True)
        return None
    except TypeError as exc:
        if "vma" in str(exc):  # pre-vma ShapeDtypeStruct/pallas_call API
            return f"{type(exc).__name__}: {exc}"
        raise
    except (ImportError, NotImplementedError) as exc:
        # no pallas package / no interpret lowering on this backend
        return f"{type(exc).__name__}: {exc}"


_PALLAS_UNAVAILABLE = _pallas_cpu_unavailable()

pytestmark = pytest.mark.skipif(
    _PALLAS_UNAVAILABLE is not None,
    reason=("Pallas CPU lowering unavailable in this environment: "
            f"{_PALLAS_UNAVAILABLE}"),
)


class TestGlmGradKernel:
    @pytest.mark.parametrize("kind", ["logistic", "squared"])
    def test_matches_jnp_reference(self, kind):
        x, y, w, wts, b = data()
        gw, gb, loss, wsum = glm_grad(x, y, w, wts, b, kind=kind, interpret=True)
        logits = x @ wts + b
        if kind == "logistic":
            err = (jax.nn.sigmoid(logits) - y) * w
            ref_loss = jnp.sum(w * (jnp.logaddexp(0.0, logits) - y * logits))
        else:
            err = (logits - y) * w
            ref_loss = 0.5 * jnp.sum(err * (logits - y))
        np.testing.assert_allclose(gw, x.T @ err, rtol=2e-4, atol=2e-4)
        np.testing.assert_allclose(gb, err.sum(), rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(loss, ref_loss, rtol=1e-4)
        np.testing.assert_allclose(wsum, w.sum(), rtol=1e-6)

    def test_row_padding_is_neutral(self):
        """n not a multiple of the tile: padded rows must contribute nothing."""
        x, y, w, wts, b = data(n=130)
        gw_a, *_ = glm_grad(x, y, w, wts, b, interpret=True, tile_rows=64)
        gw_b, *_ = glm_grad(x, y, w, wts, b, interpret=True, tile_rows=512)
        np.testing.assert_allclose(gw_a, gw_b, rtol=1e-5, atol=1e-5)

    def test_wide_d_tile_shrinks_to_vmem_budget(self):
        x, y, w, wts, b = data(n=64, d=3000)
        gw, *_ = glm_grad(x, y, w, wts, b, interpret=True)
        logits = x @ wts + b
        err = (jax.nn.sigmoid(logits) - y) * w
        np.testing.assert_allclose(gw, x.T @ err, rtol=2e-3, atol=2e-3)


class TestPallasGradFnIntegration:
    def test_grad_fn_contract(self):
        """make_pallas_grad_fn satisfies the GradFn contract numerically."""
        x, y, w, wts, b = data()
        grad_fn = make_pallas_grad_fn("logistic", with_intercept=True)
        (g_w, g_b), loss, wsum = grad_fn((wts, b), x, y, w)
        logits = x @ wts + b
        err = (jax.nn.sigmoid(logits) - y) * w
        np.testing.assert_allclose(g_w, x.T @ err, rtol=2e-4, atol=2e-4)
        np.testing.assert_allclose(g_b, err.sum(), rtol=1e-4, atol=1e-4)

        no_b = make_pallas_grad_fn("logistic", with_intercept=False)
        (_, g_b0), *_ = no_b((wts, b), x, y, w)
        assert float(g_b0) == 0.0

    def test_trains_through_harness(self):
        """make_pallas_grad_fn drops into train_glm and converges — runs in
        the CPU CI suite via interpret mode (the grad fn declares
        shard_map_check_vma=False there; strict vma on real TPU).  This was
        the one skipped test through r3 (VERDICT r3 weak #5)."""
        from flink_ml_tpu.lib.common import pack_minibatches, train_glm
        from flink_ml_tpu.parallel.mesh import default_mesh

        rng = np.random.RandomState(1)
        X = rng.randn(160, 4)
        true_w = np.array([1.0, -2.0, 0.5, 0.0])
        y = ((X @ true_w) > 0).astype(np.float64)
        mesh = default_mesh()
        stack = pack_minibatches(X, y, jax.device_count())
        grad_fn = make_pallas_grad_fn("logistic", with_intercept=True)
        result = train_glm(
            (jnp.zeros((4,), jnp.float32), jnp.zeros((), jnp.float32)),
            stack, grad_fn, mesh, learning_rate=0.5, max_iter=60,
        )
        w, b = result.params
        preds = (X @ w + b) > 0
        assert np.mean(preds == y) > 0.9

    def test_trains_through_listener_path(self):
        """The listener/checkpoint epoch path (make_glm_epoch_step ->
        make_data_parallel_step) must also honor the grad fn's vma
        declaration (r4 review finding)."""
        from flink_ml_tpu.iteration.listener import IterationListener
        from flink_ml_tpu.lib.common import pack_minibatches, train_glm
        from flink_ml_tpu.parallel.mesh import default_mesh

        class Counter(IterationListener):
            epochs = 0

            def on_epoch_watermark_incremented(self, epoch, context):
                self.epochs += 1

        rng = np.random.RandomState(3)
        X = rng.randn(128, 4)
        y = ((X @ np.array([1.0, -2.0, 0.5, 0.0])) > 0).astype(np.float64)
        listener = Counter()
        result = train_glm(
            (jnp.zeros((4,), jnp.float32), jnp.zeros((), jnp.float32)),
            pack_minibatches(X, y, jax.device_count()),
            make_pallas_grad_fn("logistic", with_intercept=True),
            default_mesh(), learning_rate=0.5, max_iter=15,
            listeners=[listener],
        )
        assert listener.epochs == result.epochs == 15
        w, b = result.params
        assert np.mean(((X @ w + b) > 0) == y) > 0.9

    def test_matches_jnp_grad_fn_through_harness(self):
        """The pallas-backed fused fit matches the jnp grad fn's fit."""
        from flink_ml_tpu.lib.classification import _log_loss_grads
        from flink_ml_tpu.lib.common import pack_minibatches, train_glm
        from flink_ml_tpu.parallel.mesh import default_mesh

        rng = np.random.RandomState(2)
        X = rng.randn(128, 6)
        y = ((X @ rng.randn(6)) > 0).astype(np.float64)
        mesh = default_mesh()
        stack = pack_minibatches(X, y, jax.device_count(), global_batch_size=32)
        p0 = (jnp.zeros((6,), jnp.float32), jnp.zeros((), jnp.float32))
        rp = train_glm((jnp.copy(p0[0]), jnp.copy(p0[1])), stack,
                       make_pallas_grad_fn("logistic", with_intercept=True),
                       mesh, learning_rate=0.5, max_iter=10)
        rj = train_glm((jnp.copy(p0[0]), jnp.copy(p0[1])), stack,
                       _log_loss_grads(True), mesh,
                       learning_rate=0.5, max_iter=10)
        np.testing.assert_allclose(rp.params[0], rj.params[0],
                                   rtol=5e-4, atol=5e-5)
        np.testing.assert_allclose(rp.params[1], rj.params[1],
                                   rtol=5e-4, atol=5e-5)
