"""Sparse (Criteo-shape) training path tests: the segment-CSR fused loop must
match the dense path on identical data, scale to wide feature spaces without
densifying, and score sparsely at transform time."""

import numpy as np
import pytest

from flink_ml_tpu.lib import LinearRegression, LogisticRegression
from flink_ml_tpu.lib.common import pack_sparse_minibatches
from flink_ml_tpu.ops.vector import DenseVector, SparseVector
from flink_ml_tpu.table.schema import DataTypes, Schema
from flink_ml_tpu.table.table import Table

SCHEMA = Schema.of(("features", DataTypes.SPARSE_VECTOR), ("label", "double"))


def sparse_data(n=300, dim=50, nnz=5, seed=0):
    rng = np.random.RandomState(seed)
    true_w = np.zeros(dim)
    k = min(10, dim)
    true_w[:k] = rng.randn(k) * 2
    vecs, ys = [], []
    for _ in range(n):
        idx = np.sort(rng.choice(dim, nnz, replace=False))
        val = rng.randn(nnz)
        x = np.zeros(dim)
        x[idx] = val
        vecs.append(SparseVector(dim, idx.astype(np.int64), val))
        ys.append(float((x @ true_w) > 0))
    return vecs, np.asarray(ys), true_w


def make_tables(vecs, ys, dim):
    sparse_t = Table.from_columns(SCHEMA, {"features": vecs, "label": ys})
    dense_schema = Schema.of(("features", DataTypes.DENSE_VECTOR), ("label", "double"))
    dense_vecs = [DenseVector(v.to_dense().values) for v in vecs]
    dense_t = Table.from_columns(dense_schema, {"features": dense_vecs, "label": ys})
    return sparse_t, dense_t


class TestPackSparse:
    def test_layout_roundtrip(self):
        vecs, ys, _ = sparse_data(n=10, dim=8, nnz=2)
        s = pack_sparse_minibatches(vecs, ys, n_dev=2, global_batch_size=4)
        assert s.mb == 2 and s.dim == 8
        # reconstruct row 0 from the packed layout
        idx = s.ints[0, 0]
        rid = s.ints[0, 1]
        vals = s.floats[0, : s.nnz_pad]
        x0 = np.zeros(8)
        mask = rid == 0
        np.add.at(x0, idx[mask], vals[mask])
        np.testing.assert_allclose(x0, vecs[0].to_dense().values, rtol=1e-6)
        # y/w segments
        np.testing.assert_allclose(s.floats[0, s.nnz_pad], ys[0])
        assert s.floats[0, s.nnz_pad + s.mb] == 1.0

    def test_padding_rows_have_zero_weight(self):
        vecs, ys, _ = sparse_data(n=5, dim=8, nnz=2)
        s = pack_sparse_minibatches(vecs, ys, n_dev=2, global_batch_size=4)
        w = s.floats[:, s.nnz_pad + s.mb :]
        assert w.sum() == 5.0  # exactly the real rows


class TestSparseLogisticRegression:
    def test_matches_dense_path(self):
        """Same data, same hyperparams: sparse and dense training agree."""
        vecs, ys, _ = sparse_data()
        sparse_t, dense_t = make_tables(vecs, ys, 50)

        def fit(t):
            return (
                LogisticRegression()
                .set_vector_col("features")
                .set_label_col("label")
                .set_prediction_col("pred")
                .set_learning_rate(0.5)
                .set_max_iter(60)
                .set_global_batch_size(64)
                .fit(t)
            )

        ms = fit(sparse_t)
        md = fit(dense_t)
        np.testing.assert_allclose(
            ms.coefficients(), md.coefficients(), rtol=1e-4, atol=1e-5
        )
        np.testing.assert_allclose(ms.intercept(), md.intercept(), atol=1e-5)

    def test_sparse_transform_scores(self):
        vecs, ys, _ = sparse_data(seed=2)
        sparse_t, dense_t = make_tables(vecs, ys, 50)
        model = (
            LogisticRegression()
            .set_vector_col("features")
            .set_label_col("label")
            .set_prediction_col("pred")
            .set_prediction_detail_col("prob")
            .set_learning_rate(0.5)
            .set_max_iter(80)
            .fit(sparse_t)
        )
        (out_s,) = model.transform(sparse_t)
        (out_d,) = model.transform(dense_t)
        np.testing.assert_allclose(
            out_s.col("prob"), out_d.col("prob"), rtol=1e-4, atol=1e-5
        )
        acc = np.mean(np.asarray(out_s.col("pred")) == ys)
        assert acc > 0.85

    def test_wide_feature_space(self):
        """numFeatures pins a dimension far wider than any observed index."""
        vecs, ys, _ = sparse_data(n=100, dim=40, nnz=3, seed=3)
        sparse_t, _ = make_tables(vecs, ys, 40)
        model = (
            LogisticRegression()
            .set_vector_col("features")
            .set_label_col("label")
            .set_prediction_col("pred")
            .set_num_features(1 << 16)
            .set_max_iter(30)
            .set_learning_rate(0.5)
            .fit(sparse_t)
        )
        assert model.coefficients().shape == (1 << 16,)

    def test_tol_early_stop_sparse(self):
        vecs, ys, _ = sparse_data(seed=4)
        sparse_t, _ = make_tables(vecs, ys, 50)
        model = (
            LogisticRegression()
            .set_vector_col("features")
            .set_label_col("label")
            .set_prediction_col("pred")
            .set_learning_rate(1.0)
            .set_max_iter(500)
            .set_tol(1e-4)
            .set_reg(0.1)
            .fit(sparse_t)
        )
        assert model.train_epochs_ < 500


class TestHotColdSplit:
    """Hot/cold sparse training (VERDICT r3 item 1): the top-K frequent
    features stream through a dense MXU slab; the cold tail stays
    segment-CSR.  On the CPU test mesh the slab path runs the identical
    program (bf16 emulated)."""

    def _power_law_data(self, n=400, dim=64, seed=3):
        """Skewed frequencies: features [0, 8) appear in most rows."""
        rng = np.random.RandomState(seed)
        true_w = rng.randn(dim)
        vecs, ys = [], []
        for _ in range(n):
            hot = rng.choice(8, 3, replace=False)
            cold = 8 + rng.choice(dim - 8, 2, replace=False)
            idx = np.sort(np.concatenate([hot, cold]))
            val = np.ones(idx.size)
            x = np.zeros(dim)
            x[idx] = val
            vecs.append(SparseVector(dim, idx.astype(np.int64), val))
            ys.append(float((x @ true_w) > 0))
        return vecs, np.asarray(ys)

    def test_split_conserves_entries_and_picks_frequent(self):
        import jax.numpy as jnp

        from flink_ml_tpu.lib.common import split_hot_cold

        vecs, ys = self._power_law_data()
        s = pack_sparse_minibatches(vecs, ys, n_dev=2, global_batch_size=64)
        h = split_hot_cold(s, hot_k=8, pad_multiple=8,
                           slab_dtype=jnp.float32)
        # the 8 ever-present features become slab positions
        assert h.hot_k == 8
        np.testing.assert_array_equal(np.sort(h.perm[:8]), np.arange(8))
        np.testing.assert_array_equal(h.inv_perm[h.perm], np.arange(s.dim))
        # entry conservation: every valid entry lands exactly once
        valid = (s.ints[:, 1, :] < s.mb).sum()
        hot_n = (h.hot_ints[:, 1, :] < s.mb).sum()
        cold_n = (h.cold.ints[:, 1, :] < s.mb).sum()
        assert hot_n + cold_n == valid
        assert hot_n == 400 * 3 and cold_n == 400 * 2
        # y/w tails preserved
        np.testing.assert_array_equal(
            h.cold.floats[:, h.cold.nnz_pad:], s.floats[:, s.nnz_pad:]
        )

    def test_f32_slab_matches_plain_sparse_fit(self):
        """With an f32 slab the hot/cold program is the same math as the
        plain segment-CSR program (different summation grouping only)."""
        import jax.numpy as jnp

        from flink_ml_tpu.lib.common import (
            split_hot_cold,
            train_glm_sparse,
            train_glm_sparse_hotcold,
        )
        from flink_ml_tpu.parallel.mesh import default_mesh

        vecs, ys = self._power_law_data()
        mesh = default_mesh()
        s = pack_sparse_minibatches(vecs, ys, n_dev=8, global_batch_size=64)
        h = split_hot_cold(s, hot_k=8, pad_multiple=8, slab_dtype=jnp.float32)
        p0 = (jnp.zeros((s.dim,), jnp.float32), jnp.zeros((), jnp.float32))
        rp = train_glm_sparse(
            (jnp.copy(p0[0]), jnp.copy(p0[1])), s, "logistic", mesh,
            learning_rate=0.5, max_iter=15,
        )
        rh = train_glm_sparse_hotcold(
            (jnp.copy(p0[0]), jnp.copy(p0[1])), h, "logistic", mesh,
            learning_rate=0.5, max_iter=15,
        )
        np.testing.assert_allclose(rh.params[0], rp.params[0],
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(rh.params[1], rp.params[1], atol=1e-5)
        np.testing.assert_allclose(rh.losses, rp.losses, rtol=1e-4)

    def test_estimator_hot_split_bf16(self):
        """numHotFeatures routes the fit through the slab path; binary
        feature values are exact in bf16, so predictions agree with the
        plain path."""
        vecs, ys = self._power_law_data(n=500)
        t = Table.from_columns(SCHEMA, {"features": vecs, "label": ys})

        def fit(hot):
            return (
                LogisticRegression().set_vector_col("features")
                .set_label_col("label").set_prediction_col("pred")
                .set_learning_rate(0.5).set_max_iter(40)
                .set_global_batch_size(64).set_num_hot_features(hot)
                .fit(t)
            )

        m_hot = fit(16)
        m_plain = fit(0)
        (ph,) = m_hot.transform(t)
        (pp,) = m_plain.transform(t)
        agree = np.mean(
            np.asarray(ph.col("pred")) == np.asarray(pp.col("pred"))
        )
        assert agree >= 0.98, agree
        acc = np.mean(np.asarray(ph.col("pred")) == ys)
        assert acc > 0.85, acc

    def test_hot_k_covering_all_features(self):
        """hot_k >= dim: everything is hot, the cold stack is empty pads."""
        import jax.numpy as jnp

        import jax

        from flink_ml_tpu.lib.common import split_hot_cold, train_glm_sparse_hotcold
        from flink_ml_tpu.parallel.mesh import create_mesh

        vecs, ys = self._power_law_data(n=200, dim=32)
        s = pack_sparse_minibatches(vecs, ys, n_dev=2, global_batch_size=32)
        h = split_hot_cold(s, hot_k=999, pad_multiple=8, slab_dtype=jnp.float32)
        assert h.hot_k == 32
        assert (h.cold.ints[:, 1, :] < s.mb).sum() == 0
        r = train_glm_sparse_hotcold(
            (jnp.zeros((32,), jnp.float32), jnp.zeros((), jnp.float32)),
            h, "logistic", create_mesh({"data": 2}, jax.devices()[:2]),
            learning_rate=0.5, max_iter=10,
        )
        assert np.all(np.isfinite(r.params[0]))

    def test_checkpoint_resume(self, tmp_path):
        import jax.numpy as jnp

        from flink_ml_tpu.iteration.checkpoint import CheckpointConfig
        from flink_ml_tpu.lib.common import split_hot_cold, train_glm_sparse_hotcold
        from flink_ml_tpu.parallel.mesh import default_mesh

        vecs, ys = self._power_law_data(n=200)
        mesh = default_mesh()
        s = pack_sparse_minibatches(vecs, ys, n_dev=8, global_batch_size=64)
        h = split_hot_cold(s, hot_k=8, pad_multiple=8, slab_dtype=jnp.float32)
        p0 = (jnp.zeros((s.dim,), jnp.float32), jnp.zeros((), jnp.float32))
        full = train_glm_sparse_hotcold(
            (jnp.copy(p0[0]), jnp.copy(p0[1])), h, "logistic", mesh,
            learning_rate=0.5, max_iter=12,
        )
        cfg = CheckpointConfig(directory=str(tmp_path / "ck"), every_n_epochs=5)
        chunked = train_glm_sparse_hotcold(
            (jnp.copy(p0[0]), jnp.copy(p0[1])), h, "logistic", mesh,
            learning_rate=0.5, max_iter=12, checkpoint=cfg,
        )
        np.testing.assert_allclose(chunked.params[0], full.params[0],
                                   rtol=1e-6, atol=1e-7)
        assert chunked.epochs == full.epochs == 12

    def test_dense_features_with_hot_k_rejected(self):
        rng = np.random.RandomState(0)
        X = rng.randn(40, 4)
        schema = Schema.of(("features", DataTypes.DENSE_VECTOR), ("label", "double"))
        t = Table.from_columns(
            schema,
            {"features": [DenseVector(r) for r in X],
             "label": (X[:, 0] > 0).astype(np.float64)},
        )
        with pytest.raises(ValueError, match="sparse vector columns"):
            (
                LogisticRegression().set_vector_col("features")
                .set_label_col("label").set_prediction_col("p")
                .set_num_hot_features(2).fit(t)
            )

    def _ooc_est(self, hot, dim, max_iter=20, **kw):
        est = (
            LogisticRegression().set_vector_col("features")
            .set_label_col("label").set_prediction_col("pred")
            .set_num_features(dim).set_learning_rate(0.5)
            .set_max_iter(max_iter).set_global_batch_size(64)
            .set_num_hot_features(hot)
        )
        for k, v in kw.items():
            getattr(est, f"set_{k}")(v)
        return est

    def test_out_of_core_bit_matches_in_memory(self):
        """Streamed hot/cold training equals the in-memory hot/cold fit
        bit for bit: same permutation (the counting pre-pass sees the same
        entries), same update schedule (step-major packing), same slab
        values (the in-program per-minibatch scatter adds the same bf16
        entries the resident-slab build does)."""
        from flink_ml_tpu.table.sources import ChunkedTable, CollectionSource

        vecs, ys = self._power_law_data(n=400)
        t = Table.from_columns(SCHEMA, {"features": vecs, "label": ys})
        rows = list(zip(vecs, ys))
        m_mem = self._ooc_est(8, 64).fit(t)
        m_ooc = self._ooc_est(8, 64).fit(
            ChunkedTable(CollectionSource(rows, SCHEMA), chunk_rows=96)
        )
        np.testing.assert_array_equal(
            m_ooc.coefficients(), m_mem.coefficients()
        )
        assert m_ooc.intercept() == m_mem.intercept()

    def test_out_of_core_checkpoint_resume(self, tmp_path):
        """A killed-and-resumed streamed hot/cold fit lands on the
        uninterrupted result: the resume re-derives the identical
        permutation from the deterministic counting pre-pass and continues
        from the permuted-space checkpoint."""
        from flink_ml_tpu.table.sources import ChunkedTable, CollectionSource

        vecs, ys = self._power_law_data(n=300)
        rows = list(zip(vecs, ys))

        def chunked():
            return ChunkedTable(CollectionSource(rows, SCHEMA), chunk_rows=64)

        full = self._ooc_est(8, 64, max_iter=12).fit(chunked())
        ck = str(tmp_path / "ck")
        # run half, then resume to completion
        self._ooc_est(8, 64, max_iter=6, checkpoint_dir=ck,
                      checkpoint_interval=3).fit(chunked())
        resumed = self._ooc_est(8, 64, max_iter=12, checkpoint_dir=ck,
                                checkpoint_interval=3).fit(chunked())
        # same tolerance as the plain OOC resume test: a resumed engine
        # re-places loaded host params, which can fuse differently at the
        # sub-ulp level (test_out_of_core.py:164)
        np.testing.assert_allclose(
            resumed.coefficients(), full.coefficients(),
            rtol=1e-6, atol=1e-9,
        )

    def test_out_of_core_checkpoint_rejects_layout_change(self, tmp_path):
        """A permuted-space stream checkpoint must refuse to resume under
        a different hot/cold layout (changed mesh model size permutes the
        same-shaped vector differently — silently wrong without the
        stamp)."""
        from flink_ml_tpu.parallel.mesh import create_mesh
        from flink_ml_tpu.table.sources import ChunkedTable, CollectionSource
        from flink_ml_tpu.utils.environment import MLEnvironmentFactory

        vecs, ys = self._power_law_data(n=200)
        rows = list(zip(vecs, ys))

        def chunked():
            return ChunkedTable(CollectionSource(rows, SCHEMA),
                                chunk_rows=64)

        ck = str(tmp_path / "ck")
        self._ooc_est(8, 64, max_iter=6, checkpoint_dir=ck,
                      checkpoint_interval=3).fit(chunked())
        env = MLEnvironmentFactory.get_default()
        old = env.get_mesh()
        env.set_mesh(create_mesh({"data": 4, "model": 2}))
        try:
            with pytest.raises(ValueError, match="different hot/cold"):
                self._ooc_est(8, 64, max_iter=12, checkpoint_dir=ck,
                              checkpoint_interval=3).fit(chunked())
        finally:
            env.set_mesh(old)

    def test_out_of_core_2d_mesh_matches_1d(self):
        """The full formulation matrix closes: hot/cold + out-of-core +
        feature-sharded (2-D) mesh.  The same streamed blocks feed the
        model-sharded chunk program (shard-local slab densify + masked
        cold + one psum), and predictions match the 1-D streamed fit."""
        from flink_ml_tpu.parallel.mesh import create_mesh
        from flink_ml_tpu.table.sources import ChunkedTable, CollectionSource
        from flink_ml_tpu.utils.environment import MLEnvironmentFactory

        vecs, ys = self._power_law_data(n=300)
        t = Table.from_columns(SCHEMA, {"features": vecs, "label": ys})
        rows = list(zip(vecs, ys))

        def chunked():
            return ChunkedTable(CollectionSource(rows, SCHEMA),
                                chunk_rows=64)

        m1 = self._ooc_est(8, 64).fit(chunked())
        env = MLEnvironmentFactory.get_default()
        old = env.get_mesh()
        env.set_mesh(create_mesh({"data": 4, "model": 2}))
        try:
            m2 = self._ooc_est(8, 64).fit(chunked())
        finally:
            env.set_mesh(old)
        (p1,) = m1.transform(t)
        (p2,) = m2.transform(t)
        agree = np.mean(
            np.asarray(p1.col("pred")) == np.asarray(p2.col("pred"))
        )
        assert agree >= 0.98, agree
        np.testing.assert_allclose(
            m2.coefficients(), m1.coefficients(), rtol=0.05, atol=0.02
        )

    def test_out_of_core_dense_with_hot_k_rejected(self):
        from flink_ml_tpu.table.sources import ChunkedTable, CollectionSource

        rng = np.random.RandomState(0)
        X = rng.randn(40, 4)
        schema = Schema.of(("features", DataTypes.DENSE_VECTOR),
                           ("label", "double"))
        rows = [(DenseVector(r), float(r[0] > 0)) for r in X]
        with pytest.raises(ValueError, match="sparse vector columns"):
            (
                LogisticRegression().set_vector_col("features")
                .set_label_col("label").set_prediction_col("p")
                .set_global_batch_size(16).set_num_hot_features(2)
                .fit(ChunkedTable(CollectionSource(rows, schema),
                                  chunk_rows=16))
            )

    def test_2d_f32_slab_matches_1d(self):
        """Feature-sharded hot/cold training (slab columns + weights over
        the 'model' axis, one psum completing logits) matches the 1-D path
        to f32 rounding — only the summation grouping changes."""
        import jax
        import jax.numpy as jnp

        from flink_ml_tpu.lib.common import (
            split_hot_cold,
            train_glm_sparse_hotcold,
        )
        from flink_ml_tpu.parallel.mesh import create_mesh

        vecs, ys = self._power_law_data()
        s = pack_sparse_minibatches(vecs, ys, n_dev=4, global_batch_size=64)
        p0 = lambda: (  # noqa: E731
            jnp.zeros((s.dim,), jnp.float32), jnp.zeros((), jnp.float32)
        )
        mesh1 = create_mesh({"data": 4}, jax.devices()[:4])
        h1 = split_hot_cold(s, hot_k=8, pad_multiple=8,
                            slab_dtype=jnp.float32)
        r1 = train_glm_sparse_hotcold(
            p0(), h1, "logistic", mesh1, learning_rate=0.5, max_iter=15
        )
        mesh2 = create_mesh({"data": 4, "model": 2})
        h2 = split_hot_cold(s, hot_k=8, pad_multiple=8,
                            slab_dtype=jnp.float32, model_size=2)
        assert h2.dim_pad >= s.dim and h2.hot_k % 2 == 0
        r2 = train_glm_sparse_hotcold(
            p0(), h2, "logistic", mesh2, learning_rate=0.5, max_iter=15
        )
        np.testing.assert_allclose(r2.params[0], r1.params[0],
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(r2.params[1], r1.params[1], atol=1e-6)
        np.testing.assert_allclose(r2.losses, r1.losses, rtol=1e-5)

    def test_2d_rounded_hot_k_dead_columns(self):
        """hot_k not divisible by the model axis rounds up; the dead slab
        columns stay at zero weight."""
        import jax.numpy as jnp

        from flink_ml_tpu.lib.common import (
            split_hot_cold,
            train_glm_sparse_hotcold,
        )
        from flink_ml_tpu.parallel.mesh import create_mesh

        vecs, ys = self._power_law_data(n=200, dim=33)
        s = pack_sparse_minibatches(vecs, ys, n_dev=4, global_batch_size=32)
        h = split_hot_cold(s, hot_k=7, pad_multiple=8,
                           slab_dtype=jnp.float32, model_size=2)
        assert h.hot_k == 8 and h.dim_pad % 2 == 0 and h.dim_pad >= 33
        r = train_glm_sparse_hotcold(
            (jnp.zeros((33,), jnp.float32), jnp.zeros((), jnp.float32)),
            h, "logistic", create_mesh({"data": 4, "model": 2}),
            learning_rate=0.5, max_iter=8,
        )
        assert r.params[0].shape == (33,)
        assert np.all(np.isfinite(r.params[0]))

    def test_model_sharded_mesh_estimator(self):
        """numHotFeatures on a ('data','model') mesh routes through the
        feature-sharded slab path; predictions agree with the 1-D fit."""
        from flink_ml_tpu.parallel.mesh import create_mesh
        from flink_ml_tpu.utils.environment import MLEnvironmentFactory

        vecs, ys = self._power_law_data(n=300)
        t = Table.from_columns(SCHEMA, {"features": vecs, "label": ys})

        def fit():
            return (
                LogisticRegression().set_vector_col("features")
                .set_label_col("label").set_prediction_col("pred")
                .set_learning_rate(0.5).set_max_iter(30)
                .set_global_batch_size(32).set_num_hot_features(8)
                .fit(t)
            )

        m1 = fit()
        env = MLEnvironmentFactory.get_default()
        old = env.get_mesh()
        env.set_mesh(create_mesh({"data": 2, "model": 4}))
        try:
            m2 = fit()
        finally:
            env.set_mesh(old)
        (p1,) = m1.transform(t)
        (p2,) = m2.transform(t)
        agree = np.mean(
            np.asarray(p1.col("pred")) == np.asarray(p2.col("pred"))
        )
        assert agree >= 0.98, agree
        # bf16 slab rounding differs only in grouping: coefficients close
        np.testing.assert_allclose(
            m2.coefficients(), m1.coefficients(), rtol=0.05, atol=0.02
        )


class TestLayoutFloors:
    def test_min_floors_are_schedule_neutral(self):
        """Packing with min_nnz_pad / min_steps floors (the multi-process
        agree_max repack) trains bit-identically to the unfloored pack —
        pad entries carry zero weight and extra steps carry zero rows."""
        import jax.numpy as jnp

        from flink_ml_tpu.lib.common import train_glm_sparse
        from flink_ml_tpu.parallel.mesh import default_mesh

        vecs, ys, _ = sparse_data(n=120, dim=40, nnz=4, seed=8)
        mesh = default_mesh()
        base = pack_sparse_minibatches(vecs, ys, n_dev=8, global_batch_size=32)
        floored = pack_sparse_minibatches(
            vecs, ys, n_dev=8, global_batch_size=32,
            min_nnz_pad=base.nnz_pad * 2, min_steps=base.steps + 3,
        )
        assert floored.nnz_pad == base.nnz_pad * 2
        assert floored.steps == base.steps + 3
        p0 = lambda: (  # noqa: E731
            jnp.zeros((40,), jnp.float32), jnp.zeros((), jnp.float32)
        )
        r1 = train_glm_sparse(p0(), base, "logistic", mesh,
                              learning_rate=0.5, max_iter=10)
        r2 = train_glm_sparse(p0(), floored, "logistic", mesh,
                              learning_rate=0.5, max_iter=10)
        np.testing.assert_array_equal(
            np.asarray(r1.params[0]), np.asarray(r2.params[0])
        )
        np.testing.assert_array_equal(
            np.asarray(r1.params[1]), np.asarray(r2.params[1])
        )

    def test_agree_max_single_process_identity(self):
        from flink_ml_tpu.parallel.mesh import agree_max

        assert agree_max(512, 7) == (512, 7)

    def test_hotcold_floors_and_counts_are_neutral(self):
        """split_hot_cold with explicit (local) counts and the natural pads
        as floors reproduces the default split exactly — the multi-process
        agreement path is a no-op when there is one process."""
        import jax
        import jax.numpy as jnp

        from flink_ml_tpu.lib.common import (
            hotcold_entry_counts,
            hotcold_layout_floors,
            split_hot_cold,
            train_glm_sparse_hotcold,
        )
        from flink_ml_tpu.parallel.mesh import create_mesh

        vecs, ys, _ = sparse_data(n=200, dim=48, nnz=5, seed=12)
        s = pack_sparse_minibatches(vecs, ys, n_dev=4, global_batch_size=32)
        counts = hotcold_entry_counts(s)
        (hp, cp), plan = hotcold_layout_floors(s, 8, counts=counts)
        h_def = split_hot_cold(s, 8, slab_dtype=jnp.float32)
        h_agr = split_hot_cold(s, 8, slab_dtype=jnp.float32, counts=counts,
                               min_hot_pad=hp, min_cold_pad=cp, plan=plan)
        np.testing.assert_array_equal(h_agr.perm, h_def.perm)
        np.testing.assert_array_equal(h_agr.hot_ints, h_def.hot_ints)
        np.testing.assert_array_equal(h_agr.hot_vals, h_def.hot_vals)
        np.testing.assert_array_equal(h_agr.cold.ints, h_def.cold.ints)
        np.testing.assert_array_equal(h_agr.cold.floats, h_def.cold.floats)
        # larger floors widen the pads but keep training identical
        h_wide = split_hot_cold(s, 8, slab_dtype=jnp.float32, counts=counts,
                                min_hot_pad=hp * 2, min_cold_pad=cp * 2)
        assert h_wide.hot_ints.shape[2] == hp * 2
        mesh = create_mesh({"data": 4}, jax.devices()[:4])
        p0 = lambda: (  # noqa: E731
            jnp.zeros((s.dim,), jnp.float32), jnp.zeros((), jnp.float32)
        )
        r1 = train_glm_sparse_hotcold(p0(), h_def, "logistic", mesh,
                                      learning_rate=0.5, max_iter=8)
        r2 = train_glm_sparse_hotcold(p0(), h_wide, "logistic", mesh,
                                      learning_rate=0.5, max_iter=8)
        np.testing.assert_array_equal(
            np.asarray(r1.params[0]), np.asarray(r2.params[0])
        )

    def test_layout_prescan_predicts_pack_exactly(self):
        """sparse_layout_floors must predict the pack's natural layout for
        both column forms — a divergence would hang multi-process runs
        (the estimator asserts this at fit time too)."""
        from flink_ml_tpu.lib.common import (
            sparse_layout_floors,
            sparse_row_counts,
        )
        from flink_ml_tpu.ops.batch import CsrRows

        for n, nnz, gbs in [(120, 4, 32), (37, 2, 0), (64, 7, 16)]:
            vecs, ys, _ = sparse_data(n=n, dim=40, nnz=nnz, seed=n)
            s = pack_sparse_minibatches(vecs, ys, n_dev=4,
                                        global_batch_size=gbs)
            counts = sparse_row_counts(vecs)
            assert sparse_layout_floors(counts, 4, gbs) == (s.nnz_pad, s.steps)
            # CSR column form: same counts, same prediction
            indptr = np.concatenate([[0], np.cumsum(counts)])
            csr = CsrRows(
                40, indptr,
                np.concatenate([v.indices for v in vecs]),
                np.concatenate([v.vals for v in vecs]),
            )
            np.testing.assert_array_equal(sparse_row_counts(csr), counts)


class TestSparseLinearRegression:
    def test_sparse_squared_loss_converges(self):
        rng = np.random.RandomState(5)
        dim = 30
        true_w = np.zeros(dim)
        true_w[:5] = [1.0, -2.0, 3.0, 0.5, -1.5]
        vecs, ys = [], []
        for _ in range(400):
            idx = np.sort(rng.choice(dim, 4, replace=False))
            val = rng.randn(4)
            x = np.zeros(dim)
            x[idx] = val
            vecs.append(SparseVector(dim, idx.astype(np.int64), val))
            ys.append(x @ true_w + 2.0)
        t = Table.from_columns(SCHEMA, {"features": vecs, "label": np.asarray(ys)})
        model = (
            LinearRegression()
            .set_vector_col("features")
            .set_label_col("label")
            .set_prediction_col("pred")
            .set_learning_rate(0.3)
            .set_max_iter(300)
            .fit(t)
        )
        np.testing.assert_allclose(model.coefficients()[:5], true_w[:5], atol=0.1)
        assert abs(model.intercept() - 2.0) < 0.1


class TestSparseValidation:
    def test_out_of_range_index_raises_in_training(self):
        vecs = [SparseVector(100, np.array([50]), np.array([1.0]))]
        t = Table.from_columns(SCHEMA, {"features": vecs, "label": [1.0]})
        with pytest.raises(ValueError, match="out of range"):
            (LogisticRegression().set_vector_col("features")
             .set_label_col("label").set_prediction_col("p")
             .set_num_features(10).set_max_iter(2).fit(t))

    def test_empty_sparse_vector_rows_train(self):
        """An all-zeros sparse row (even with unknown size) is legal."""
        vecs = [
            SparseVector(5, np.array([1]), np.array([2.0])),
            SparseVector(),  # unknown size, zero nnz
            SparseVector(5, np.array([3]), np.array([-1.0])),
        ]
        t = Table.from_columns(
            SCHEMA, {"features": vecs, "label": [1.0, 0.0, 0.0]}
        )
        model = (LogisticRegression().set_vector_col("features")
                 .set_label_col("label").set_prediction_col("p")
                 .set_max_iter(5).fit(t))
        assert model.coefficients().shape == (5,)

    def test_varied_batch_sizes_share_compiled_scorer(self):
        vecs, ys, _ = sparse_data(n=100, dim=20, nnz=3, seed=9)
        t, _ = make_tables(vecs, ys, 20)
        model = (LogisticRegression().set_vector_col("features")
                 .set_label_col("label").set_prediction_col("p")
                 .set_max_iter(10).fit(t))
        # different row counts must not blow up (and should reuse buckets)
        for n in (1, 7, 63, 100):
            (out,) = model.transform(t.slice_rows(0, n))
            assert out.num_rows() == n


class TestNativeMalformed:
    def test_trailing_colon_rejected(self, tmp_path):
        """Regression: 'idx:' at line end must not consume the next label."""
        from flink_ml_tpu import native
        if not native.available():
            pytest.skip("native library not built")
        p = tmp_path / "bad.svm"
        p.write_text("1 2:\n0 3:1.5\n")
        with pytest.raises(ValueError):
            native.read_libsvm(str(p), None, False)


class TestHotColdStreamFormulation:
    """VERDICT r4 #1: the scalable in-memory formulation — slabs densify
    in-program per minibatch (HBM holds O(nnz), never O(rows x hot_k))."""

    def _data(self, n=500, dim=64, seed=3):
        rng = np.random.RandomState(seed)
        true_w = rng.randn(dim)
        vecs, ys = [], []
        for _ in range(n):
            hot = rng.choice(8, 3, replace=False)
            cold = 8 + rng.choice(dim - 8, 2, replace=False)
            idx = np.sort(np.concatenate([hot, cold]))
            x = np.zeros(dim)
            x[idx] = 1.0
            vecs.append(SparseVector(dim, idx.astype(np.int64), np.ones(5)))
            ys.append(float((x @ true_w) > 0))
        return vecs, np.asarray(ys)

    def _fit(self, t, mode, hot=16):
        return (
            LogisticRegression().set_vector_col("features")
            .set_label_col("label").set_prediction_col("pred")
            .set_learning_rate(0.5).set_max_iter(30)
            .set_global_batch_size(64).set_num_hot_features(hot)
            .set_hot_slab_mode(mode)
            .fit(t)
        )

    def test_stream_mode_matches_resident_mode(self):
        vecs, ys = self._data()
        t = Table.from_columns(SCHEMA, {"features": vecs, "label": ys})
        m_res = self._fit(t, "resident")
        m_str = self._fit(t, "stream")
        np.testing.assert_allclose(
            m_str.coefficients(), m_res.coefficients(), rtol=1e-5, atol=1e-7
        )

    def test_auto_mode_picks_stream_over_budget(self, monkeypatch):
        from flink_ml_tpu.lib import common as lc

        calls = {}
        orig = lc.train_glm_sparse_hotcold

        def spy(*a, **kw):
            calls["resident"] = kw.get("resident_slabs")
            return orig(*a, **kw)

        monkeypatch.setattr(
            "flink_ml_tpu.lib.glm.train_glm_sparse_hotcold", spy,
            raising=False,
        )
        # glm imports inside the method; patch at source module
        monkeypatch.setattr(lc, "train_glm_sparse_hotcold", spy)
        vecs, ys = self._data()
        t = Table.from_columns(SCHEMA, {"features": vecs, "label": ys})
        monkeypatch.setenv("FMT_HOT_SLAB_BUDGET_MB", "0")
        self._fit(t, "auto")
        assert calls["resident"] is False
        calls.clear()
        monkeypatch.setenv("FMT_HOT_SLAB_BUDGET_MB", "100000")
        self._fit(t, "auto")
        assert calls["resident"] is True

    def test_stream_mode_2d_matches_1d(self):
        import jax

        from flink_ml_tpu.lib.common import (
            split_hot_cold,
            train_glm_sparse_hotcold,
        )
        from flink_ml_tpu.parallel.mesh import create_mesh

        vecs, ys = self._data(n=300, dim=32)
        mesh = create_mesh({"data": 2, "model": 2},
                           devices=jax.devices()[:4])
        s = pack_sparse_minibatches(vecs, ys, n_dev=2, global_batch_size=32)
        import jax.numpy as jnp

        kw = dict(
            kind="logistic", learning_rate=0.5, max_iter=10, reg=0.0,
            tol=0.0, with_intercept=True, resident_slabs=False,
        )
        h2 = split_hot_cold(s, hot_k=8, pad_multiple=8,
                            slab_dtype=jnp.float32, model_size=2)
        w0 = (jnp.zeros((32,), jnp.float32), jnp.zeros((), jnp.float32))
        r2 = train_glm_sparse_hotcold(w0, h2, mesh=mesh, **kw)
        mesh1 = create_mesh({"data": 2}, devices=jax.devices()[:2])
        h1 = split_hot_cold(s, hot_k=8, pad_multiple=8,
                            slab_dtype=jnp.float32)
        r1 = train_glm_sparse_hotcold(w0, h1, mesh=mesh1, **kw)
        np.testing.assert_allclose(
            np.asarray(r2.params[0]), np.asarray(r1.params[0]),
            rtol=1e-5, atol=1e-7,
        )


def test_unsorted_csr_rows_pack_sorted():
    """CSR columns from file order may carry per-row ids out of order; the
    pack must restore the per-row ascending invariant (the hot-slab
    scatter declares its index tuples sorted)."""
    from flink_ml_tpu.lib.common import pack_sparse_minibatches
    from flink_ml_tpu.ops.batch import CsrRows

    indptr = np.array([0, 3, 5, 8], dtype=np.int64)
    indices = np.array([7, 3, 9, 4, 1, 0, 6, 2], dtype=np.int64)  # unsorted
    values = np.arange(8, dtype=np.float64) + 1.0
    rows = CsrRows(16, indptr, indices, values)
    y = np.array([1.0, 0.0, 1.0])
    s = pack_sparse_minibatches(rows, y, n_dev=1, global_batch_size=4)
    idx = s.ints[0, 0, :]
    rid = s.ints[0, 1, :]
    valid = rid < s.mb
    # per-row ascending after the pack
    for r in range(3):
        ids = idx[valid & (rid == r)]
        assert np.all(np.diff(ids) > 0), ids
    # entries conserved with their values
    got = sorted(zip(idx[valid].tolist(), s.floats[0, : s.nnz_pad][valid].tolist()))
    want = sorted(zip(indices.tolist(), values.tolist()))
    assert got == want


class TestCsrEmptyRowPack:
    """ADVICE r5 high (the tier-1 red test): CSR packing raised IndexError
    whenever the column carried empty trailing rows — interior indptr
    entries equal to nnz_total put nnz_total-1 into the length-(nnz_total-1)
    adjacent-pair mask.  Any libsvm file ending in a featureless row
    crashed the vectorized ingestion path."""

    def _pack_both(self, indptr, indices, values, dim):
        from flink_ml_tpu.lib.common import pack_sparse_minibatches
        from flink_ml_tpu.ops.batch import CsrRows

        indptr = np.asarray(indptr, dtype=np.int64)
        indices = np.asarray(indices, dtype=np.int64)
        values = np.asarray(values, dtype=np.float64)
        n = len(indptr) - 1
        y = np.arange(n, dtype=np.float64)
        csr_stack = pack_sparse_minibatches(
            CsrRows(dim, indptr, indices, values), y, 1, n, dim=dim
        )
        vecs = [
            SparseVector(dim, indices[indptr[i]:indptr[i + 1]],
                         values[indptr[i]:indptr[i + 1]])
            for i in range(n)
        ]
        row_stack = pack_sparse_minibatches(vecs, y, 1, n, dim=dim)
        return csr_stack, row_stack

    def test_trailing_empty_row(self):
        # the ADVICE repro: indptr=[0,2,3,3], fully sorted indices
        s_csr, s_row = self._pack_both(
            [0, 2, 3, 3], [1, 4, 2], [1.0, 2.0, 3.0], dim=8
        )
        np.testing.assert_array_equal(s_csr.ints, s_row.ints)
        np.testing.assert_array_equal(s_csr.floats, s_row.floats)
        assert s_csr.n_rows == 3

    def test_leading_and_interior_empty_rows(self):
        s_csr, s_row = self._pack_both(
            [0, 0, 2, 2, 3], [3, 5, 0], [1.0, 2.0, 3.0], dim=8
        )
        np.testing.assert_array_equal(s_csr.ints, s_row.ints)
        np.testing.assert_array_equal(s_csr.floats, s_row.floats)

    def test_trailing_empty_row_with_unsorted_indices(self):
        # the sort path must also survive empty-row indptr repeats
        s_csr, s_row = self._pack_both(
            [0, 2, 4, 4], [4, 1, 9, 2], [1.0, 2.0, 3.0, 4.0], dim=16
        )
        np.testing.assert_array_equal(s_csr.ints, s_row.ints)
        np.testing.assert_array_equal(s_csr.floats, s_row.floats)

    def test_trailing_empty_rows_train_end_to_end(self):
        from flink_ml_tpu.ops.batch import CsrRows

        rng = np.random.RandomState(3)
        n, dim, nnz = 60, 12, 3
        indptr = [0]
        idx_all, val_all = [], []
        for i in range(n):
            k = 0 if i in (0, n - 1, n - 2) else nnz  # empty head + tail
            idx = np.sort(rng.choice(dim, k, replace=False))
            idx_all.append(idx)
            val_all.append(rng.randn(k))
            indptr.append(indptr[-1] + k)
        rows = CsrRows(
            dim,
            np.asarray(indptr, dtype=np.int64),
            np.concatenate(idx_all).astype(np.int64),
            np.concatenate(val_all),
        )
        y = (rng.randn(n) > 0).astype(np.float64)
        t = Table.from_columns(SCHEMA, {"features": rows, "label": y})
        model = (LogisticRegression().set_vector_col("features")
                 .set_label_col("label").set_prediction_col("p")
                 .set_num_features(dim).set_max_iter(3).fit(t))
        assert model.train_epochs_ >= 1
