"""Checkpoint/resume: interrupted training resumed from a snapshot must
bit-match an uninterrupted run (deterministic data-order replay)."""

import os

import numpy as np
import pytest

from flink_ml_tpu.iteration.checkpoint import (
    latest_checkpoint,
    load_checkpoint,
    prune_checkpoints,
    save_checkpoint,
)
from flink_ml_tpu.lib import LinearRegression
from flink_ml_tpu.table.schema import Schema
from flink_ml_tpu.table.table import Table


def make_table(n=120, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, 2)
    y = X @ np.array([1.5, -0.5]) + 1.0
    schema = Schema.of(("f0", "double"), ("f1", "double"), ("label", "double"))
    return Table.from_columns(
        schema, {"f0": X[:, 0], "f1": X[:, 1], "label": y}
    )


def estimator(ckpt_dir=None, max_iter=10):
    est = (
        LinearRegression()
        .set_feature_cols(["f0", "f1"])
        .set_label_col("label")
        .set_prediction_col("pred")
        .set_learning_rate(0.1)
        .set_max_iter(max_iter)
    )
    if ckpt_dir:
        est.set_checkpoint_dir(str(ckpt_dir))
    return est


class TestCheckpointPrimitives:
    def test_save_load_roundtrip(self, tmp_path):
        params = (np.arange(3.0), np.asarray(2.0))
        save_checkpoint(str(tmp_path), 4, params, meta={"losses": [1.0, 0.5]})
        path = latest_checkpoint(str(tmp_path))
        assert path.endswith("epoch_4.npz")
        loaded, meta = load_checkpoint(path, like=params)
        np.testing.assert_array_equal(loaded[0], params[0])
        assert meta["epoch"] == 4 and meta["losses"] == [1.0, 0.5]

    def test_latest_picks_highest_epoch(self, tmp_path):
        p = (np.zeros(2),)
        for e in (0, 10, 2):
            save_checkpoint(str(tmp_path), e, p)
        assert latest_checkpoint(str(tmp_path)).endswith("epoch_10.npz")

    def test_prune_keeps_newest(self, tmp_path):
        p = (np.zeros(2),)
        for e in range(6):
            save_checkpoint(str(tmp_path), e, p)
        prune_checkpoints(str(tmp_path), keep=2)
        names = sorted(os.listdir(str(tmp_path)))
        assert "epoch_4.npz" in names and "epoch_5.npz" in names
        assert "epoch_0.npz" not in names

    def test_structure_mismatch_raises(self, tmp_path):
        save_checkpoint(str(tmp_path), 0, (np.zeros(2),))
        with pytest.raises(ValueError, match="leaves"):
            load_checkpoint(
                latest_checkpoint(str(tmp_path)), like=(np.zeros(2), np.zeros(1))
            )


class TestResumeTraining:
    def test_resume_matches_uninterrupted(self, tmp_path):
        t = make_table()
        # uninterrupted 10-epoch run (no checkpointing -> fused path)
        full = estimator(max_iter=10).fit(t)

        # interrupted: 4 epochs with snapshots, then resume to 10
        ckpt = tmp_path / "ckpt"
        part = estimator(ckpt, max_iter=4).fit(t)
        assert latest_checkpoint(str(ckpt)) is not None
        resumed = estimator(ckpt, max_iter=10).fit(t)

        assert resumed.train_epochs_ == 10
        np.testing.assert_allclose(
            resumed.coefficients(), full.coefficients(), rtol=1e-6
        )
        np.testing.assert_allclose(resumed.intercept(), full.intercept(), rtol=1e-6)

    def test_resume_past_max_iter_is_noop(self, tmp_path):
        t = make_table()
        ckpt = tmp_path / "ckpt"
        m1 = estimator(ckpt, max_iter=5).fit(t)
        m2 = estimator(ckpt, max_iter=3).fit(t)  # already past 3 epochs
        assert m2.train_epochs_ == 5
        np.testing.assert_allclose(m2.coefficients(), m1.coefficients())

    def test_checkpoint_interval(self, tmp_path):
        t = make_table()
        ckpt = tmp_path / "ckpt"
        est = estimator(ckpt, max_iter=9).set_checkpoint_interval(3)
        est.fit(t)
        epochs = sorted(
            int(n.split("_")[1].split(".")[0])
            for n in os.listdir(str(ckpt))
            if n.endswith(".npz")
        )
        assert epochs == [2, 5, 8]


class TestSparseCheckpoint:
    def test_sparse_resume_matches_uninterrupted(self, tmp_path):
        from flink_ml_tpu.lib import LogisticRegression
        from flink_ml_tpu.ops.vector import SparseVector
        from flink_ml_tpu.table.schema import DataTypes

        rng = np.random.RandomState(0)
        vecs, ys = [], []
        for _ in range(120):
            idx = np.sort(rng.choice(12, 3, replace=False))
            val = rng.randn(3)
            vecs.append(SparseVector(12, idx.astype(np.int64), val))
            ys.append(float(val.sum() > 0))
        schema = Schema.of(("features", DataTypes.SPARSE_VECTOR), ("label", "double"))
        t = Table.from_columns(schema, {"features": vecs, "label": np.asarray(ys)})

        def est(mi, ckpt=None):
            e = (LogisticRegression().set_vector_col("features")
                 .set_label_col("label").set_prediction_col("p")
                 .set_learning_rate(0.5).set_max_iter(mi))
            if ckpt:
                e.set_checkpoint_dir(str(ckpt)).set_checkpoint_interval(2)
            return e

        full = est(8).fit(t)
        ckpt = tmp_path / "sc"
        est(4, ckpt).fit(t)
        assert latest_checkpoint(str(ckpt)) is not None
        resumed = est(8, ckpt).fit(t)
        assert resumed.train_epochs_ == 8
        np.testing.assert_allclose(
            resumed.coefficients(), full.coefficients(), rtol=1e-5, atol=1e-6
        )


class TestSparseCheckpointTol:
    def test_tol_stops_checkpointed_sparse_run(self, tmp_path):
        """Regression: interval=1 chunks used to mask tol convergence."""
        from flink_ml_tpu.lib import LogisticRegression
        from flink_ml_tpu.ops.vector import SparseVector
        from flink_ml_tpu.table.schema import DataTypes

        rng = np.random.RandomState(4)
        vecs, ys = [], []
        for _ in range(150):
            idx = np.sort(rng.choice(10, 3, replace=False))
            val = rng.randn(3)
            vecs.append(SparseVector(10, idx.astype(np.int64), val))
            ys.append(float(val.sum() > 0))
        schema = Schema.of(("features", DataTypes.SPARSE_VECTOR), ("label", "double"))
        t = Table.from_columns(schema, {"features": vecs, "label": np.asarray(ys)})

        def est(ckpt=None):
            e = (LogisticRegression().set_vector_col("features")
                 .set_label_col("label").set_prediction_col("p")
                 .set_learning_rate(1.0).set_max_iter(400)
                 .set_tol(1e-4).set_reg(0.1))
            if ckpt:
                e.set_checkpoint_dir(str(ckpt))  # default interval = 1
            return e

        plain = est().fit(t)
        assert plain.train_epochs_ < 400
        ckpt = est(tmp_path / "c").fit(t)
        # converges within one extra epoch of the uncheckpointed run
        assert abs(ckpt.train_epochs_ - plain.train_epochs_) <= 1


def test_missing_meta_sidecar_derives_epoch_from_filename(tmp_path):
    """Regression: a snapshot without its .meta.json must still resume."""
    params = (np.arange(4.0),)
    path = save_checkpoint(str(tmp_path), 6, params)
    os.remove(path + ".meta.json")
    loaded, meta = load_checkpoint(latest_checkpoint(str(tmp_path)), like=params)
    assert meta["epoch"] == 6
    np.testing.assert_array_equal(loaded[0], params[0])


class TestConvergedResume:
    def _sparse_table(self, seed=4):
        from flink_ml_tpu.ops.vector import SparseVector
        from flink_ml_tpu.table.schema import DataTypes

        rng = np.random.RandomState(seed)
        vecs, ys = [], []
        for _ in range(150):
            idx = np.sort(rng.choice(10, 3, replace=False))
            val = rng.randn(3)
            vecs.append(SparseVector(10, idx.astype(np.int64), val))
            ys.append(float(val.sum() > 0))
        schema = Schema.of(("features", DataTypes.SPARSE_VECTOR), ("label", "double"))
        return Table.from_columns(schema, {"features": vecs, "label": np.asarray(ys)})

    def test_sparse_refit_after_convergence_is_noop(self, tmp_path):
        """Regression: re-fitting a tol-converged checkpointed run used to
        execute at least one extra epoch per invocation (the fused while_loop
        always runs a chunk's epoch 0), drifting from the uninterrupted run."""
        from flink_ml_tpu.lib import LogisticRegression

        t = self._sparse_table()

        def est():
            return (LogisticRegression().set_vector_col("features")
                    .set_label_col("label").set_prediction_col("p")
                    .set_learning_rate(1.0).set_max_iter(400)
                    .set_tol(1e-4).set_reg(0.1)
                    .set_checkpoint_dir(str(tmp_path / "c")))

        first = est().fit(t)
        assert first.train_epochs_ < 400  # converged by tol
        again = est().fit(t)
        assert again.train_epochs_ == first.train_epochs_
        np.testing.assert_array_equal(again.coefficients(), first.coefficients())

    def test_dense_refit_after_convergence_is_noop(self, tmp_path):
        from flink_ml_tpu.lib import LogisticRegression
        from flink_ml_tpu.ops.vector import DenseVector
        from flink_ml_tpu.table.schema import DataTypes

        rng = np.random.RandomState(1)
        X = rng.randn(160, 4)
        y = (X @ np.array([1.0, -2.0, 0.5, 1.5]) > 0).astype(np.float64)
        schema = Schema.of(("features", DataTypes.DENSE_VECTOR), ("label", "double"))
        t = Table.from_columns(
            schema,
            {"features": [DenseVector(r) for r in X], "label": y},
        )

        def est():
            return (LogisticRegression().set_vector_col("features")
                    .set_label_col("label").set_prediction_col("p")
                    .set_learning_rate(1.0).set_max_iter(400)
                    .set_tol(1e-4).set_reg(0.1)
                    .set_checkpoint_dir(str(tmp_path / "d")))

        first = est().fit(t)
        assert first.train_epochs_ < 400
        again = est().fit(t)
        assert again.train_epochs_ == first.train_epochs_
        np.testing.assert_array_equal(again.coefficients(), first.coefficients())

    def test_refit_with_tighter_tol_keeps_training(self, tmp_path):
        """A run stamped converged at a loose tol must keep training when
        re-fit with a stricter tol instead of early-returning stale params."""
        from flink_ml_tpu.lib import LogisticRegression

        t = self._sparse_table()

        def est(tol):
            return (LogisticRegression().set_vector_col("features")
                    .set_label_col("label").set_prediction_col("p")
                    .set_learning_rate(1.0).set_max_iter(400)
                    .set_tol(tol).set_reg(0.1)
                    .set_checkpoint_dir(str(tmp_path / "t")))

        loose = est(1e-2).fit(t)
        assert loose.train_epochs_ < 400
        tight = est(1e-5).fit(t)
        assert tight.train_epochs_ > loose.train_epochs_
