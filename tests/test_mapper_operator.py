"""Tests for the mapper machinery (SURVEY §2.3.2) and operator DAG layer (§2.3.3).

Mirrors the reference's mapper/adapter tests: mapper output schema merge,
model loading at open time, link/linkFrom chaining, source-op behavior.
"""

import numpy as np
import pytest

from flink_ml_tpu.common import (
    BroadcastModelSource,
    Mapper,
    MapperAdapter,
    ModelMapper,
    ModelMapperAdapter,
    RowsModelSource,
    TablesModelSource,
)
from flink_ml_tpu.operator import (
    BatchOperator,
    TableSourceBatchOp,
    TableSourceStreamOp,
)
from flink_ml_tpu.table.schema import Schema
from flink_ml_tpu.table.table import Table


def make_table():
    schema = Schema.of(("f0", "double"), ("f1", "double"), ("label", "double"))
    return Table.from_columns(
        schema,
        {"f0": [1.0, 2.0, 3.0], "f1": [10.0, 20.0, 30.0], "label": [0.0, 1.0, 0.0]},
    )


class SumMapper(Mapper):
    """f0 + f1 -> 'sum' column; batched, row-aligned."""

    def output_cols(self):
        return ["sum"], ["double"]

    def map_batch(self, batch):
        return {"sum": np.asarray(batch.col("f0")) + np.asarray(batch.col("f1"))}


class TestMapper:
    def test_output_schema_appends_col(self):
        t = make_table()
        m = SumMapper(t.schema)
        assert m.get_output_schema().field_names == ["f0", "f1", "label", "sum"]

    def test_apply_values(self):
        t = make_table()
        out = SumMapper(t.schema).apply(t)
        np.testing.assert_allclose(out.col("sum"), [11.0, 22.0, 33.0])
        np.testing.assert_allclose(out.col("f0"), [1.0, 2.0, 3.0])

    def test_apply_batched_matches_whole(self):
        t = make_table()
        whole = SumMapper(t.schema).apply(t)
        batched = SumMapper(t.schema).apply(t, batch_size=2)
        np.testing.assert_allclose(whole.col("sum"), batched.col("sum"))

    def test_reserved_cols_override(self):
        class Keep1(SumMapper):
            def reserved_cols(self):
                return ["label"]

        t = make_table()
        out = Keep1(t.schema).apply(t)
        assert out.schema.field_names == ["label", "sum"]

    def test_output_col_overrides_input_in_place(self):
        class Overwrite(Mapper):
            def output_cols(self):
                return ["f1"], ["double"]

            def map_batch(self, batch):
                return {"f1": np.asarray(batch.col("f1")) * 2}

        t = make_table()
        out = Overwrite(t.schema).apply(t)
        # f1 keeps its position, gets the new values (OutputColsHelper rules)
        assert out.schema.field_names == ["f0", "f1", "label"]
        np.testing.assert_allclose(out.col("f1"), [20.0, 40.0, 60.0])

    def test_adapter(self):
        t = make_table()
        fn = MapperAdapter(SumMapper(t.schema), batch_size=2)
        np.testing.assert_allclose(fn(t).col("sum"), [11.0, 22.0, 33.0])


class ScaleModelMapper(ModelMapper):
    """Model = one row holding a scale factor; output f0 * scale."""

    def output_cols(self):
        return ["scaled"], ["double"]

    def load_model(self, *model_tables):
        self.scale = float(model_tables[0].col("scale")[0])

    def map_batch(self, batch):
        return {"scaled": np.asarray(batch.col("f0")) * self.scale}


class TestModelMapper:
    def make_model_table(self):
        return Table.from_columns(Schema.of(("scale", "double")), {"scale": [10.0]})

    def test_model_mapper_adapter_opens_once(self):
        t = make_table()
        model = self.make_model_table()
        mapper = ScaleModelMapper([model.schema], t.schema)
        adapter = ModelMapperAdapter(mapper, TablesModelSource(model))
        out = adapter(t)
        np.testing.assert_allclose(out.col("scaled"), [10.0, 20.0, 30.0])

    def test_rows_model_source(self):
        src = RowsModelSource([(3.0,)], Schema.of(("scale", "double")))
        (table,) = src.get_model_tables()
        assert table.num_rows() == 1

    def test_broadcast_model_source_packs_once(self):
        import jax.numpy as jnp

        model = self.make_model_table()
        calls = []

        def pack(t):
            calls.append(1)
            return jnp.asarray(t.col("scale"))

        src = BroadcastModelSource((model,), pack=pack)
        a = src.get_packed()
        b = src.get_packed()
        assert a is b and len(calls) == 1


class PlusOneOp(BatchOperator):
    def link_from(self, *inputs):
        self.check_op_size(1, inputs)
        t = inputs[0].get_output()
        self.set_output(t.with_column("f0", "double", np.asarray(t.col("f0")) + 1))
        return self


class TestBatchOperator:
    def test_link_chaining(self):
        src = TableSourceBatchOp(make_table())
        out = src.link(PlusOneOp()).link(PlusOneOp())
        np.testing.assert_allclose(out.get_output().col("f0"), [3.0, 4.0, 5.0])

    def test_from_table_and_collect(self):
        op = BatchOperator.from_table(make_table())
        assert len(op.collect()) == 3

    def test_source_rejects_link_from(self):
        src = TableSourceBatchOp(make_table())
        with pytest.raises(RuntimeError):
            src.link_from(TableSourceBatchOp(make_table()))

    def test_source_rejects_null(self):
        with pytest.raises(ValueError):
            TableSourceBatchOp(None)

    def test_check_op_size(self):
        with pytest.raises(ValueError):
            PlusOneOp().link_from(
                TableSourceBatchOp(make_table()), TableSourceBatchOp(make_table())
            )

    def test_transform_unifies_with_api(self):
        # operator usable through the api-level AlgoOperator.transform
        (out,) = PlusOneOp().transform(make_table())
        np.testing.assert_allclose(out.col("f0"), [2.0, 3.0, 4.0])

    def test_output_before_link_raises(self):
        with pytest.raises(RuntimeError):
            PlusOneOp().get_output()


class TestStreamOperator:
    def test_source_stream(self):
        from flink_ml_tpu.table.sources import GeneratorSource

        schema = Schema.of(("x", "double"),)
        src = GeneratorSource.linear_timestamps([(1.0,), (2.0,)], 10, schema)
        op = TableSourceStreamOp(src)
        assert op.get_stream() is src
        assert op.get_schema().field_names == ["x"]
        with pytest.raises(RuntimeError):
            op.link_from(op)
