"""KNOB001/KNOB002 bad cases: bypassing or escaping the registry."""
import os
from os import environ, getenv

from flink_ml_tpu.utils import knobs


def bypass():
    return os.environ.get("FMT_OBS", "0")          # KNOB001: direct read


def bypass_subscript():
    return os.environ["FMT_TRACE"]                 # KNOB001: direct read


def undeclared():
    return knobs.knob_int("FMT_NOT_A_REAL_KNOB")   # KNOB002: undeclared


def bypass_from_import():
    return environ.get("FMT_GUARD")                # KNOB001: aliased read


def bypass_getenv_from_import():
    return getenv("FMT_DRIFT")                     # KNOB001: aliased read
