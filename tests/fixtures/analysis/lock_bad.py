"""LOCK001/LOCK002 bad cases: guarded attributes touched bare."""
import threading


class Racy:
    def __init__(self):
        self._lock = threading.Lock()
        self._count = 0
        self._state = "closed"

    def bump(self):
        with self._lock:
            self._count += 1
            self._state = "open"

    def peek(self):
        return self._count          # LOCK002: bare read

    def reset(self):
        self._state = "closed"      # LOCK001: bare write
