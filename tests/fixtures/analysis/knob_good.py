"""KNOB good cases: declared knobs read through the registry."""
import os

from flink_ml_tpu.utils import knobs


def declared_reads():
    return (knobs.knob_bool("FMT_OBS"), knobs.knob_float("FMT_RETRY_BASE_S"))


def env_write_is_fine():
    os.environ["FMT_OBS"] = "1"    # test-setup idiom: writes are not reads


def non_knob_env_read():
    return os.environ.get("JAX_PLATFORMS", "")     # not an FMT_* knob
