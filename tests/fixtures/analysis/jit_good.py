"""JIT good cases: pure jnp kernels, host work outside the traced path."""
import time

import jax
import jax.numpy as jnp
import numpy as np


def _pure_step(x, w):
    return jnp.dot(x, w)


@jax.jit
def decorated_root(x, w):
    return _pure_step(x, w)


def build(x):
    t0 = time.time()                     # host side: before the dispatch
    fn = jax.jit(_pure_step, donate_argnames=("x",))
    out = np.asarray(fn(x, x))           # host side: after the dispatch
    return out, time.time() - t0


class GoodMapper:
    def fused_kernel(self):
        def fn(x, w):
            return {"scores": jnp.dot(x, w)}

        def finalize(fetched, n):
            return {"p": np.asarray(fetched["scores"])}  # host tail: exempt

        return FusedKernel(fn=fn, finalize=finalize,  # noqa: F821
                           out_keys=("scores",))
