"""JIT001/JIT002/JIT003 bad cases: host effects on traced paths."""
import time

import jax
import jax.numpy as jnp
import numpy as np

from flink_ml_tpu import obs


def _impure_step(x):
    obs.counter_add("fixture.steps")   # metric mutation at trace time
    print("step")                      # host I/O at trace time
    return jnp.sum(x) + time.time()    # clock frozen into the program


@jax.jit
def decorated_root(x):
    return _impure_step(x)


def call_root(x):
    fn = jax.jit(_impure_step, donate_argnames=("missing",))
    return fn(x)


class BadMapper:
    def fused_kernel(self):
        def fn(x, w):
            return {"scores": np.asarray(x) @ w}  # host materialization

        return FusedKernel(fn=fn, out_keys=("scores",))  # noqa: F821
