"""SCOPE/METRIC good cases."""
import contextlib

from flink_ml_tpu import obs
from flink_ml_tpu.obs import trace
from flink_ml_tpu.serve import quarantine


def scoped(parents):
    with trace.use(parents):
        with quarantine.capture() as captured:
            return captured


def scoped_stack(parents):
    with contextlib.ExitStack() as stack:
        stack.enter_context(trace.use(parents))


def good_names():
    obs.counter_add("serving.requests")
    obs.gauge_set("serving.queue_depth", 3.0)
    with obs.phase("pack_csr"):
        pass
