"""LOCK good cases: every touch locked, or the _locked convention."""
import threading


class Disciplined:
    def __init__(self):
        self._lock = threading.Lock()
        self._count = 0
        self._config = "fixed"      # only ever written in __init__

    def bump(self):
        with self._lock:
            self._count += 1
            self._bump_more_locked()

    def _bump_more_locked(self):
        self._count += 1            # caller holds the lock by convention

    def peek(self):
        with self._lock:
            return self._count

    def describe(self):
        return self._config         # unguarded config read: fine
