"""SCOPE001/METRIC001/METRIC002 bad cases."""
from flink_ml_tpu import obs
from flink_ml_tpu.obs import trace
from flink_ml_tpu.serve import quarantine


def leaky(parents):
    trace.use(parents)            # SCOPE001: ambient scope never exits
    quarantine.capture()          # SCOPE001


def bad_names():
    obs.counter_add("Serving.Requests")   # METRIC001: not dotted-lowercase
    obs.counter_add("fixture.mixed")      # METRIC002 pair: counter...
    obs.gauge_set("fixture.mixed", 1.0)   # ...and gauge, one name
