"""Shared epoch definition for the cross-process equivalence test.

test_distributed.py (single-process 8-device reference) and
distributed_worker.py (2-process x 4-device run) must execute the IDENTICAL
training epoch; importing the definition from one place makes that
invariant structural rather than copy-synced.
"""

import numpy as np

N_DEV = 8
GLOBAL_BATCH = 16
LEARNING_RATE = 0.5


def make_epoch_inputs():
    """(combined minibatch stack view, zero params) for the shared epoch."""
    from flink_ml_tpu.lib.common import _combined_view, pack_minibatches

    rng = np.random.RandomState(0)
    Xg = rng.randn(64, 3)
    yg = (Xg @ np.array([1.0, -1.0, 0.5]) > 0).astype(np.float64)
    stack = pack_minibatches(
        Xg, yg, n_dev=N_DEV, global_batch_size=GLOBAL_BATCH
    )
    params0 = (np.zeros((3,), np.float32), np.zeros((), np.float32))
    return _combined_view(stack), params0


def make_epoch_step(mesh):
    from flink_ml_tpu.lib.classification import _log_loss_grads
    from flink_ml_tpu.lib.common import make_glm_epoch_step

    return make_glm_epoch_step(
        _log_loss_grads(True), mesh, learning_rate=LEARNING_RATE, reg=0.0
    )


# -- per-process file-shard fit (VERDICT r3 item 2) ---------------------------

SHARD_ROWS = 128     # rows per process shard (equal shards by contract)
SHARD_DIM = 6
SHARD_G = 32         # GLOBAL batch size
SHARD_EPOCHS = 5
SHARD_FEATURES = [f"f{i}" for i in range(SHARD_DIM)]


def shard_schema():
    from flink_ml_tpu.table.schema import Schema

    return Schema(SHARD_FEATURES + ["label"],
                  ["double"] * (SHARD_DIM + 1))


def make_shard_rows(num_processes):
    """The full deterministic dataset, one (X, y) block per process shard."""
    rng = np.random.RandomState(7)
    n = SHARD_ROWS * num_processes
    X = rng.randn(n, SHARD_DIM)
    y = (X @ rng.randn(SHARD_DIM) > 0).astype(np.float64)
    return [
        (X[p * SHARD_ROWS:(p + 1) * SHARD_ROWS],
         y[p * SHARD_ROWS:(p + 1) * SHARD_ROWS])
        for p in range(num_processes)
    ]


def write_shard_csv(path, X, y):
    with open(path, "w") as f:
        for row, lab in zip(X, y):
            f.write(",".join(f"{v:.17g}" for v in row) + f",{lab:.1f}\n")


def interleaved_rows(shards, num_processes):
    """The single-process row order equivalent to the multi-process schedule:
    global SGD step s consumes each process's s-th (G/P)-row window, so the
    canonical order interleaves per-shard windows round-robin."""
    g_local = SHARD_G // num_processes
    Xs = [s[0] for s in shards]
    ys = [s[1] for s in shards]
    xw, yw = [], []
    for start in range(0, SHARD_ROWS, g_local):
        for p in range(num_processes):
            xw.append(Xs[p][start:start + g_local])
            yw.append(ys[p][start:start + g_local])
    return np.concatenate(xw), np.concatenate(yw)


def fit_shard_table(table):
    """The estimator-level fit both sides run (identical hyperparameters);
    ``table`` may be a materialized Table or a ChunkedTable (out-of-core)."""
    from flink_ml_tpu.lib import LogisticRegression

    est = (
        LogisticRegression().set_feature_cols(SHARD_FEATURES)
        .set_label_col("label").set_prediction_col("pred")
        .set_learning_rate(LEARNING_RATE).set_max_iter(SHARD_EPOCHS)
        .set_global_batch_size(SHARD_G)
    )
    model = est.fit(table)
    (mt,) = model.get_model_data()
    w = np.asarray(mt.col("coefficients")[0].to_dense().values)
    b = float(mt.col("intercept")[0])
    return w, b


# -- per-process SPARSE shard fit (cross-process nnz_pad agreement) -----------

SPARSE_DIM = 2048
#: per-process nnz density — deliberately UNEQUAL so the local packs land on
#: different padded nnz widths (512 vs 1024 at pad_multiple=512) and the
#: cross-process agree_max repack is genuinely exercised, not a no-op
SPARSE_NNZ_BASE = 5
SPARSE_NNZ_STEP = 145


def make_sparse_shard_rows(num_processes):
    """One (vectors, y) block per process shard; process p's rows carry
    ``SPARSE_NNZ_BASE + p * SPARSE_NNZ_STEP`` stored entries each."""
    from flink_ml_tpu.ops.vector import SparseVector

    rng = np.random.RandomState(13)
    true_w = rng.randn(SPARSE_DIM)
    shards = []
    for p in range(num_processes):
        nnz = SPARSE_NNZ_BASE + p * SPARSE_NNZ_STEP
        vecs, ys = [], []
        for _ in range(SHARD_ROWS):
            idx = np.sort(rng.choice(SPARSE_DIM, nnz, replace=False))
            vals = rng.randn(nnz)
            vecs.append(SparseVector(SPARSE_DIM, idx.astype(np.int64), vals))
            ys.append(float((vals @ true_w[idx]) > 0))
        shards.append((vecs, np.asarray(ys)))
    return shards


def make_unequal_sparse_shard_rows(num_processes):
    """Shards with UNEQUAL row counts (process p holds SHARD_ROWS + 32*p
    rows): the shorter shard must pad its out-of-core epochs with gated
    no-op blocks up to the agreed per-epoch block count, or the collective
    chunk calls deadlock."""
    from flink_ml_tpu.ops.vector import SparseVector

    rng = np.random.RandomState(29)
    true_w = rng.randn(SPARSE_DIM)
    shards = []
    for p in range(num_processes):
        vecs, ys = [], []
        for _ in range(SHARD_ROWS + 32 * p):
            idx = np.sort(rng.choice(SPARSE_DIM, 5, replace=False))
            vals = rng.randn(5)
            vecs.append(SparseVector(SPARSE_DIM, idx.astype(np.int64), vals))
            ys.append(float((vals @ true_w[idx]) > 0))
        shards.append((vecs, np.asarray(ys)))
    return shards


def sparse_shard_schema():
    from flink_ml_tpu.table.schema import DataTypes, Schema

    return Schema.of(
        ("features", DataTypes.SPARSE_VECTOR), ("label", "double")
    )


def interleaved_sparse_rows(shards, num_processes):
    """Single-process row order equivalent to the multi-process sparse
    schedule (same windowing rule as :func:`interleaved_rows`)."""
    g_local = SHARD_G // num_processes
    vecs, ys = [], []
    for start in range(0, SHARD_ROWS, g_local):
        for p in range(num_processes):
            vecs.extend(shards[p][0][start:start + g_local])
            ys.extend(shards[p][1][start:start + g_local])
    return vecs, np.asarray(ys)


KM_K = 5
KM_EPOCHS = 5
KM_SEED = 3


def fit_kmeans_shard_table(table):
    """KMeans fit both sides run.  NOTE the single-process reference table
    must hold the shards CONCATENATED in process order (not interleaved):
    KMeans shards rows as contiguous device blocks, so process p's rows map
    to devices [p*4, (p+1)*4) — the same partition the concatenated order
    produces on the 8-device mesh."""
    from flink_ml_tpu.lib import KMeans

    est = (
        KMeans().set_feature_cols(SHARD_FEATURES)
        .set_prediction_col("cluster").set_k(KM_K)
        .set_max_iter(KM_EPOCHS).set_seed(KM_SEED)
    )
    model = est.fit(table)
    (mt,) = model.get_model_data()
    cents = np.asarray(
        [v.to_dense().values for v in mt.col("centroid")], dtype=np.float64
    )
    return cents, float(model.train_cost_)


def fit_sparse_shard_table(table, hot_k: int = 0, checkpoint_dir=None,
                           max_iter=None):
    from flink_ml_tpu.lib import LogisticRegression

    est = (
        LogisticRegression().set_vector_col("features")
        .set_label_col("label").set_prediction_col("pred")
        .set_num_features(SPARSE_DIM)
        .set_learning_rate(LEARNING_RATE)
        .set_max_iter(SHARD_EPOCHS if max_iter is None else max_iter)
        .set_global_batch_size(SHARD_G)
    )
    if hot_k:
        est.set_num_hot_features(hot_k)
    if checkpoint_dir is not None:
        est.set_checkpoint_dir(str(checkpoint_dir)).set_checkpoint_interval(1)
    model = est.fit(table)
    (mt,) = model.get_model_data()
    w = np.asarray(mt.col("coefficients")[0].to_dense().values)
    b = float(mt.col("intercept")[0])
    return w, b
