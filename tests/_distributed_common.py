"""Shared epoch definition for the cross-process equivalence test.

test_distributed.py (single-process 8-device reference) and
distributed_worker.py (2-process x 4-device run) must execute the IDENTICAL
training epoch; importing the definition from one place makes that
invariant structural rather than copy-synced.
"""

import numpy as np

N_DEV = 8
GLOBAL_BATCH = 16
LEARNING_RATE = 0.5


def make_epoch_inputs():
    """(combined minibatch stack view, zero params) for the shared epoch."""
    from flink_ml_tpu.lib.common import _combined_view, pack_minibatches

    rng = np.random.RandomState(0)
    Xg = rng.randn(64, 3)
    yg = (Xg @ np.array([1.0, -1.0, 0.5]) > 0).astype(np.float64)
    stack = pack_minibatches(
        Xg, yg, n_dev=N_DEV, global_batch_size=GLOBAL_BATCH
    )
    params0 = (np.zeros((3,), np.float32), np.zeros((), np.float32))
    return _combined_view(stack), params0


def make_epoch_step(mesh):
    from flink_ml_tpu.lib.classification import _log_loss_grads
    from flink_ml_tpu.lib.common import make_glm_epoch_step

    return make_glm_epoch_step(
        _log_loss_grads(True), mesh, learning_rate=LEARNING_RATE, reg=0.0
    )
