"""Mesh/collectives tests on the virtual 8-device CPU mesh — the analog of the
reference's in-JVM mini-cluster exercising real shuffles/broadcasts locally
(SURVEY.md §4 'multi-node without a cluster')."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flink_ml_tpu.parallel import (
    create_mesh,
    default_mesh,
    make_data_parallel_step,
    pmean,
    replicate,
    shard_batch,
)


def test_eight_virtual_devices():
    assert jax.device_count() == 8


def test_default_mesh_covers_devices():
    mesh = default_mesh()
    assert mesh.axis_names == ("data",)
    assert mesh.devices.size == 8


def test_create_mesh_2d():
    mesh = create_mesh({"data": 4, "model": 2})
    assert mesh.axis_names == ("data", "model")
    assert mesh.shape == {"data": 4, "model": 2}


def test_create_mesh_wrong_size():
    with pytest.raises(ValueError, match="require"):
        create_mesh({"data": 3})


def test_shard_and_replicate_placement():
    mesh = default_mesh()
    batch = {"x": np.arange(16.0).reshape(16, 1), "y": np.arange(16.0)}
    sharded = shard_batch(mesh, batch)
    assert len(sharded["x"].sharding.device_set) == 8
    params = replicate(mesh, {"w": np.ones(3)})
    assert params["w"].sharding.is_fully_replicated


def test_data_parallel_step_psum_gradient():
    """The reference round (map grads -> reduce -> avg -> rebroadcast,
    LinearRegression.java:108-121) as one jitted step with in-step pmean."""
    mesh = default_mesh()

    def local_step(state, batch):
        w = state["w"]
        x, y = batch["x"], batch["y"]
        pred = x @ w
        # local grad on this shard, averaged across the mesh over ICI
        grad = x.T @ (pred - y) / x.shape[0]
        grad = pmean(grad, "data")
        loss = pmean(jnp.mean((pred - y) ** 2), "data")
        return {"w": w - 0.1 * grad}, {"loss": loss}

    step = make_data_parallel_step(local_step, mesh, donate_state=False)

    rng = np.random.default_rng(0)
    w_true = np.array([2.0, -1.0])
    x = rng.standard_normal((64, 2))
    y = x @ w_true
    state = replicate(mesh, {"w": jnp.zeros(2)})
    batch = shard_batch(mesh, {"x": jnp.asarray(x), "y": jnp.asarray(y)})

    losses = []
    for _ in range(200):
        state, aux = step(state, batch)
        losses.append(float(aux["loss"]))
    assert losses[-1] < 1e-3 < losses[0]
    np.testing.assert_allclose(np.asarray(state["w"]), w_true, atol=1e-2)


def test_data_parallel_matches_single_device():
    """Sharded training must be numerically equivalent to one-device training."""
    mesh = default_mesh()

    def local_step(state, batch):
        grad = batch["x"].T @ (batch["x"] @ state - batch["y"]) / batch["x"].shape[0]
        return state - 0.05 * pmean(grad, "data"), ()

    step = make_data_parallel_step(local_step, mesh, donate_state=False)
    rng = np.random.default_rng(1)
    x = rng.standard_normal((32, 3))
    y = rng.standard_normal(32)

    state = replicate(mesh, jnp.zeros(3))
    batch = shard_batch(mesh, {"x": jnp.asarray(x), "y": jnp.asarray(y)})
    for _ in range(10):
        state, _ = step(state, batch)

    # host reference: identical math with mean-of-shard-means
    w = np.zeros(3)
    for _ in range(10):
        grads = [
            xs.T @ (xs @ w - ys) / xs.shape[0]
            for xs, ys in zip(np.split(x, 8), np.split(y, 8))
        ]
        w = w - 0.05 * np.mean(grads, axis=0)
    np.testing.assert_allclose(np.asarray(state), w, rtol=1e-6)


class TestBoundedDispatchDonation:
    def test_long_loop_with_donation_and_inflight(self):
        """Regression: pending outputs whose state was donated by the next call
        must not be waited on (BlockHostUntilReady on deleted buffer)."""
        mesh = default_mesh()

        def local_step(state, batch):
            grad = pmean(batch.sum(), "data")
            return state + grad, grad

        step = make_data_parallel_step(
            local_step, mesh, donate_state=True, max_inflight=4
        )
        state = jnp.zeros(())
        batch = jnp.ones((8, 2))
        for _ in range(12):
            state, _ = step(state, batch)
        assert float(state) == 12 * 2.0

    def test_aux_free_output_still_bounded(self):
        """All-donated pending entries are skipped, newest syncs the pipeline."""
        mesh = default_mesh()

        def local_step(state, batch):
            return state + pmean(batch.sum(), "data"), ()

        step = make_data_parallel_step(
            local_step, mesh, donate_state=True, max_inflight=2
        )
        state = jnp.zeros(())
        batch = jnp.ones((8, 2))
        for _ in range(8):
            state, _ = step(state, batch)
        assert float(state) == 8 * 2.0
