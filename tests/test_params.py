"""Params system tests — behavior parity with ParamsTest.java:34-153 and
ExtractParamInfosUtilTest.java:34-101."""

import pytest

from flink_ml_tpu.params import (
    ParamInfo,
    Params,
    WithParams,
    extract_param_infos,
    param_info,
)


def test_default_behavior():
    p = Params()
    info = param_info("k", "num clusters", default=2)
    assert p.get(info) == 2
    p.set(info, 5)
    assert p.get(info) == 5


def test_optional_without_default_raises():
    p = Params()
    info = param_info("k", optional=True)
    with pytest.raises(ValueError, match="default"):
        p.get(info)


def test_required_unset_raises():
    p = Params()
    info = param_info("k", optional=False)
    with pytest.raises(ValueError, match="non-optional"):
        p.get(info)


def test_validator_rejects():
    p = Params()
    info = param_info("k", validator=lambda v: v > 0, default=1)
    p.set(info, 3)
    with pytest.raises(ValueError, match="invalid"):
        p.set(info, -1)
    assert p.get(info) == 3


def test_alias_resolution():
    p = Params()
    info = param_info("numClusters", alias=["k"], default=2)
    p.set_raw("k", 7)
    assert p.get(info) == 7


def test_alias_conflict_raises():
    p = Params()
    info = param_info("numClusters", alias=["k"], default=2)
    p.set_raw("numClusters", 3)
    p.set_raw("k", 7)
    with pytest.raises(ValueError, match="Duplicate"):
        p.get(info)


def test_remove_clears_aliases():
    p = Params()
    info = param_info("numClusters", alias=["k"], default=2)
    p.set_raw("k", 7)
    assert p.contains(info)
    p.remove(info)
    assert not p.contains(info)
    assert p.get(info) == 2


def test_json_round_trip():
    p = Params()
    p.set(param_info("lr"), 0.01)
    p.set(param_info("cols"), ["a", "b"])
    p.set(param_info("name"), "model")
    p.set(param_info("nothing"), None)
    restored = Params.from_json(p.to_json())
    assert restored == p
    assert restored.get(param_info("cols")) == ["a", "b"]
    assert restored.get(param_info("nothing")) is None


def test_merge_and_clone():
    a = Params().set(param_info("x"), 1)
    b = Params().set(param_info("x"), 2).set(param_info("y"), 3)
    c = a.clone()
    a.merge(b)
    assert a.get(param_info("x")) == 2
    assert a.get(param_info("y")) == 3
    assert c.get(param_info("x")) == 1
    assert not c.contains(param_info("y"))


def test_size_clear_empty():
    p = Params()
    assert p.is_empty() and p.size() == 0
    p.set(param_info("x"), 1)
    assert len(p) == 1
    p.clear()
    assert p.is_empty()


class _Base(WithParams):
    ALPHA = param_info("alpha", default=0.1)


class _MixinIface(WithParams):
    BETA = param_info("beta", default=0.2)


class _Derived(_Base, _MixinIface):
    GAMMA = param_info("gamma", default=0.3)


def test_extract_param_infos_walks_mro():
    infos = extract_param_infos(_Derived())
    assert set(infos) == {"alpha", "beta", "gamma"}
    assert all(isinstance(i, ParamInfo) for i in infos.values())


def test_with_params_get_set():
    d = _Derived()
    assert d.get(_Derived.ALPHA) == 0.1
    d.set(_Derived.ALPHA, 0.9)
    assert d.get(_Derived.ALPHA) == 0.9
    # instance-local params: another instance is untouched
    assert _Derived().get(_Derived.ALPHA) == 0.1


def test_shared_mixins():
    from flink_ml_tpu.params.shared import HasPredictionCol, HasReservedCols

    class Op(HasPredictionCol, HasReservedCols):
        pass

    op = Op()
    op.set_prediction_col("pred")
    assert op.get_prediction_col() == "pred"
    assert op.get_reserved_cols() is None
    op.set_reserved_cols(["a"])
    assert op.get_reserved_cols() == ["a"]
    with pytest.raises(ValueError):
        Op().get_prediction_col()  # required, unset


def test_value_type_enforced():
    p = Params()
    info = param_info("col", value_type=str)
    with pytest.raises(TypeError, match="expected str"):
        p.set(info, 123)
    p.set(info, "ok")
    finfo = param_info("lr", value_type=float)
    p.set(finfo, 1)  # int where float declared is fine
    with pytest.raises(TypeError):
        p.set(finfo, True)  # bool is not a number here
    linfo = param_info("cols", value_type=list)
    p.set(linfo, ("a", "b"))  # tuple ok, becomes list
    assert Params.from_json(p.to_json()).get(linfo) == ["a", "b"]
