"""Memory-pressure resilience (ISSUE 9): OOM classification, adaptive
batch bisection, HBM-budget admission, pool pressure eviction, and the
exact-parity recovery contracts on every dispatch surface."""

import os
import subprocess
import sys
import time

import numpy as np
import pytest

from flink_ml_tpu import fault, obs
from flink_ml_tpu.fault import injection, pressure, retry
from flink_ml_tpu.fault.injection import InjectedFault

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

OOM_MSG = "RESOURCE_EXHAUSTED: Out of memory while trying to allocate 123456 bytes."


@pytest.fixture(autouse=True)
def _clean_pressure_state(tmp_path, monkeypatch):
    monkeypatch.setenv("FMT_OBS_REPORTS", str(tmp_path / "_reports"))
    injection.reset()
    pressure.reset_states()
    yield
    injection.reset()
    pressure.reset_states()
    obs.disable()
    obs.reset()


def _dense_table(n=256, dim=5, seed=3):
    from flink_ml_tpu.table.schema import DataTypes, Schema
    from flink_ml_tpu.table.table import Table

    rng = np.random.RandomState(seed)
    X = rng.randn(n, dim).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float64)
    return Table.from_columns(
        Schema.of(("features", DataTypes.DENSE_VECTOR), ("label", "double")),
        {"features": X, "label": y},
    )


def _logreg(lr=0.5, iters=3, **extra):
    from flink_ml_tpu.lib import LogisticRegression

    est = (
        LogisticRegression().set_vector_col("features")
        .set_label_col("label").set_prediction_col("p")
        .set_learning_rate(lr).set_max_iter(iters)
    )
    for k, v in extra.items():
        getattr(est, f"set_{k}")(v)
    return est


class TestOomClassification:
    def test_allocator_messages_are_oom(self):
        for msg in (
            OOM_MSG,
            "Resource exhausted: Failed to allocate request for 2.5GiB",
            "Allocator (TPU_0) ran out of memory trying to allocate 1.2G",
            "RESOURCE_EXHAUSTED: Error allocating device buffer (HBM)",
            "XlaRuntimeError: Out of memory",
        ):
            assert pressure.is_oom(RuntimeError(msg)), msg

    def test_host_memory_error_is_oom(self):
        assert pressure.is_oom(MemoryError())

    def test_quota_exhaustion_stays_transient(self):
        # the satellite-1 contract: RESOURCE_EXHAUSTED without allocator
        # vocabulary is quota/RPC backpressure — a retry plausibly fixes it
        quota = RuntimeError("RESOURCE_EXHAUSTED: quota exceeded for rpc")
        assert not pressure.is_oom(quota)
        assert retry.is_transient(quota)

    def test_non_exhaustion_errors_are_not_oom(self):
        for exc in (
            RuntimeError("UNAVAILABLE: socket closed"),
            ValueError("bad shape"),
            KeyboardInterrupt(),
        ):
            assert not pressure.is_oom(exc)

    def test_injected_oom_point_classified(self):
        injection.configure("fault.oom>10")
        with pytest.raises(InjectedFault) as ei:
            pressure.maybe_oom(11)
        assert pressure.is_oom(ei.value)
        assert not retry.is_transient(ei.value)
        # other injection points keep their transient classification
        assert retry.is_transient(InjectedFault("place.h2d", 1))


class TestRetryDeclassification:
    def test_oom_not_retried_same_size(self):
        """The red test for the old behavior: fault/retry.py classified
        every RESOURCE_EXHAUSTED as transient, so a deterministic
        allocator OOM was retried at the identical batch size
        ``FMT_RETRY_ATTEMPTS`` times (failing identically each time,
        tripling the latency) before giving up.  Now it re-raises on the
        FIRST attempt and routes to pressure recovery."""
        attempts = [0]

        def body():
            attempts[0] += 1
            raise RuntimeError(OOM_MSG)

        with pytest.raises(RuntimeError, match="Out of memory"):
            fault.with_retry(body, "test.oom",
                             retry.RetryPolicy(attempts=3, base_delay_s=0.0))
        assert attempts[0] == 1  # the old behavior burned all 3

    def test_transient_exhaustion_still_retried(self):
        attempts = [0]

        def body():
            attempts[0] += 1
            if attempts[0] < 3:
                raise RuntimeError("RESOURCE_EXHAUSTED: quota exceeded")
            return "ok"

        assert fault.with_retry(
            body, "test.quota",
            retry.RetryPolicy(attempts=3, base_delay_s=0.0),
        ) == "ok"
        assert attempts[0] == 3


class TestValueConditionedRules:
    def test_over_threshold_rule_fires_while_value_exceeds(self):
        injection.configure("fault.oom>256")
        pressure.maybe_oom(256)  # boundary: not strictly greater
        pressure.maybe_oom(100)
        with pytest.raises(InjectedFault):
            pressure.maybe_oom(257)
        with pytest.raises(InjectedFault):
            pressure.maybe_oom(512)  # fires EVERY over-threshold call
        assert injection.fire_count("fault.oom") == 2

    def test_no_value_never_fires(self):
        injection.configure("some.point>10")
        injection.maybe_fail("some.point")  # plain hook: no value, no fire
        assert injection.fire_count("some.point") == 0

    def test_mixed_spec_parses(self):
        injection.configure("a@2,b~0.5,c>64")
        with pytest.raises(InjectedFault):
            injection.maybe_fail("c", value=65)

    def test_bad_threshold_rejected(self):
        with pytest.raises(ValueError, match="must be a number"):
            injection.configure("p>abc")
        with pytest.raises(ValueError, match=">= 0"):
            injection.configure("p>-1")


class TestRunBisected:
    def _capacity_fn(self, capacity, log=None):
        def fn(lo, hi):
            if log is not None:
                log.append((lo, hi))
            if hi - lo > capacity:
                raise RuntimeError(OOM_MSG)
            return np.arange(lo, hi)

        return fn

    def test_converges_and_concatenates_exactly(self):
        obs.enable()
        out = pressure.run_bisected(
            self._capacity_fn(100), 1000, surface="t.bisect"
        )
        np.testing.assert_array_equal(out, np.arange(1000))
        c = obs.registry().snapshot()["counters"]
        assert c.get("pressure.ooms", 0) >= 1
        assert c.get("pressure.bisections", 0) >= 1

    def test_state_remembered_across_runs(self):
        log = []
        fn = self._capacity_fn(100, log)
        pressure.run_bisected(fn, 1000, surface="t.mem")
        log.clear()
        out = pressure.run_bisected(fn, 1000, surface="t.mem")
        np.testing.assert_array_equal(out, np.arange(1000))
        # second run chunks at the remembered cap: zero failing probes
        assert all(hi - lo <= 100 for lo, hi in log), log

    def test_aimd_probe_recovers_full_batch(self, monkeypatch):
        obs.enable()
        fn = self._capacity_fn(100)
        pressure.run_bisected(fn, 1000, surface="t.aimd")
        st = pressure.state("t.aimd")
        assert st.cap is not None
        monkeypatch.setenv("FMT_PRESSURE_PROBE_S", "0")
        for _ in range(20):
            st.admit(1000)
        assert st.cap is None  # fully recovered
        assert obs.registry().snapshot()["counters"].get(
            "pressure.resizes", 0) >= 1
        # and with capacity restored the next run is ONE unsplit call
        log = []
        pressure.run_bisected(self._capacity_fn(10_000, log), 1000,
                              surface="t.aimd")
        assert log == [(0, 1000)]

    def test_floor_oom_reraises(self):
        def fn(lo, hi):
            raise RuntimeError(OOM_MSG)

        with pytest.raises(RuntimeError, match="Out of memory"):
            pressure.run_bisected(fn, 64, surface="t.floor", floor=8)

    def test_non_oom_raises_through(self):
        def fn(lo, hi):
            raise ValueError("a real bug")

        with pytest.raises(ValueError, match="a real bug"):
            pressure.run_bisected(fn, 64, surface="t.raise")

    def test_dict_and_list_results_concatenate(self):
        def fn(lo, hi):
            if hi - lo > 4:
                raise RuntimeError(OOM_MSG)
            return {"a": np.arange(lo, hi), "b": [str(i) for i in range(lo, hi)]}

        out = pressure.run_bisected(fn, 10, surface="t.dict")
        np.testing.assert_array_equal(out["a"], np.arange(10))
        assert out["b"] == [str(i) for i in range(10)]

    def test_disabled_layer_fails_fast(self, monkeypatch):
        monkeypatch.setenv("FMT_PRESSURE", "0")
        log = []
        with pytest.raises(RuntimeError, match="Out of memory"):
            pressure.run_bisected(self._capacity_fn(100, log), 1000,
                                  surface="t.off")
        assert log == [(0, 1000)]  # one attempt, no recovery


class TestPoolPressureEviction:
    def test_unpinned_dropped_pinned_kept(self):
        from flink_ml_tpu.table import slab_pool

        pool = slab_pool.SlabPool(budget_bytes=1 << 30)
        a = np.arange(1024.0)
        b = np.arange(2048.0)
        va = pool.get_or_build(("a",), lambda: a, nbytes=a.nbytes)
        pool.get_or_build(("b",), lambda: b, nbytes=b.nbytes)
        with pool.pinned(va):
            dropped = pool.evict_for_pressure()
            assert dropped == b.nbytes  # only the unpinned entry
            assert pool._entries  # the pinned one survived
        assert pool.evict_for_pressure() == a.nbytes

    def test_bisection_evicts_before_shrinking(self):
        from flink_ml_tpu.table import slab_pool

        slab_pool.reset_pool()
        big = np.arange(4096.0)
        slab_pool.pool().get_or_build(("victim",), lambda: big,
                                      nbytes=big.nbytes)
        obs.enable()
        calls = {"n": 0}

        def fn(lo, hi):
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError(OOM_MSG)
            return np.arange(lo, hi)  # eviction freed enough: same size OK

        out = pressure.run_bisected(fn, 100, surface="t.evict")
        np.testing.assert_array_equal(out, np.arange(100))
        assert calls["n"] == 2  # retried at FULL size after eviction
        c = obs.registry().snapshot()["counters"]
        assert c.get("pressure.evictions", 0) >= 1
        assert c.get("slab_pool.pressure_evictions", 0) >= 1
        assert pressure.state("t.evict").cap is None  # never shrank
        slab_pool.reset_pool()


class TestFusedBisectionParity:
    def _pipeline_and_table(self, n=512):
        from flink_ml_tpu.api.pipeline import Pipeline
        from flink_ml_tpu.lib.feature import StandardScaler

        t = _dense_table(n=n)
        model = Pipeline([
            StandardScaler().set_selected_col("features"),
            _logreg(),
        ]).fit(t)
        return model, t

    def test_transform_under_ceiling_bit_identical(self):
        model, t = self._pipeline_and_table()
        (ref,) = model.transform(t)
        obs.enable()
        obs.reset()
        injection.configure("fault.oom>64")
        try:
            (out,) = model.transform(t)
        finally:
            injection.configure(None)
        np.testing.assert_array_equal(
            np.asarray(out.col("p")), np.asarray(ref.col("p"))
        )
        c = obs.registry().snapshot()["counters"]
        assert c.get("pressure.bisections", 0) >= 1, c
        # under pressure the plan dispatches MORE, never fewer, rows
        assert c.get("pipeline.fused_rows", 0) >= t.num_rows()

    def test_quarantine_offsets_survive_bisection(self):
        from flink_ml_tpu.serve import quarantine
        from flink_ml_tpu.table.table import Table

        model, t = self._pipeline_and_table()
        bad_rows = [7, 300]
        X = np.asarray(t.features_dense("features"), dtype=np.float32).copy()
        for r in bad_rows:
            X[r, 1] = np.nan
        bad_t = Table.from_columns(t.schema, {
            "features": X, "label": t.col("label"),
        })
        quarantine.reset()
        (ref,) = model.transform(bad_t)
        ref_side = quarantine.quarantine_table("StandardScalerModel")
        ref_rows = list(ref_side.col(quarantine.QUARANTINE_ROW_COL))
        quarantine.reset()
        injection.configure("fault.oom>64")
        try:
            (out,) = model.transform(bad_t)
        finally:
            injection.configure(None)
        side = quarantine.quarantine_table("StandardScalerModel")
        assert list(side.col(quarantine.QUARANTINE_ROW_COL)) == ref_rows
        assert sorted(ref_rows) == bad_rows  # original-feed offsets
        np.testing.assert_array_equal(
            np.asarray(out.col("p")), np.asarray(ref.col("p"))
        )
        quarantine.reset()

    def test_staged_apply_chunking_parity(self):
        """KMeans assign + Knn scan (the apply_batched/apply_sharded
        chunking) under the injected ceiling: predictions exact."""
        from flink_ml_tpu.lib import KMeans, Knn

        t = _dense_table(n=300)
        km = (KMeans().set_vector_col("features").set_k(4)
              .set_prediction_col("c").set_max_iter(3).fit(t))
        knn = (Knn().set_vector_col("features").set_label_col("label")
               .set_k(3).set_prediction_col("p").fit(t))
        (km_ref,) = km.transform(t)
        (knn_ref,) = knn.transform(t)
        obs.enable()
        obs.reset()
        injection.configure("fault.oom>32")
        try:
            (km_out,) = km.transform(t)
            (knn_out,) = knn.transform(t)
        finally:
            injection.configure(None)
        np.testing.assert_array_equal(np.asarray(km_out.col("c")),
                                      np.asarray(km_ref.col("c")))
        np.testing.assert_array_equal(np.asarray(knn_out.col("p")),
                                      np.asarray(knn_ref.col("p")))
        c = obs.registry().snapshot()["counters"]
        assert c.get("pressure.ooms.apply", 0) >= 1, c


class TestServingUnderPressure:
    def _model_and_table(self, n=512):
        from flink_ml_tpu.api.pipeline import Pipeline
        from flink_ml_tpu.lib.feature import StandardScaler

        t = _dense_table(n=n)
        model = Pipeline([
            StandardScaler().set_selected_col("features"),
            _logreg(),
        ]).fit(t)
        return model, t

    def test_coalesced_batches_survive_injected_ceiling(self):
        from flink_ml_tpu.serving import ModelServer

        model, t = self._model_and_table()
        (ref,) = model.transform(t)
        refp = np.asarray(ref.col("p"))
        obs.enable()
        obs.reset()
        injection.configure("fault.oom>64")
        try:
            with ModelServer(model, max_batch=256, max_wait_ms=1) as server:
                futs = [server.submit(t.slice_rows(i * 32, (i + 1) * 32))
                        for i in range(16)]
                for i, f in enumerate(futs):
                    got = np.asarray(f.result(120).table.col("p"))
                    np.testing.assert_array_equal(
                        got, refp[i * 32:(i + 1) * 32],
                        err_msg=f"request {i} diverged under pressure",
                    )
        finally:
            injection.configure(None)
        c = obs.registry().snapshot()["counters"]
        assert c.get("pressure.bisections", 0) >= 1, c
        assert c.get("serving.failed_requests", 0) == 0, c

    def test_dispatcher_splits_at_request_boundary(self):
        """A model whose TRANSFORM OOMs wholesale (no internal bisection
        available — e.g. a custom stage) forces the dispatcher-level
        split: each caller still gets its exact solo result."""
        from flink_ml_tpu.serving import ModelServer

        class CeilingModel:
            """transform raises allocator OOM for batches over 40 rows."""

            stages = []

            def transform(self, table):
                if table.num_rows() > 40:
                    raise RuntimeError(OOM_MSG)
                return (table,)

        obs.enable()
        obs.reset()
        t = _dense_table(n=128)
        with ModelServer(CeilingModel(), max_batch=128, max_wait_ms=20,
                         start=False) as server:
            futs = [server.submit(t.slice_rows(i * 16, (i + 1) * 16))
                    for i in range(8)]  # coalesces to one 128-row batch
            server.start()
            for i, f in enumerate(futs):
                res = f.result(60)
                np.testing.assert_array_equal(
                    np.asarray(res.table.features_dense("features")),
                    np.asarray(
                        t.slice_rows(i * 16, (i + 1) * 16)
                        .features_dense("features")
                    ),
                )
        c = obs.registry().snapshot()["counters"]
        assert c.get("serving.pressure_splits", 0) >= 1, c
        assert c.get("serving.failed_requests", 0) == 0, c
        # the pressure state caps later coalescing
        assert pressure.state("serving.batch").cap is not None

    def test_bytes_cap_sheds_memory_pressure(self):
        from flink_ml_tpu.serving import ModelServer
        from flink_ml_tpu.serving.errors import (
            SHED_MEMORY_PRESSURE,
            ServerOverloadedError,
        )

        model, t = self._model_and_table(n=512)
        obs.enable()
        obs.reset()
        # features are 512x5 f32 + 512x8 label: one row ~ 28 bytes; cap
        # the queue at ~2 KiB so the third 32-row request cannot fit
        server = ModelServer(model, queue_cap=4096,
                             queue_cap_mb=2.0 / 1024.0, max_wait_ms=1,
                             start=False)
        server.submit(t.slice_rows(0, 32))
        server.submit(t.slice_rows(32, 64))
        with pytest.raises(ServerOverloadedError) as ei:
            server.submit(t.slice_rows(64, 96))
        assert ei.value.reason == SHED_MEMORY_PRESSURE
        c = obs.registry().snapshot()["counters"]
        assert c.get(f"serving.shed.{SHED_MEMORY_PRESSURE}", 0) == 1, c
        server.start()
        server.shutdown()  # drains the two admitted requests

    def test_bytes_cap_off_by_default(self):
        from flink_ml_tpu.serving.admission import ServingConfig

        assert ServingConfig.from_env().queue_cap_bytes == 0
        cfg = ServingConfig.from_env(queue_cap_mb=1.5)
        assert cfg.queue_cap_bytes == int(1.5 * (1 << 20))

    def test_table_nbytes_estimates_schema_width(self):
        from flink_ml_tpu.serving.admission import table_nbytes

        t = _dense_table(n=64, dim=5)
        est = table_nbytes(t)
        # 64 rows x (5 f32 features + 1 f64 label) = 64*(20+8)
        assert est == 64 * (5 * 4 + 8)


class TestTrainingUnderPressure:
    def test_fit_under_ceiling_matches_exactly(self):
        """Injected OOM above the window size: the micro-batch fallback
        streams the identical update schedule — params EXACTLY equal the
        unpressured fit's."""
        t = _dense_table()
        est = lambda: _logreg(iters=4, global_batch_size=32)  # noqa: E731
        m0 = est().fit(t)
        w0 = np.asarray(m0.coefficients())
        b0 = float(m0.intercept())
        from flink_ml_tpu.table import slab_pool

        slab_pool.reset_pool()
        pressure.reset_states()
        obs.enable()
        obs.reset()
        injection.configure("fault.oom>64")
        try:
            m1 = est().fit(t)
        finally:
            injection.configure(None)
        np.testing.assert_array_equal(np.asarray(m1.coefficients()), w0)
        assert float(m1.intercept()) == b0
        c = obs.registry().snapshot()["counters"]
        assert c.get("pressure.ooms.train.glm", 0) >= 1, c
        assert c.get("train.pressure_runs", 0) >= 1, c
        # the state remembers: a second pressured fit re-bisects nothing
        obs.reset()
        injection.configure("fault.oom>64")
        try:
            m2 = est().fit(t)
        finally:
            injection.configure(None)
        np.testing.assert_array_equal(np.asarray(m2.coefficients()), w0)
        c = obs.registry().snapshot()["counters"]
        assert c.get("pressure.ooms.train.glm", 0) == 0, c

    def test_single_step_accumulation_deterministic_and_close(self):
        """A ceiling below even one SGD step forces within-step gradient
        accumulation: sum-based, ascending-chunk order — deterministic
        across runs, and numerically within f32 accumulation tolerance
        of the unpressured fit."""
        from flink_ml_tpu.table import slab_pool

        t = _dense_table()
        est = lambda: _logreg(iters=4, global_batch_size=32)  # noqa: E731
        m0 = est().fit(t)
        w0 = np.asarray(m0.coefficients())

        def pressured_fit():
            slab_pool.reset_pool()
            pressure.reset_states()
            injection.configure("fault.oom>16")
            try:
                return est().fit(t)
            finally:
                injection.configure(None)

        obs.enable()
        m1, m2 = pressured_fit(), pressured_fit()
        np.testing.assert_array_equal(
            np.asarray(m1.coefficients()), np.asarray(m2.coefficients())
        )  # bitwise-stable accumulation order
        np.testing.assert_allclose(
            np.asarray(m1.coefficients()), w0, rtol=1e-5, atol=1e-6
        )
        c = obs.registry().snapshot()["counters"]
        assert c.get("pressure.accum_steps", 0) >= 1, c

    def test_aimd_restores_fused_path(self, monkeypatch):
        from flink_ml_tpu.table import slab_pool

        t = _dense_table()
        est = lambda: _logreg(iters=2, global_batch_size=32)  # noqa: E731
        slab_pool.reset_pool()
        obs.enable()
        injection.configure("fault.oom>64")
        try:
            est().fit(t)
        finally:
            injection.configure(None)
        st = pressure.state("train.glm")
        assert st.cap is not None
        monkeypatch.setenv("FMT_PRESSURE_PROBE_S", "0")
        for _ in range(20):
            st.admit(1024)
        assert st.cap is None
        obs.reset()
        est().fit(t)  # back on the fused whole-batch program
        c = obs.registry().snapshot()["counters"]
        assert c.get("train.fused_runs", 0) >= 1, c
        assert c.get("train.pressure_runs", 0) == 0, c

    def test_subprocess_fit_under_oom_matches_exactly(self, tmp_path):
        """The satellite contract end-to-end: a fresh process whose
        ENVIRONMENT carries the injected HBM ceiling (configured before
        any flink_ml_tpu import, like production FMT_FAULT_INJECT) fits
        through grad-accumulation windows and prints params BIT-IDENTICAL
        to the fault-free subprocess fit."""
        script = (
            "import numpy as np\n"
            "import jax\n"
            "jax.config.update('jax_platforms', 'cpu')\n"
            "jax.config.update('jax_enable_x64', True)\n"
            "from flink_ml_tpu.lib import LogisticRegression\n"
            "from flink_ml_tpu.table.schema import DataTypes, Schema\n"
            "from flink_ml_tpu.table.table import Table\n"
            "rng = np.random.RandomState(3)\n"
            "X = rng.randn(256, 5).astype(np.float32)\n"
            "y = (X[:, 0] > 0).astype(np.float64)\n"
            "t = Table.from_columns(Schema.of(('features', "
            "DataTypes.DENSE_VECTOR), ('label', 'double')), "
            "{'features': X, 'label': y})\n"
            "m = (LogisticRegression().set_vector_col('features')"
            ".set_label_col('label').set_prediction_col('p')"
            ".set_learning_rate(0.5).set_max_iter(4)"
            ".set_global_batch_size(32).fit(t))\n"
            "w = list(np.asarray(m.coefficients())) + [float(m.intercept())]\n"
            "print('PARAMS ' + ' '.join(f'{v:.17g}' for v in w))\n"
        )

        def run(spec):
            env = dict(os.environ)
            env.pop("FMT_FAULT_INJECT", None)
            if spec:
                env["FMT_FAULT_INJECT"] = spec
            env["FMT_OBS"] = "0"
            env["JAX_ENABLE_X64"] = "1"
            env["XLA_FLAGS"] = (
                env.get("XLA_FLAGS", "")
                + " --xla_force_host_platform_device_count=8"
            )
            out = subprocess.run(
                [sys.executable, "-c", script], capture_output=True,
                text=True, timeout=240, env=env, cwd=REPO,
            )
            assert out.returncode == 0, out.stderr[-2000:]
            lines = [ln for ln in out.stdout.splitlines()
                     if ln.startswith("PARAMS")]
            assert lines, out.stdout
            return lines[0]

        clean = run(None)
        pressured = run("fault.oom>64")
        assert pressured == clean, (pressured, clean)


class TestPressureStateUnit:
    def test_shrink_halves_and_admit_probes(self, monkeypatch):
        st = pressure.PressureState("unit")
        assert st.admit(1000) == 1000
        st.shrink(1000)
        assert st.cap == 500
        st.shrink(500)
        assert st.cap == 250
        monkeypatch.setenv("FMT_PRESSURE_PROBE_S", "3600")
        assert st.admit(1000) == 250  # probe interval not elapsed
        monkeypatch.setenv("FMT_PRESSURE_PROBE_S", "0")
        assert st.admit(1000) == 375  # +1000//8
        assert st.capped_below(1000)
        assert not st.capped_below(300)

    def test_probe_interval_respected(self, monkeypatch):
        st = pressure.PressureState("unit2")
        st.admit(800)
        st.shrink(800)
        monkeypatch.setenv("FMT_PRESSURE_PROBE_S", "60")
        before = st.cap
        st.admit(800)
        assert st.cap == before  # too soon to probe
        st._last_change = time.monotonic() - 61
        st.admit(800)
        assert st.cap == before + 100  # 800 // 8
