"""Replica router (ISSUE 13) — scale-out front-end over the telemetry
plane: shed-reason classification, health-aware power-of-two-choices
balancing, drain-aware rolling deploys, crash supervision — plus the
PR's satellites (ephemeral telemetry-port discovery via
``FMT_TELEMETRY_PORT_FILE`` / ``ModelServer.telemetry_address``, the
wire table codec's bit-identity).

Two tiers: routing POLICY is tested against in-process fakes speaking
the ``ReplicaClient`` protocol (scripted sheds, real ``ModelServer``
backends — fast, deterministic), and the subprocess SUBSTRATE (spawn,
handshake, wire parity, SIGKILL -> respawn) against real replica
children.
"""

import os
import signal
import threading
import time

import numpy as np
import pytest

from flink_ml_tpu.api.pipeline import Pipeline
from flink_ml_tpu.lib import LogisticRegression
from flink_ml_tpu.lib.feature import StandardScaler
from flink_ml_tpu.obs import telemetry
from flink_ml_tpu.serving import (
    ModelServer,
    ReplicaClient,
    ReplicaProcess,
    ReplicaRemoteError,
    ReplicaRouter,
    ReplicaUnreachableError,
    RollingDeployError,
    RouterConfig,
    ServerClosedError,
    ServerOverloadedError,
    shed_policy,
)
from flink_ml_tpu.serving.batcher import ServeResult
from flink_ml_tpu.serving.errors import (
    POLICY_FAIL,
    POLICY_RETRY,
    POLICY_ROUTE_AWAY,
)
from flink_ml_tpu.serving.replica import decode_table, encode_table
from flink_ml_tpu.table.schema import DataTypes, Schema
from flink_ml_tpu.table.table import Table

N, D = 256, 5
SCHEMA = Schema.of(("features", DataTypes.DENSE_VECTOR), ("label", "double"))
WAIT = 60  # generous future timeout: a hang fails loudly, not flakily


@pytest.fixture(scope="module")
def dense_table():
    rng = np.random.RandomState(11)
    X = (2.0 * rng.randn(N, D) + 1.0).astype(np.float32)
    w = rng.randn(D).astype(np.float32)
    y = ((X - 1.0) @ w > 0).astype(np.float64)
    return Table.from_columns(SCHEMA, {"features": X, "label": y})


def _fit(table, max_iter):
    return Pipeline([
        StandardScaler().set_selected_col("features"),
        LogisticRegression().set_vector_col("features")
        .set_label_col("label").set_prediction_col("pred")
        .set_learning_rate(0.5).set_max_iter(max_iter),
    ]).fit(table)


@pytest.fixture(scope="module")
def saved(tmp_path_factory, dense_table):
    """Two fitted+saved pipeline versions plus their solo predictions —
    the parity oracle every routed request is judged against."""
    root = tmp_path_factory.mktemp("router_models")
    m1, m2 = _fit(dense_table, 3), _fit(dense_table, 5)
    paths = {"v1": str(root / "v1"), "v2": str(root / "v2")}
    m1.save(paths["v1"])
    m2.save(paths["v2"])
    solo = {}
    for version, m in (("v1", m1), ("v2", m2)):
        (out,) = m.transform(dense_table)
        solo[version] = np.asarray(out.col("pred"))
    return {"paths": paths, "models": {"v1": m1, "v2": m2}, "solo": solo}


# -- shed-reason retryability (satellite) -------------------------------------


class TestShedPolicy:
    def test_transient_load_reasons_retry_elsewhere(self):
        for reason in ("queue_full", "memory_pressure", "deadline_expired"):
            assert shed_policy(reason) == POLICY_RETRY, reason
            assert ServerOverloadedError(reason).retryable is True

    def test_replica_degradation_routes_away(self):
        for reason in ("shutdown", "breaker_open"):
            assert shed_policy(reason) == POLICY_ROUTE_AWAY, reason
            assert ServerOverloadedError(reason).retryable is True

    def test_unknown_reasons_fail_conservatively(self):
        for reason in ("no_replica", "some_future_reason", ""):
            assert shed_policy(reason) == POLICY_FAIL, reason
            assert ServerOverloadedError(reason).retryable is False


# -- router config ------------------------------------------------------------


class TestRouterConfig:
    def test_env_defaults(self):
        cfg = RouterConfig.from_env()
        assert cfg.replicas == 2
        assert cfg.queue_cap == 4096
        assert cfg.retries == 2

    def test_overrides_win(self, monkeypatch):
        monkeypatch.setenv("FMT_ROUTER_REPLICAS", "7")
        assert RouterConfig.from_env().replicas == 7
        assert RouterConfig.from_env(replicas=3).replicas == 3

    def test_validation(self):
        with pytest.raises(ValueError):
            RouterConfig.from_env(replicas=0)


# -- telemetry port discovery (satellite) -------------------------------------


class TestPortFile:
    def test_round_trip(self, tmp_path):
        path = str(tmp_path / "addr")
        telemetry.write_port_file(path, "127.0.0.1", 12345)
        assert telemetry.read_port_file(path) == ("127.0.0.1", 12345)

    def test_stale_file_is_overwritten(self, tmp_path):
        """A file left by a previous (crashed, recycled) process must be
        REPLACED on bind — a reader can never see the stale address, a
        partial write, or a concatenation of the two."""
        path = str(tmp_path / "addr")
        with open(path, "w") as f:
            f.write("127.0.0.1:9\n")  # a previous run's port
        telemetry.write_port_file(path, "127.0.0.1", 54321)
        assert telemetry.read_port_file(path) == ("127.0.0.1", 54321)
        assert open(path).read() == "127.0.0.1:54321\n"

    def test_malformed_file_raises_for_retry(self, tmp_path):
        path = str(tmp_path / "addr")
        with open(path, "w") as f:
            f.write("garbage")
        with pytest.raises(ValueError):
            telemetry.read_port_file(path)

    def test_telemetry_server_publishes_on_bind(self, tmp_path,
                                                monkeypatch):
        """The ephemeral-port discovery fix: with ``FMT_TELEMETRY_PORT=0``
        the bound port was only observable in-process — the knob file is
        how a parent finds its child's endpoint."""
        path = str(tmp_path / "addr")
        monkeypatch.setenv("FMT_TELEMETRY_PORT_FILE", path)
        server = telemetry.TelemetryServer(port=0).start()
        try:
            host, port = telemetry.read_port_file(path)
            assert (host, port) == (server.host, server.port)
        finally:
            server.stop()

    def test_model_server_telemetry_address(self, tmp_path, monkeypatch,
                                            dense_table, saved):
        path = str(tmp_path / "addr")
        monkeypatch.setenv("FMT_TELEMETRY_PORT_FILE", path)
        server = ModelServer(saved["models"]["v1"], telemetry_port=0)
        try:
            address = server.telemetry_address
            assert address is not None
            host, port = telemetry.read_port_file(path)
            assert address == f"{host}:{port}"
        finally:
            server.shutdown()
        assert server.telemetry_address is None


# -- the wire table codec -----------------------------------------------------


class TestWireTables:
    def test_round_trip_is_bit_identical(self, dense_table):
        wire = encode_table(dense_table)
        back = decode_table(wire)
        assert back.schema.field_names == dense_table.schema.field_names
        assert back.schema.field_types == dense_table.schema.field_types
        for name in dense_table.schema.field_names:
            np.testing.assert_array_equal(
                np.asarray(back.col(name)),
                np.asarray(dense_table.col(name)), err_msg=name)

    def test_encode_strips_process_local_state(self, dense_table):
        names, types, cols = encode_table(dense_table)
        assert set(cols) == set(names)
        # the wire tuple carries only schema lists + column buffers — a
        # pack cache (which may pin device arrays) must never ride along
        assert all(not hasattr(v, "_pack_cache") for v in cols.values())


# -- routing policy against scripted fakes ------------------------------------


class _FakeClient:
    """Scripted ReplicaClient: ``script`` entries are consumed per
    submit — an exception instance raises, anything else echoes the
    request back as a served result."""

    def __init__(self, name, script=(), queue_depth=0.0):
        self.name = name
        self.script = list(script)
        self.queue_depth = queue_depth
        self.submits = 0
        self.deploys = []

    def submit(self, table, deadline_ms=None, timeout_s=120.0):
        self.submits += 1
        if self.script:
            step = self.script.pop(0)
            if isinstance(step, BaseException):
                raise step
        return ServeResult(table=table, quarantine={}, version="v1")

    def deploy(self, path, version, timeout_s=600.0):
        self.deploys.append((path, version))
        return version

    def probe(self, timeout_s=2.0, depth=True):
        out = {"ready": True, "reasons": []}
        if depth:
            out["queue_depth"] = self.queue_depth
        return out


def _fake_router(clients, **kw):
    table = {f"replica-{i}-g{i + 1}": c for i, c in enumerate(clients)}

    def factory(name, path, version):
        return table[name], None

    # park the poll loop out of the way: policy tests script the replica
    # responses and must not race a probe re-admitting a shed replica
    # (shutdown still returns immediately — the stop event interrupts
    # the wait)
    kw.setdefault("poll_ms", 600_000.0)
    return ReplicaRouter("/nonexistent", replicas=len(clients),
                         replica_factory=factory, **kw)


class TestRoutingPolicy:
    def test_served_request_resolves(self, dense_table):
        a, b = _FakeClient("a"), _FakeClient("b")
        router = _fake_router([a, b])
        try:
            res = router.predict(dense_table.slice_rows(0, 4), timeout=WAIT)
            assert res.num_rows == 4
            assert a.submits + b.submits == 1
        finally:
            router.shutdown()

    def test_transient_shed_retries_on_another_replica(self, dense_table):
        a = _FakeClient("a", script=[ServerOverloadedError("queue_full")])
        b = _FakeClient("b", script=[ServerOverloadedError("queue_full")])
        router = _fake_router([a, b])
        try:
            res = router.predict(dense_table.slice_rows(0, 4), timeout=WAIT)
            assert res.num_rows == 4
            # whichever replica shed first, the OTHER was tried next —
            # and its own first shed retried back (budget is 2)
            assert a.submits + b.submits >= 2
            assert router.stats().get("router.retries", 0) >= 1
        finally:
            router.shutdown()

    def test_route_away_ejects_the_replica_from_rotation(self, dense_table):
        a = _FakeClient("a", script=[
            ServerOverloadedError("breaker_open")] * 50)
        b = _FakeClient("b")
        router = _fake_router([a, b])
        try:
            for i in range(10):
                router.predict(dense_table.slice_rows(i, i + 1),
                               timeout=WAIT)
            # after a's first breaker_open shed it left the rotation (no
            # probe clears it: the poll interval is parked at 1s): every
            # later request went straight to b
            assert a.submits == 1
            assert b.submits == 10
            snapshot = {r["name"]: r for r in router.replicas}
            bad = [r for r in snapshot.values()
                   if r["reasons"] == ["breaker_open"]]
            assert len(bad) == 1
        finally:
            router.shutdown()

    def test_unknown_shed_reason_reaches_the_caller(self, dense_table):
        a = _FakeClient("a", script=[
            ServerOverloadedError("mystery_reason")] * 5)
        b = _FakeClient("b", script=[
            ServerOverloadedError("mystery_reason")] * 5)
        router = _fake_router([a, b])
        try:
            with pytest.raises(ServerOverloadedError) as excinfo:
                router.predict(dense_table.slice_rows(0, 4), timeout=WAIT)
            assert excinfo.value.reason == "mystery_reason"
            assert excinfo.value.retryable is False
            assert a.submits + b.submits == 1  # no blind retry
        finally:
            router.shutdown()

    def test_remote_error_propagates_without_cross_replica_retry(
            self, dense_table):
        a = _FakeClient("a", script=[
            ReplicaRemoteError("ValueError", "bad rows")] * 5)
        b = _FakeClient("b", script=[
            ReplicaRemoteError("ValueError", "bad rows")] * 5)
        router = _fake_router([a, b])
        try:
            with pytest.raises(ReplicaRemoteError) as excinfo:
                router.predict(dense_table.slice_rows(0, 4), timeout=WAIT)
            assert excinfo.value.remote_type == "ValueError"
            assert a.submits + b.submits == 1  # deterministic: no retry
        finally:
            router.shutdown()

    def test_unreachable_replica_retries_elsewhere(self, dense_table):
        a = _FakeClient("a", script=[
            ReplicaUnreachableError("conn refused")] * 50)
        b = _FakeClient("b")
        router = _fake_router([a, b])
        try:
            for i in range(6):
                res = router.predict(dense_table.slice_rows(i, i + 1),
                                     timeout=WAIT)
                assert res.num_rows == 1
        finally:
            router.shutdown()

    def test_power_of_two_choices_prefers_the_lighter_replica(
            self, dense_table):
        """With exactly two candidates P2C samples both every time, so
        the lower-load replica must win EVERY pick."""
        heavy = _FakeClient("a", queue_depth=1000.0)
        light = _FakeClient("b", queue_depth=0.0)
        router = _fake_router([heavy, light], poll_ms=10.0)
        try:
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:  # probes import the depths
                snap = {r["name"]: r["queue_depth"]
                        for r in router.replicas}
                if snap.get("replica-0-g1") == 1000.0:
                    break
                time.sleep(0.01)
            for i in range(12):
                router.predict(dense_table.slice_rows(i, i + 1),
                               timeout=WAIT)
            assert light.submits >= 12
            assert heavy.submits == 0
        finally:
            router.shutdown()

    def test_queue_cap_sheds_at_the_door(self, dense_table):
        router = _fake_router([_FakeClient("a")], queue_cap=8,
                              dispatch_threads=1, start=False)
        try:
            router.submit(dense_table.slice_rows(0, 8))  # fills the cap
            with pytest.raises(ServerOverloadedError) as excinfo:
                router.submit(dense_table.slice_rows(8, 16))
            assert excinfo.value.reason == "queue_full"
        finally:
            router.shutdown()

    def test_submit_after_shutdown_raises_closed(self, dense_table):
        router = _fake_router([_FakeClient("a")])
        router.shutdown()
        with pytest.raises(ServerClosedError):
            router.submit(dense_table.slice_rows(0, 1))

    def test_empty_request_rejected(self, dense_table):
        router = _fake_router([_FakeClient("a")], start=False)
        try:
            with pytest.raises(ValueError):
                router.submit(dense_table.slice_rows(0, 0))
        finally:
            router.shutdown()


# -- rolling deploy over in-process ModelServer backends ----------------------


class _LocalClient:
    """The ReplicaClient protocol over an IN-PROCESS ModelServer — full
    deploy/serve fidelity without subprocess cost.  ``gate`` (optional)
    blocks deploys so drain interleavings can be scripted."""

    def __init__(self, server, gate=None):
        self.server = server
        self.gate = gate
        self.submits = 0
        self.deploy_started = threading.Event()

    def submit(self, table, deadline_ms=None, timeout_s=120.0):
        self.submits += 1
        return self.server.predict(table, deadline_ms=deadline_ms,
                                   timeout=timeout_s)

    def deploy(self, path, version, timeout_s=600.0):
        self.deploy_started.set()
        if self.gate is not None:
            assert self.gate.wait(WAIT)
        self.server.deploy(path, version)
        return self.server.active_version

    def probe(self, timeout_s=2.0, depth=True):
        return {"ready": True, "reasons": [], "queue_depth": 0.0}


def _local_router(saved, n=2, gates=None, **kw):
    servers = [ModelServer(path=saved["paths"]["v1"], version="v1")
               for _ in range(n)]
    clients = [_LocalClient(s, gate=(gates or {}).get(i))
               for i, s in enumerate(servers)]
    table = {f"replica-{i}-g{i + 1}": c for i, c in enumerate(clients)}

    def factory(name, path, version):
        return table[name], None

    kw.setdefault("poll_ms", 600_000.0)
    router = ReplicaRouter(saved["paths"]["v1"], version="v1", replicas=n,
                           replica_factory=factory, **kw)
    return router, servers, clients


class TestRollingDeploy:
    def test_outputs_bit_identical_across_the_version_boundary(
            self, dense_table, saved):
        router, servers, clients = _local_router(saved)
        try:
            for i in range(4):
                res = router.predict(dense_table.slice_rows(i * 8,
                                                            i * 8 + 8),
                                     timeout=WAIT)
                assert res.version == "v1"
                np.testing.assert_array_equal(
                    np.asarray(res.table.col("pred")),
                    saved["solo"]["v1"][i * 8:i * 8 + 8])
            status = router.deploy(saved["paths"]["v2"], "v2")
            assert status["ok"] is True
            assert [r["outcome"] for r in status["replicas"]] == \
                ["deployed", "deployed"]
            assert router.active_version == "v2"
            assert all(s.active_version == "v2" for s in servers)
            for i in range(4):
                res = router.predict(dense_table.slice_rows(i * 8,
                                                            i * 8 + 8),
                                     timeout=WAIT)
                assert res.version == "v2"
                np.testing.assert_array_equal(
                    np.asarray(res.table.col("pred")),
                    saved["solo"]["v2"][i * 8:i * 8 + 8])
        finally:
            router.shutdown()
            for s in servers:
                s.shutdown()

    def test_draining_replica_takes_no_new_requests(self, dense_table,
                                                    saved):
        """The drain contract: while replica 0 swaps, every new request
        routes to the rest of the fleet — a deploy sheds nothing."""
        gate = threading.Event()
        router, servers, clients = _local_router(saved, gates={0: gate})
        try:
            submits_before = clients[0].submits
            deployer = threading.Thread(
                target=router.deploy,
                args=(saved["paths"]["v2"], "v2"), daemon=True)
            deployer.start()
            assert clients[0].deploy_started.wait(WAIT)
            # replica 0 is mid-deploy (drained, gated): traffic flows,
            # all of it on replica 1
            for i in range(8):
                res = router.predict(dense_table.slice_rows(i, i + 4),
                                     timeout=WAIT)
                assert res.num_rows == 4
            assert clients[0].submits == submits_before
            assert clients[1].submits >= 8
            gate.set()
            deployer.join(WAIT)
            assert not deployer.is_alive()
            assert router.deploy_status["ok"] is True
        finally:
            gate.set()
            router.shutdown()
            for s in servers:
                s.shutdown()

    def test_drain_waits_for_in_flight_requests(self, dense_table, saved):
        """Deploy must not reach a replica while a router-originated
        request is still in flight on it."""
        router, servers, clients = _local_router(saved, n=1)
        release = threading.Event()
        entered = threading.Event()
        order = []
        real_submit = clients[0].submit
        real_deploy = clients[0].deploy

        def slow_submit(table, **kw):
            entered.set()
            assert release.wait(WAIT)
            order.append("submit_done")
            return real_submit(table, **kw)

        def tracked_deploy(path, version, **kw):
            order.append("deploy")
            return real_deploy(path, version, **kw)

        clients[0].submit = slow_submit
        clients[0].deploy = tracked_deploy
        try:
            fut = router.submit(dense_table.slice_rows(0, 4))
            assert entered.wait(WAIT)  # request is in flight on replica 0
            deployer = threading.Thread(
                target=router.deploy,
                args=(saved["paths"]["v2"], "v2"), daemon=True)
            deployer.start()
            time.sleep(0.2)  # the deploy is draining: no deploy() yet
            assert order == []
            release.set()
            deployer.join(WAIT)
            assert order == ["submit_done", "deploy"]
            assert fut.result(WAIT).num_rows == 4
        finally:
            release.set()
            router.shutdown()
            for s in servers:
                s.shutdown()

    def test_corrupt_deploy_rolls_back_one_replica_and_stops(
            self, dense_table, saved, tmp_path):
        """The partial-deploy contract: a corrupt artifact fails on the
        FIRST replica (which keeps serving its old version — the swap
        contract is the rollback), the roll stops, the fleet stays on
        the known-good version, and the router reports partial status."""
        import glob

        bad_dir = str(tmp_path / "bad")
        saved["models"]["v2"].save(bad_dir)
        mdf = glob.glob(os.path.join(bad_dir, "stage_*",
                                     "model_data.jsonl"))[0]
        blob = bytearray(open(mdf, "rb").read())
        blob[len(blob) // 2] ^= 0xFF
        with open(mdf, "wb") as f:
            f.write(bytes(blob))
        router, servers, clients = _local_router(saved)
        try:
            with pytest.raises(RollingDeployError) as excinfo:
                router.deploy(bad_dir, "v2")
            status = excinfo.value.status
            assert status["ok"] is False
            outcomes = [r["outcome"] for r in status["replicas"]]
            assert outcomes == ["failed"]  # the roll stopped at replica 0
            assert status["replicas"][0]["error"] == "ModelIntegrityError"
            assert router.deploy_status == status
            # the fleet never left the known-good version
            assert router.active_version == "v1"
            assert all(s.active_version == "v1" for s in servers)
            res = router.predict(dense_table.slice_rows(0, 8),
                                 timeout=WAIT)
            assert res.version == "v1"
            np.testing.assert_array_equal(
                np.asarray(res.table.col("pred")),
                saved["solo"]["v1"][:8])
        finally:
            router.shutdown()
            for s in servers:
                s.shutdown()


# -- elastic membership + crash supervision (round 22) ------------------------


class _FakeProc:
    """A scriptable ReplicaProcess stand-in: ``exit_code`` is waitpid's
    verdict (None = alive)."""

    def __init__(self, exit_code=None):
        self.pid = 4242
        self.serve_address = "127.0.0.1:1"
        self.telemetry_address = "127.0.0.1:2"
        self.exit_code = exit_code
        self.stopped = False

    def poll_dead(self):
        return self.exit_code

    def alive(self):
        return self.exit_code is None

    def stop(self, grace_s=None):
        self.stopped = True
        if self.exit_code is None:
            self.exit_code = 0


class _FlakyProbeClient(_FakeClient):
    """A _FakeClient whose next ``fail_probes`` probes raise — a replica
    that blackholes scrapes while its process stays alive."""

    def __init__(self, name, fail_probes=0, **kw):
        super().__init__(name, **kw)
        self.fail_probes = fail_probes
        self.probes = 0

    def probe(self, timeout_s=2.0, depth=True):
        self.probes += 1
        if self.fail_probes > 0:
            self.fail_probes -= 1
            raise ReplicaUnreachableError("blackholed scrape")
        return super().probe(timeout_s=timeout_s, depth=depth)


def _proc_router(make_replica, n=1, **kw):
    """Router over process-backed fakes; the factory serves boots AND
    respawns.  ``make_replica(name) -> (client, proc)``."""

    def factory(name, path, version):
        return make_replica(name)

    kw.setdefault("poll_ms", 600_000.0)
    return ReplicaRouter("/nonexistent", replicas=n,
                         replica_factory=factory, **kw)


class TestScrapeStrikes:
    def test_config_knobs(self, monkeypatch):
        cfg = RouterConfig.from_env()
        assert cfg.scrape_strikes == 3
        assert cfg.crashloop_max == 3
        assert cfg.crashloop_window_s == 30.0
        monkeypatch.setenv("FMT_ROUTER_SCRAPE_STRIKES", "5")
        assert RouterConfig.from_env().scrape_strikes == 5
        assert RouterConfig.from_env(scrape_strikes=2).scrape_strikes == 2

    def test_strikes_accumulate_before_eviction(self):
        """The debounce unit contract: below the strike count the
        replica keeps its rotation slot; at the count it leaves with the
        ``unreachable`` reason; one good probe clears the tally."""
        from flink_ml_tpu.serving.router import _Replica

        replica = _Replica("r", _FakeClient("r"), scrape_strikes=3)
        replica.mark_probe({"ready": True, "reasons": []})
        assert replica.note_probe_failure() == 1
        assert replica.routable() is True
        assert replica.note_probe_failure() == 2
        assert replica.routable() is True
        assert replica.note_probe_failure() == 3
        assert replica.routable() is False
        assert replica.snapshot()["reasons"] == ["unreachable"]
        replica.mark_probe({"ready": True, "reasons": []})
        assert replica.routable() is True
        assert replica.note_probe_failure() == 1  # tally was reset

    def test_one_blackholed_scrape_keeps_the_replica_routable(self):
        """The red test this satellite exists for: a live replica that
        drops ONE scrape then recovers must never leave rotation — the
        probe pass itself re-probes (jittered) and comes back green."""
        client = _FlakyProbeClient("a")
        router = _proc_router(lambda name: (client, _FakeProc()))
        try:
            replica = router._replicas_snapshot()[0]
            assert replica.routable() is True
            probes_before = client.probes
            client.fail_probes = 1  # blackhole exactly the next scrape
            router._probe_replica(0, replica, depth=True)
            # the failed scrape was retried within the SAME probe pass
            assert client.probes >= probes_before + 2
            assert replica.routable() is True
            assert replica.is_dead() is False
        finally:
            router.shutdown()

    def test_sustained_blackhole_routes_away_after_strikes(self):
        client = _FlakyProbeClient("a", fail_probes=0)
        router = _proc_router(lambda name: (client, _FakeProc()))
        try:
            replica = router._replicas_snapshot()[0]
            probes_before = client.probes
            client.fail_probes = 50  # a real blackhole, not a blip
            router._probe_replica(0, replica, depth=True)
            # struck out at exactly the configured count — no more
            assert client.probes == probes_before + 3
            assert replica.routable() is False
            assert replica.snapshot()["reasons"] == ["unreachable"]
            # the process is alive: routed away, NOT declared dead
            assert replica.is_dead() is False
        finally:
            router.shutdown()

    def test_waitpid_death_is_immediate_despite_strikes(self):
        """Strikes debounce SCRAPES only: a reaped child is dead on the
        very next liveness sweep, zero probe failures required."""
        procs = []

        def make(name):
            proc = _FakeProc()
            procs.append(proc)
            return _FakeClient(name), proc

        router = _proc_router(make)
        try:
            replica = router._replicas_snapshot()[0]
            assert replica.routable() is True
            procs[0].exit_code = 9  # SIGKILLed out from under us
            router._sweep_liveness()
            assert replica.is_dead() is True
            deadline = time.monotonic() + WAIT
            while time.monotonic() < deadline:
                if router.stats().get("router.respawns", 0) >= 1:
                    break
                time.sleep(0.01)
            assert router.stats().get("router.respawns", 0) >= 1
        finally:
            router.shutdown()


class TestCrashLoopQuarantine:
    def test_crashloop_quarantines_instead_of_hot_respawn(self):
        """A slot whose replacements die on arrival must stop burning
        the spawn path: after ``crashloop_max`` deaths in the window the
        slot is quarantined with backoff, observably."""
        spawned = []

        def make(name):
            # first boot lives; every replacement is born dead
            proc = _FakeProc(exit_code=None if not spawned else 1)
            spawned.append(name)
            return _FakeClient(name), proc

        router = _proc_router(make, crashloop_max=2,
                              crashloop_window_s=30.0)
        try:
            first = router._replicas_snapshot()[0]
            first.process.exit_code = 1  # kill the original
            deadline = time.monotonic() + WAIT
            while time.monotonic() < deadline:
                router._sweep_liveness()
                if router.quarantined_count() == 1:
                    break
                time.sleep(0.01)
            assert router.quarantined_count() == 1
            stats = router.stats()
            assert stats.get("router.crashloops", 0) >= 1
            assert "0" in stats["quarantined_slots"]
            assert stats["quarantined_slots"]["0"]["episodes"] >= 1
            # no hot loop: during the backoff the spawn count is frozen
            spawns_at_quarantine = len(spawned)
            time.sleep(0.5)
            router._sweep_liveness()
            assert len(spawned) == spawns_at_quarantine
        finally:
            router.shutdown()

    def test_crashloop_flight_event_names_slot_and_status(self):
        from flink_ml_tpu.obs import flight

        def make(name):
            return _FakeClient(name), _FakeProc(exit_code=None)

        router = _proc_router(make, crashloop_max=1,
                              crashloop_window_s=30.0)
        try:
            router._replicas_snapshot()[0].process.exit_code = 7
            deadline = time.monotonic() + WAIT
            while time.monotonic() < deadline:
                router._sweep_liveness()
                if router.quarantined_count() == 1:
                    break
                time.sleep(0.01)
            events = [e for e in flight.events()
                      if e.get("kind") == "router.crashloop"]
            assert events, "no router.crashloop flight event recorded"
            assert events[-1]["slot"] == 0
            assert events[-1]["exit_status"] == 7
            assert events[-1]["backoff_s"] > 0
        finally:
            router.shutdown()


class TestElasticMembership:
    def test_add_replica_grows_the_fleet(self, dense_table):
        clients = {}

        def factory(name, path, version):
            clients[name] = _FakeClient(name)
            return clients[name], None

        router = ReplicaRouter("/nonexistent", replicas=1,
                               replica_factory=factory, poll_ms=600_000.0)
        try:
            assert router.fleet_size() == 1
            name = router.add_replica()
            assert name is not None and name in clients
            assert router.fleet_size() == 2
            assert router.ready_count() == 2
            res = router.predict(dense_table.slice_rows(0, 4), timeout=WAIT)
            assert res.num_rows == 4
            assert router.stats().get("router.replicas_added", 0) == 1
        finally:
            router.shutdown()

    def test_remove_replica_drains_before_terminating(self):
        # replica 0 carries scraped depth, so the idle replica 1 is the
        # least-loaded victim
        a = _FakeClient("a", queue_depth=5.0)
        b = _FakeClient("b")
        router = _fake_router([a, b])
        try:
            victim = router._replicas_snapshot()[1]
            victim.begin_dispatch()  # one request in flight on it
            threading.Timer(0.3, victim.end_dispatch).start()
            t0 = time.monotonic()
            removed = router.remove_replica()
            assert removed == victim.name
            assert time.monotonic() - t0 >= 0.25  # it WAITED for drain
            assert router.fleet_size() == 1
            # the slot is tombstoned, not reindexed
            slots = router._replicas_snapshot()
            assert len(slots) == 2 and slots[1] is None
            assert router.stats().get("router.replicas_removed", 0) == 1
        finally:
            router.shutdown()

    def test_remove_drain_timeout_readmits_the_replica(self):
        a = _FakeClient("a", queue_depth=5.0)
        b = _FakeClient("b")
        router = _fake_router([a, b], drain_timeout_s=0.2)
        victim = router._replicas_snapshot()[1]
        victim.begin_dispatch()  # never finishes inside the budget
        try:
            assert router.remove_replica() is None
            assert victim.routable() is True  # re-admitted, not wedged
            assert router.fleet_size() == 2
            assert router.stats().get(
                "router.remove_drain_timeouts", 0) == 1
        finally:
            victim.end_dispatch()
            router.shutdown()

    def test_never_removes_the_last_routable_replica(self):
        router = _fake_router([_FakeClient("a")])
        try:
            assert router.remove_replica() is None
            assert router.fleet_size() == 1
        finally:
            router.shutdown()

    def test_membership_blocked_while_deploy_holds_the_fleet(self):
        router = _fake_router([_FakeClient("a"), _FakeClient("b")])
        try:
            assert router._deploy_lock.acquire(blocking=False)
            try:
                assert router.add_replica() is None
                assert router.remove_replica() is None
            finally:
                router._deploy_lock.release()
        finally:
            router.shutdown()

    def test_fleet_health_aggregates_burn_and_probe_state(self):
        class _BurnClient(_FakeClient):
            def probe(self, timeout_s=2.0, depth=True):
                out = super().probe(timeout_s=timeout_s, depth=depth)
                if depth:
                    out["burn_rates"] = {"serving_p99_ms": 2.0}
                return out

        router = _fake_router([_BurnClient("a"), _FakeClient("b")])
        try:
            health = router.fleet_health()
            assert health["size"] == 2
            assert health["ready"] == 2
            assert health["quarantined"] == 0
            assert health["probe_suspect"] == 0
            # one replica exposes judged burn data; the fleet max rides up
            assert health["burn_seen"] is True
            assert health["max_burn_rate"] == 2.0
            # a struck-out replica reads as probe_suspect (a fail-closed
            # input for the autoscaler), not as idleness
            replica = router._replicas_snapshot()[1]
            for _ in range(router.config.scrape_strikes):
                replica.note_probe_failure()
            health = router.fleet_health()
            assert health["probe_suspect"] == 1
            assert health["ready"] == 1
        finally:
            router.shutdown()


# -- the real subprocess substrate --------------------------------------------


class TestReplicaSubprocess:
    def test_spawn_serve_deploy_stop(self, dense_table, saved, tmp_path):
        """One child, whole lifecycle: handshake publishes both
        endpoints, wire results are bit-identical to solo transforms,
        probes answer off the telemetry plane, a wire deploy swaps
        versions, a corrupt wire deploy raises the remote
        ModelIntegrityError, SIGTERM stops it cleanly."""
        import glob

        process = ReplicaProcess.spawn(saved["paths"]["v1"], "v1")
        try:
            client = ReplicaClient(process.serve_address,
                                   process.telemetry_address)
            # handshake files carry the BOUND addresses
            host, port = telemetry.read_port_file(
                os.path.join(process.workdir, "telemetry.addr"))
            assert f"{host}:{port}" == process.telemetry_address
            res = client.submit(dense_table.slice_rows(0, 16))
            assert res.version == "v1"
            np.testing.assert_array_equal(
                np.asarray(res.table.col("pred")),
                saved["solo"]["v1"][:16])
            probe = client.probe()
            assert probe["ready"] is True
            assert client.deploy(saved["paths"]["v2"], "v2") == "v2"
            res = client.submit(dense_table.slice_rows(0, 16))
            assert res.version == "v2"
            np.testing.assert_array_equal(
                np.asarray(res.table.col("pred")),
                saved["solo"]["v2"][:16])
            # a corrupt artifact is refused REMOTELY, old version serves
            bad_dir = str(tmp_path / "bad_wire")
            saved["models"]["v1"].save(bad_dir)
            mdf = glob.glob(os.path.join(bad_dir, "stage_*",
                                         "model_data.jsonl"))[0]
            blob = bytearray(open(mdf, "rb").read())
            blob[len(blob) // 2] ^= 0xFF
            with open(mdf, "wb") as f:
                f.write(bytes(blob))
            with pytest.raises(ReplicaRemoteError) as excinfo:
                client.deploy(bad_dir, "v3")
            assert excinfo.value.remote_type == "ModelIntegrityError"
            assert client.submit(dense_table.slice_rows(0, 4)
                                 ).version == "v2"
        finally:
            process.stop()
        assert not process.alive()
        assert process.poll_dead() == 0  # SIGTERM -> drain -> exit 0


class TestRouterLive:
    def test_parity_kill_respawn(self, dense_table, saved):
        """The chaos contract, in-suite: routed results are
        bit-identical to solo transforms; a SIGKILLed replica's traffic
        retries on the survivor with ZERO caller-visible failures and a
        replacement rejoins the fleet."""
        router = ReplicaRouter(saved["paths"]["v1"], version="v1",
                               replicas=2, poll_ms=25.0)
        try:
            futures = [router.submit(dense_table.slice_rows(i * 8,
                                                            i * 8 + 8))
                       for i in range(8)]
            for i, fut in enumerate(futures):
                res = fut.result(WAIT)
                assert res.version == "v1"
                np.testing.assert_array_equal(
                    np.asarray(res.table.col("pred")),
                    saved["solo"]["v1"][i * 8:i * 8 + 8])
            victim = router.replicas[0]["pid"]
            fails = []
            stop = threading.Event()

            def load():
                i = 0
                while not stop.is_set():
                    lo = (i * 4) % (N - 4)
                    try:
                        res = router.predict(
                            dense_table.slice_rows(lo, lo + 4),
                            timeout=WAIT)
                        np.testing.assert_array_equal(
                            np.asarray(res.table.col("pred")),
                            saved["solo"]["v1"][lo:lo + 4])
                    except BaseException as exc:  # noqa: BLE001
                        fails.append(exc)
                    i += 1
                    time.sleep(0.002)

            loader = threading.Thread(target=load, daemon=True)
            loader.start()
            time.sleep(0.2)
            os.kill(victim, signal.SIGKILL)
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline:
                stats = router.stats()
                if (stats.get("router.respawns", 0) >= 1
                        and router.ready_count() >= 2):
                    break
                time.sleep(0.1)
            stop.set()
            loader.join(WAIT)
            assert not fails, f"{len(fails)} requests failed: {fails[0]!r}"
            stats = router.stats()
            assert stats.get("router.replica_deaths", 0) >= 1
            assert stats.get("router.respawns", 0) >= 1
            assert router.ready_count() == 2
            res = router.predict(dense_table.slice_rows(0, 8),
                                 timeout=WAIT)
            np.testing.assert_array_equal(
                np.asarray(res.table.col("pred")), saved["solo"]["v1"][:8])
        finally:
            router.shutdown()
