"""Iteration runtime tests — the FLIP-176 semantics the reference specified
but never implemented (Iterations.java:38-49,93-96; IterationConfig lifecycles;
IterationListener callbacks; replay semantics; streaming windows)."""

import jax.numpy as jnp
import pytest

from flink_ml_tpu.iteration import (
    IterationBodyResult,
    IterationConfig,
    IterationListener,
    OperatorLifeCycle,
    ReplayableInputs,
    StreamingDriver,
    iterate_bounded,
    iterate_unbounded,
    train_epochs,
    train_until,
)
from flink_ml_tpu.table import DataTypes, GeneratorSource, Schema, Table


class RecordingListener(IterationListener):
    def __init__(self):
        self.epochs = []
        self.terminated = 0

    def on_epoch_watermark_incremented(self, epoch, context):
        self.epochs.append(epoch)
        context.output("epoch_log", epoch)

    def on_iteration_terminated(self, context):
        self.terminated += 1


class TestBounded:
    def test_max_epochs_termination(self):
        def body(state, inputs, epoch):
            return IterationBodyResult(feedback=state + 1)

        listener = RecordingListener()
        res = iterate_bounded(
            0, None, body, IterationConfig(max_epochs=5), listeners=[listener]
        )
        assert res.final_variables == 5
        assert res.epochs_run == 5
        assert listener.epochs == [0, 1, 2, 3, 4]
        assert listener.terminated == 1
        assert res.listener_context.get_outputs("epoch_log") == [0, 1, 2, 3, 4]

    def test_no_feedback_terminates(self):
        def body(state, inputs, epoch):
            if epoch == 2:
                return IterationBodyResult(feedback=None, outputs={"final": state})
            return IterationBodyResult(feedback=state * 2)

        res = iterate_bounded(1, None, body)
        assert res.epochs_run == 3
        assert res.last_output("final") == 4

    def test_empty_criteria_terminates(self):
        """Terminate when the criteria output is empty in a round
        (IterationBodyResult.java:44-48)."""

        def body(state, inputs, epoch):
            remaining = 3 - epoch
            criteria = Table.from_rows(
                [(i,) for i in range(remaining)], Schema(["c"], [DataTypes.INT])
            )
            return IterationBodyResult(feedback=state + 1, termination_criteria=criteria)

        res = iterate_bounded(0, None, body, IterationConfig(max_epochs=100))
        # epochs 0,1,2 have non-empty criteria; epoch 3's is empty -> stop
        assert res.epochs_run == 4
        assert res.final_variables == 4

    def test_replay_vs_no_replay(self):
        seen = []

        def body(state, inputs, epoch):
            seen.append(sorted(inputs.keys()))
            if epoch == 2:
                return IterationBodyResult(feedback=None)
            return IterationBodyResult(feedback=state)

        data = ReplayableInputs.replay(train=1).and_no_replay(init=2)
        iterate_bounded(0, data, body)
        assert seen[0] == ["init", "train"]  # epoch 0 gets both
        assert seen[1] == ["train"]  # later epochs only replayed inputs
        assert seen[2] == ["train"]

    def test_per_round_lifecycle_recreates_body(self):
        created = []

        def factory():
            created.append(True)

            def body(state, inputs, epoch):
                if epoch >= 2:
                    return IterationBodyResult(feedback=None)
                return IterationBodyResult(feedback=state)

            return body

        iterate_bounded(
            0,
            None,
            factory,
            IterationConfig(operator_life_cycle=OperatorLifeCycle.PER_ROUND),
        )
        assert len(created) == 3

    def test_bad_body_return_raises(self):
        with pytest.raises(TypeError, match="IterationBodyResult"):
            iterate_bounded(0, None, lambda s, i, e: 42)


class TestDeviceLoops:
    def test_train_epochs_scan(self):
        final = train_epochs(lambda s, e: s + 1.0, jnp.asarray(0.0), 10)
        assert float(final) == 10.0

    def test_train_until_convergence(self):
        # halve until below tol; epoch count comes back exact
        final, epochs = train_until(
            step=lambda s, e: s * 0.5,
            state=jnp.asarray(1.0),
            should_continue=lambda s, e: s > 0.01,
            max_epochs=100,
        )
        assert float(final) < 0.01
        assert int(epochs) == 7  # 1/2^7 < 0.01

    def test_train_until_respects_max(self):
        _, epochs = train_until(
            lambda s, e: s, jnp.asarray(1.0), lambda s, e: jnp.asarray(True), 5
        )
        assert int(epochs) == 5


def _train_source(rows, interval=1000):
    return GeneratorSource.linear_timestamps(
        rows, interval, Schema(["v"], [DataTypes.DOUBLE])
    )


class TestStreaming:
    def test_windows_fire_on_event_time(self):
        # 10 records at 1000ms spacing, 5000ms windows -> windows [0,5000),[5000,10000)
        rows = [(float(i),) for i in range(10)]
        updates = []

        def update(state, table, epoch):
            updates.append((epoch, table.col("v").tolist()))
            return state + table.num_rows()

        res = iterate_unbounded(0, _train_source(rows), update, window_ms=5000)
        assert res.windows_fired == 2
        assert updates[0] == (0, [0.0, 1.0, 2.0, 3.0, 4.0])
        assert updates[1] == (1, [5.0, 6.0, 7.0, 8.0, 9.0])
        assert res.final_state == 10

    def test_prediction_sees_freshest_model(self):
        """Predictor semantics (IncrementalLearningSkeleton.java:182-211):
        a prediction's result reflects the latest completed window."""
        train = _train_source([(1.0,), (2.0,), (3.0,), (4.0,)], interval=1000)
        # predictions at t=500 (before any window) and t=4500 (after window 0)
        pred_schema = Schema(["q"], [DataTypes.DOUBLE])

        def pred_gen():
            yield 500, (100.0,)
            yield 4500, (200.0,)

        pred = GeneratorSource(pred_gen, pred_schema)

        def update(state, table, epoch):
            return state + table.num_rows()

        def predict(state, batch):
            return [state] * batch.num_rows()

        res = StreamingDriver(window_ms=4000).run(
            0, train, update, prediction_source=pred, predict=predict
        )
        # window [0,4000) fires with 4 records? records at 0,1000,2000,3000 -> 4 rows
        by_ts = dict(res.predictions)
        assert by_ts[500] == 0  # before any model update
        assert by_ts[4500] == 4  # after first window (4 training rows seen)

    def test_empty_windows_skip_updates(self):
        def gen():
            yield 0, (1.0,)
            yield 20000, (2.0,)  # big event-time gap -> empty windows between

        src = GeneratorSource(gen, Schema(["v"], [DataTypes.DOUBLE]))
        count = []
        res = iterate_unbounded(
            0, src, lambda s, t, e: (count.append(e), s)[1], window_ms=5000
        )
        assert len(count) == 2  # only two non-empty windows fired

    def test_max_windows_stops(self):
        rows = [(float(i),) for i in range(100)]
        res = iterate_unbounded(
            0,
            _train_source(rows),
            lambda s, t, e: s + 1,
            window_ms=5000,
            max_windows=3,
        )
        assert res.windows_fired == 3

    def test_listener_epochs(self):
        listener = RecordingListener()
        rows = [(float(i),) for i in range(10)]
        iterate_unbounded(
            0,
            _train_source(rows),
            lambda s, t, e: s,
            window_ms=5000,
            listeners=[listener],
        )
        assert listener.epochs == [0, 1]
        assert listener.terminated == 1

    def test_mismatched_predict_args_raise(self):
        with pytest.raises(ValueError, match="together"):
            StreamingDriver(1000).run(
                0, _train_source([(1.0,)]), lambda s, t, e: s, predict=lambda s, b: []
            )


class TestStreamingRobustness:
    """Bounded out-of-orderness + streaming checkpoint (VERDICT r02 gaps
    #3/#4): the watermark machinery the reference gets from Flink
    (IncrementalLearningSkeleton.java:144-158 assigns timestamps AND
    watermarks; checkpointing.randomization in the root pom surefire)."""

    SCHEMA = Schema(["v"], [DataTypes.DOUBLE])

    def _collecting_update(self, store):
        def update(state, table, epoch):
            store.append((epoch, sorted(table.col("v").tolist())))
            return state + table.num_rows()

        return update

    def test_shuffled_within_lateness_lands_in_correct_window(self):
        # event times shuffled with <=2000ms disorder; windows of 5000ms
        order = [0, 3000, 1000, 6000, 4000, 2000, 9000, 7000, 5000, 8000]
        src = GeneratorSource(
            lambda: iter([(t, (float(t // 1000),)) for t in order]), self.SCHEMA
        )
        got = []
        res = iterate_unbounded(
            0, src, self._collecting_update(got), window_ms=5000,
            allowed_lateness_ms=2000,
        )
        assert res.late_records == []
        assert res.windows_fired == 2
        assert got[0] == (0, [0.0, 1.0, 2.0, 3.0, 4.0])
        assert got[1] == (1, [5.0, 6.0, 7.0, 8.0, 9.0])

    def test_beyond_lateness_goes_to_side_output(self):
        def gen():
            yield 0, (0.0,)
            yield 7000, (7.0,)  # watermark -> 7000, window [0,5000) fires
            yield 1000, (1.0,)  # >5000 late: its window already closed

        src = GeneratorSource(gen, self.SCHEMA)
        got = []
        res = iterate_unbounded(
            0, src, self._collecting_update(got), window_ms=5000,
            allowed_lateness_ms=0,
        )
        assert res.late_records == [(1000, (1.0,))]
        assert got[0] == (0, [0.0])  # the late record never corrupted a window

    def test_late_record_for_unfired_window_is_still_late(self):
        """Flink's isWindowLate rule: lateness is judged against the
        watermark, not against which windows happened to fire — a record
        whose (empty, never-fired) window the watermark already passed must
        not spawn a fresh one-record window."""
        def gen():
            yield 1000, (1.0,)    # opens [0,5000)
            yield 12000, (12.0,)  # wm=12000: fires [0,5000); [5000,10000) empty
            yield 6000, (6.0,)    # its window end 10000 <= wm: late

        src = GeneratorSource(gen, self.SCHEMA)
        got = []
        res = iterate_unbounded(
            0, src, self._collecting_update(got), window_ms=5000,
        )
        assert res.late_records == [(6000, (6.0,))]
        assert [g[1] for g in got] == [[1.0], [12.0]]

    def test_lateness_zero_in_order_behavior_unchanged(self):
        rows = [(float(i),) for i in range(10)]
        src = GeneratorSource.linear_timestamps(rows, 1000, self.SCHEMA)
        got = []
        res = iterate_unbounded(
            0, src, self._collecting_update(got), window_ms=5000
        )
        assert res.windows_fired == 2 and res.late_records == []

    def test_early_flush_waits_for_unfired_windows(self):
        """r3 advisor (medium): a prediction-buffer flush must not serve
        predictions past the watermark — a window with end <= their event
        time may still fire (or even open) while the watermark lags by the
        allowed lateness, and each record must see that window's post-update
        model."""
        train = GeneratorSource(
            lambda: iter([(500, (0.5,)), (6500, (6.5,))]), self.SCHEMA
        )
        pred_times = [1500, 1600, 12000, 12100]
        pred = GeneratorSource(
            lambda: iter([(t, (float(t),)) for t in pred_times]), self.SCHEMA
        )
        res = iterate_unbounded(
            0,
            train,
            lambda s, t, e: s + 1,  # state counts fired windows
            window_ms=1000,
            allowed_lateness_ms=5000,
            prediction_source=pred,
            predict=lambda s, b: [s] * b.num_rows(),
            prediction_flush_rows=2,
        )
        # model at t: windows [0,1000) and [6000,7000) fire before t>=7000
        assert dict(res.predictions) == {1500: 1, 1600: 1, 12000: 2, 12100: 2}

    def test_kill_resume_matches_uninterrupted(self, tmp_path):
        from flink_ml_tpu.iteration.checkpoint import CheckpointConfig

        rows = [(float(i),) for i in range(40)]

        def make_src():
            return GeneratorSource.linear_timestamps(rows, 1000, self.SCHEMA)

        def update(state, table, epoch):
            return state + float(sum((i + 1) * v for i, v in enumerate(table.col("v"))))

        baseline = iterate_unbounded(0.0, make_src(), update, window_ms=5000)

        cfg = CheckpointConfig(directory=str(tmp_path / "ck"), every_n_epochs=2)

        calls = {"n": 0}

        def crashing_update(state, table, epoch):
            calls["n"] += 1
            if epoch == 5:
                raise RuntimeError("killed mid-stream")
            return update(state, table, epoch)

        with pytest.raises(RuntimeError, match="killed"):
            iterate_unbounded(
                0.0, make_src(), crashing_update, window_ms=5000, checkpoint=cfg
            )
        resumed = iterate_unbounded(
            0.0, make_src(), update, window_ms=5000, checkpoint=cfg
        )
        assert resumed.windows_fired == baseline.windows_fired
        assert float(resumed.final_state) == float(baseline.final_state)

    def test_snapshot_restores_open_windows_and_watermark(self, tmp_path):
        """A snapshot taken while out-of-order windows are still open
        round-trips buffers through the codec and resumes bit-identically."""
        from flink_ml_tpu.iteration.checkpoint import CheckpointConfig

        # disorder keeps window N open while window N+1 accumulates
        times = []
        for base in range(0, 60000, 10000):
            times.extend([base + 6000, base + 1000, base + 9000, base + 4000])

        def make_src():
            return GeneratorSource(
                lambda: iter([(t, (float(t),)) for t in times]), self.SCHEMA
            )

        def update(state, table, epoch):
            return state + float(sum(table.col("v"))) * (epoch + 1)

        baseline = iterate_unbounded(
            0.0, make_src(), update, window_ms=5000, allowed_lateness_ms=4000
        )
        cfg = CheckpointConfig(directory=str(tmp_path / "ck"), every_n_epochs=3)

        def crashing_update(state, table, epoch):
            if epoch == 7:
                raise RuntimeError("killed")
            return update(state, table, epoch)

        with pytest.raises(RuntimeError, match="killed"):
            iterate_unbounded(
                0.0, make_src(), crashing_update, window_ms=5000,
                allowed_lateness_ms=4000, checkpoint=cfg,
            )
        resumed = iterate_unbounded(
            0.0, make_src(), update, window_ms=5000,
            allowed_lateness_ms=4000, checkpoint=cfg,
        )
        assert resumed.windows_fired == baseline.windows_fired
        assert float(resumed.final_state) == float(baseline.final_state)
        assert resumed.late_records == baseline.late_records

    def test_out_of_order_predictions_see_event_time_model(self):
        """A prediction record's result must reflect the model that was
        current at its EVENT time, even when it arrives out of order
        relative to training records (within the lateness bound)."""
        PRED_SCHEMA = Schema(["q"], [DataTypes.DOUBLE])

        def train_gen():
            yield 1000, (1.0,)
            yield 2000, (2.0,)
            yield 9000, (9.0,)   # fires window [0,5000) once wm passes

        def pred_gen():
            # arrives after the ts=9000 training record merged it late, but
            # its event time 3000 precedes window [0,5000)'s close
            yield 3000, (30.0,)
            yield 12000, (120.0,)

        def update(state, table, epoch):
            return state + table.num_rows()

        def predict(state, batch):
            return [state] * batch.num_rows()

        res = StreamingDriver(
            window_ms=5000, allowed_lateness_ms=4000
        ).run(
            0,
            GeneratorSource(train_gen, self.SCHEMA),
            update,
            prediction_source=GeneratorSource(pred_gen, PRED_SCHEMA),
            predict=predict,
        )
        by_ts = dict(res.predictions)
        assert by_ts[3000] == 0   # before window [0,5000) fired
        assert by_ts[12000] == 3  # after both windows fired (2 + 1 rows)
