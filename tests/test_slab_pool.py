"""Cross-fit device slab pool (ISSUE 2): content-identity keying, budgeted
LRU eviction, pin-during-dispatch refcounting, double-buffered placement,
and the warm-fit behavior of the estimator + inference paths."""

import gc
import warnings

import numpy as np
import pytest

from flink_ml_tpu import obs
from flink_ml_tpu.parallel.mesh import (
    default_mesh,
    shard_batch,
    shard_batch_prefetched,
)
from flink_ml_tpu.table import slab_pool
from flink_ml_tpu.table.schema import DataTypes, Schema
from flink_ml_tpu.table.table import Table


@pytest.fixture(autouse=True)
def _fresh_pool():
    slab_pool.reset_pool()
    yield
    slab_pool.reset_pool()


def _dense_table(X, y):
    schema = Schema.of(
        ("features", DataTypes.DENSE_VECTOR), ("label", "double")
    )
    return Table.from_columns(schema, {"features": X, "label": y})


def _logreg(lr=0.5, epochs=5):
    from flink_ml_tpu.lib import LogisticRegression

    return (
        LogisticRegression().set_vector_col("features")
        .set_label_col("label").set_prediction_col("p")
        .set_learning_rate(lr).set_max_iter(epochs)
    )


class TestContentTokens:
    def test_shared_buffers_share_tokens(self):
        X = np.random.RandomState(0).randn(16, 3)
        y = np.arange(16.0)
        t1 = _dense_table(X, y)
        t2 = _dense_table(X, y)  # new Table, SAME column buffers
        tok1, _ = slab_pool.table_token(t1)
        tok2, _ = slab_pool.table_token(t2)
        assert tok1 == tok2

    def test_in_place_mutation_changes_token(self):
        """Tables are immutable by contract, but a zero-copy column shares
        the caller's buffer: normalizing it in place and re-wrapping a
        fresh Table must MISS (content canary), never serve the
        pre-mutation slab."""
        X = np.random.RandomState(0).randn(64, 4).astype(np.float32)
        y = np.arange(64.0)
        tok1, _ = slab_pool.table_token(_dense_table(X, y))
        X -= X.mean(axis=0)  # in-place: same buffer, new content
        tok2, _ = slab_pool.table_token(_dense_table(X, y))
        assert tok1 != tok2

    def test_mutated_buffer_refits_correctly(self):
        X = np.random.RandomState(1).randn(256, 4).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.float64)
        m1 = _logreg().fit(_dense_table(X, y))
        X *= 3.0  # contract violation the canary must absorb
        m2 = _logreg().fit(_dense_table(X, y))
        m2_fresh = _logreg().fit(_dense_table(X.copy(), y))
        np.testing.assert_array_equal(
            m2.coefficients(), m2_fresh.coefficients()
        )
        assert not np.array_equal(m1.coefficients(), m2.coefficients())

    def test_distinct_buffers_distinct_tokens(self):
        X = np.random.RandomState(0).randn(16, 3)
        y = np.arange(16.0)
        tok1, _ = slab_pool.table_token(_dense_table(X, y))
        tok2, _ = slab_pool.table_token(_dense_table(X.copy(), y))
        assert tok1 != tok2

    def test_dead_source_buffer_invalidates_entry(self):
        pool = slab_pool.pool()
        X = np.random.RandomState(0).randn(8, 2)
        refs: list = []
        key = ("t", slab_pool.array_token(X, refs))
        built = []
        pool.get_or_build(key, lambda: built.append(1) or "v", refs=refs)
        assert pool.get_or_build(key, lambda: built.append(2) or "v2",
                                 refs=refs) == "v"
        del X
        gc.collect()
        # the guard died with the buffer: same key must rebuild, never
        # resurrect a slab whose source identity was recycled
        assert pool.get_or_build(key, lambda: built.append(3) or "v3",
                                 refs=[]) == "v3"
        assert built == [1, 3]


class TestPoolMechanics:
    def test_lru_eviction_under_budget(self):
        pool = slab_pool.SlabPool(budget_bytes=100)
        a = pool.get_or_build("a", lambda: np.zeros(10, np.float32))  # 40 B
        pool.get_or_build("b", lambda: np.zeros(10, np.float32))
        pool.get_or_build("a", lambda: np.zeros(10, np.float32))  # refresh a
        pool.get_or_build("c", lambda: np.zeros(10, np.float32))  # evicts b
        assert pool.evictions == 1
        assert pool.get_or_build("a", lambda: "rebuilt") is a  # still hot
        rebuilt = pool.get_or_build("b", lambda: np.ones(10, np.float32))
        assert rebuilt[0] == 1.0  # b was the LRU victim

    def test_pinned_entries_survive_eviction(self):
        pool = slab_pool.SlabPool(budget_bytes=50)
        v = pool.get_or_build("hot", lambda: np.zeros(10, np.float32))
        with pool.pinned(v):
            # both newcomers exceed the budget; the pinned slab must stay
            pool.get_or_build("x", lambda: np.zeros(10, np.float32))
            pool.get_or_build("y", lambda: np.zeros(10, np.float32))
            assert pool.get_or_build("hot", lambda: "rebuilt") is v
        assert pool.hits >= 1

    def test_dead_entries_swept_on_next_put(self):
        pool = slab_pool.SlabPool(budget_bytes=1 << 20)
        X = np.zeros(100, np.float32)
        refs: list = []
        key = ("k1", slab_pool.array_token(X, refs))
        pool.get_or_build(key, lambda: np.zeros(100, np.float32), refs=refs)
        assert pool.bytes == 400
        del X
        gc.collect()
        # a transient-source entry gets a unique key no lookup revisits;
        # the next put's dead sweep must reclaim it anyway
        pool.get_or_build("k2", lambda: np.zeros(10, np.float32))
        assert pool.bytes == 40

    def test_dead_entries_reaped_on_lookup_without_insert(self):
        """A dropped table's slab must not wait for the NEXT INSERT to be
        reclaimed: the weakref death callback queues the key, and any pool
        access (a pure hit included) drains the queue."""
        pool = slab_pool.SlabPool(budget_bytes=1 << 20)
        keeper = pool.get_or_build("keeper", lambda: np.zeros(2, np.float32))
        X = np.zeros(100, np.float32)
        refs: list = []
        key = ("k1", slab_pool.array_token(X, refs))
        pool.get_or_build(key, lambda: np.zeros(100, np.float32), refs=refs)
        assert pool.bytes == 408
        del X
        gc.collect()
        assert pool.get_or_build("keeper", lambda: "rebuilt") is keeper
        assert pool.bytes == 8  # dead slab reclaimed by the hit's drain

    def test_disabled_pool_always_builds(self, monkeypatch):
        monkeypatch.setenv("FMT_SLAB_POOL", "0")
        pool = slab_pool.pool()
        builds = []
        pool.get_or_build("k", lambda: builds.append(1) or 1)
        pool.get_or_build("k", lambda: builds.append(2) or 2)
        assert builds == [1, 2]

    def test_counters_land_in_obs_registry(self):
        obs.enable()
        obs.reset()
        try:
            pool = slab_pool.pool()
            pool.get_or_build("k", lambda: np.zeros(4, np.float32))
            pool.get_or_build("k", lambda: np.zeros(4, np.float32))
            c = obs.registry().snapshot()["counters"]
            assert c["slab_pool.misses"] == 1
            assert c["slab_pool.hits"] == 1
            assert c["slab_pool.bytes_placed"] == 16
        finally:
            obs.disable()
            obs.reset()


class TestChunkedPlacement:
    def test_matches_shard_batch(self):
        mesh = default_mesh()
        n_dev = mesh.shape["data"]
        x = np.arange(n_dev * 24 * 5, dtype=np.float32).reshape(n_dev * 24, 5)
        y = np.arange(n_dev * 24, dtype=np.float64)
        ref = shard_batch(mesh, (x, y, np.float32(3.0)))
        # chunk_bytes tiny + min_bytes 0 forces the double-buffered path
        out = shard_batch_prefetched(
            mesh, (x, y, np.float32(3.0)), chunk_bytes=256, min_bytes=0
        )
        for r, o in zip(ref, out):
            np.testing.assert_array_equal(np.asarray(r), np.asarray(o))
            assert o.sharding == r.sharding

    def test_small_leaves_take_direct_path(self):
        mesh = default_mesh()
        n_dev = mesh.shape["data"]
        x = np.zeros((n_dev * 2, 3), np.float32)
        out = shard_batch_prefetched(mesh, (x,))
        np.testing.assert_array_equal(np.asarray(out[0]), x)


class TestWarmFit:
    def _data(self, n=512, d=6, seed=3):
        rng = np.random.RandomState(seed)
        X = rng.randn(n, d).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.float64)
        return X, y

    def test_second_fit_hits_pool_and_matches(self):
        X, y = self._data()
        t = _dense_table(X, y)
        m1 = _logreg().fit(t)
        pool = slab_pool.pool()
        misses0 = pool.misses
        m2 = _logreg().fit(t)
        assert pool.hits >= 1 and pool.misses == misses0
        np.testing.assert_array_equal(m1.coefficients(), m2.coefficients())
        assert m1.intercept() == m2.intercept()

    def test_content_identity_crosses_table_instances(self):
        X, y = self._data()
        m1 = _logreg().fit(_dense_table(X, y))
        pool = slab_pool.pool()
        misses0 = pool.misses
        m2 = _logreg().fit(_dense_table(X, y))  # fresh Table, same buffers
        assert pool.hits >= 1 and pool.misses == misses0
        np.testing.assert_array_equal(m1.coefficients(), m2.coefficients())

    def test_rewrapped_table_with_extra_column_still_hits(self):
        """Pool tokens scope to the columns the layout reads: adding an
        unrelated column (or selecting a subset) while sharing the
        feature/label buffers must still hit."""
        X, y = self._data()
        t = _dense_table(X, y)
        m1 = _logreg().fit(t)
        pool = slab_pool.pool()
        misses0 = pool.misses
        t2 = t.with_column("weight", "double", np.ones(len(t)))
        m2 = _logreg().fit(t2)
        assert pool.hits >= 1 and pool.misses == misses0
        np.testing.assert_array_equal(m1.coefficients(), m2.coefficients())

    def test_varied_learning_rate_still_hits_slab(self):
        X, y = self._data()
        t = _dense_table(X, y)
        _logreg(lr=0.5).fit(t)
        pool = slab_pool.pool()
        misses0 = pool.misses
        _logreg(lr=0.25).fit(t)  # new program, SAME placed batch
        assert pool.hits >= 1 and pool.misses == misses0

    def test_uncached_path_parity(self, monkeypatch):
        X, y = self._data()
        t = _dense_table(X, y)
        warm1 = _logreg().fit(t)
        warm2 = _logreg().fit(t)  # pool-hit fit
        monkeypatch.setenv("FMT_SLAB_POOL", "0")
        cold = _logreg().fit(_dense_table(X.copy(), y.copy()))
        np.testing.assert_array_equal(
            warm2.coefficients(), cold.coefficients()
        )
        np.testing.assert_array_equal(
            warm1.coefficients(), warm2.coefficients()
        )

    def test_sparse_fit_hits_pool(self):
        from flink_ml_tpu.ops.vector import SparseVector

        rng = np.random.RandomState(7)
        rows = [
            SparseVector(32, np.sort(rng.choice(32, 3, replace=False)),
                         rng.randn(3))
            for _ in range(256)
        ]
        y = rng.randint(0, 2, 256).astype(np.float64)
        schema = Schema.of(
            ("features", DataTypes.SPARSE_VECTOR), ("label", "double")
        )
        t = Table.from_columns(schema, {"features": rows, "label": y})
        m1 = _logreg().set_num_features(32).fit(t)
        pool = slab_pool.pool()
        misses0 = pool.misses
        m2 = _logreg().set_num_features(32).fit(t)
        assert pool.hits >= 1 and pool.misses == misses0
        np.testing.assert_array_equal(m1.coefficients(), m2.coefficients())

    def test_fit_report_carries_pool_delta_and_latency(self, tmp_path,
                                                       monkeypatch):
        monkeypatch.setenv("FMT_OBS_REPORTS", str(tmp_path))
        obs.enable()
        obs.reset()
        try:
            X, y = self._data()
            t = _dense_table(X, y)
            _logreg().fit(t)
            _logreg().fit(t)
            fits = [r for r in obs.load_reports() if r["kind"] == "fit"]
            cold, warm = fits[-2]["extra"], fits[-1]["extra"]
            assert cold["slab_pool_misses"] >= 1
            assert warm["slab_pool_hits"] >= 1
            assert warm["slab_pool_misses"] == 0
            assert warm["slab_pool_hit_rate"] == 1.0
            assert warm["call_latency_ms"] > 0
            assert "call_latency_ms" in fits[-1]["step_summary"]
        finally:
            obs.disable()
            obs.reset()


class TestDonationAliasing:
    """Satellite: lock in the jnp.copy guard (lib/common.py) — donated
    params must never free a caller's pre-placed arrays or a pooled slab."""

    def _stack_and_grads(self):
        import jax.numpy as jnp

        from flink_ml_tpu.lib.classification import _log_loss_grads
        from flink_ml_tpu.lib.common import pack_minibatches
        from flink_ml_tpu.parallel.mesh import data_parallel_size
        from flink_ml_tpu.utils.environment import MLEnvironmentFactory

        mesh = MLEnvironmentFactory.get_default().get_mesh()
        rng = np.random.RandomState(5)
        X = rng.randn(256, 4).astype(np.float32)
        y = (X[:, 1] > 0).astype(np.float32)
        stack = pack_minibatches(X, y, data_parallel_size(mesh))
        w0 = jnp.zeros((4,), jnp.float32)
        b0 = jnp.zeros((), jnp.float32)
        return mesh, stack, _log_loss_grads(True), (w0, b0)

    def test_two_fits_from_same_preplaced_params(self):
        from flink_ml_tpu.lib.common import train_glm
        from flink_ml_tpu.parallel.mesh import replicate

        mesh, stack, grad_fn, params = self._stack_and_grads()
        placed = replicate(mesh, params)
        r1 = train_glm(placed, stack, grad_fn, mesh,
                       learning_rate=0.5, max_iter=4)
        # the donated program must have trained on COPIES: the caller's
        # placed arrays are still alive and still zero
        np.testing.assert_array_equal(np.asarray(placed[0]), np.zeros(4))
        r2 = train_glm(placed, stack, grad_fn, mesh,
                       learning_rate=0.5, max_iter=4)
        np.testing.assert_array_equal(r1.params[0], r2.params[0])
        np.testing.assert_array_equal(
            np.asarray(r1.params[1]), np.asarray(r2.params[1])
        )

    def test_two_fits_from_same_pooled_slab(self):
        """The new hazard class: with the slab pool, fit 2 receives the
        SAME device batch object fit 1 trained on — it must neither crash
        (deleted buffers) nor drift (corrupted buffers)."""
        from flink_ml_tpu.lib.common import train_glm
        from flink_ml_tpu.parallel.mesh import replicate

        mesh, stack, grad_fn, params = self._stack_and_grads()
        placed = replicate(mesh, params)
        r1 = train_glm(placed, stack, grad_fn, mesh,
                       learning_rate=0.5, max_iter=4)
        pool = slab_pool.pool()
        assert pool.misses >= 1
        misses0 = pool.misses
        r2 = train_glm(placed, stack, grad_fn, mesh,
                       learning_rate=0.5, max_iter=4)
        assert pool.hits >= 1 and pool.misses == misses0  # same pooled slab
        np.testing.assert_array_equal(r1.params[0], r2.params[0])


class TestPooledInference:
    def test_repeated_transform_reuses_placed_batch(self):
        rng = np.random.RandomState(9)
        X = rng.randn(200, 5).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.float64)
        model = _logreg().fit(_dense_table(X, y))
        q = Table.from_columns(
            Schema.of(("features", DataTypes.DENSE_VECTOR)),
            {"features": X},
        )
        pool = slab_pool.pool()
        s1 = np.asarray(model.transform(q)[0].col("p"))
        misses0 = pool.misses
        s2 = np.asarray(model.transform(q)[0].col("p"))
        assert pool.misses == misses0 and pool.hits >= 1
        np.testing.assert_array_equal(s1, s2)

    def test_knn_model_reload_reuses_placement(self):
        from flink_ml_tpu.lib.knn import Knn

        rng = np.random.RandomState(4)
        X = rng.randn(64, 3).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.float64)
        schema = Schema.of(
            ("features", DataTypes.DENSE_VECTOR), ("label", "double")
        )
        t = Table.from_columns(schema, {"features": X, "label": y})
        model = Knn().set_vector_col("features").set_label_col("label") \
            .set_k(3).set_prediction_col("p").fit(t)
        q = Table.from_columns(
            Schema.of(("features", DataTypes.DENSE_VECTOR)), {"features": X}
        )
        r1 = np.asarray(model.transform(q)[0].col("p"))
        pool = slab_pool.pool()
        # a FRESH mapper over the same model table must hit the pooled
        # reference-set placement instead of re-transferring the train set
        model._mapper_cache = None
        knn_misses0 = pool.misses
        r2 = np.asarray(model.transform(q)[0].col("p"))
        assert pool.hits >= 1 and pool.misses == knn_misses0
        np.testing.assert_array_equal(r1, r2)


class TestPrefetchAbandonment:
    """Satellite: a producer exception recorded after the consumer
    abandoned the stream must surface (warning) and the thread must be
    joined — never silently dropped with the queue."""

    def test_abandoned_stream_surfaces_producer_error(self):
        import threading

        from flink_ml_tpu.utils.prefetch import prefetch_iter

        def items():
            yield 1
            yield 2
            raise ValueError("producer exploded")

        it = prefetch_iter(items(), depth=1, name="t-prefetch")
        assert next(it) == 1
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            it.close()  # consumer abandons mid-stream
        msgs = [str(w.message) for w in caught
                if issubclass(w.category, RuntimeWarning)]
        assert any("producer exploded" in m for m in msgs), msgs
        assert not any(
            th.name == "t-prefetch" and th.is_alive()
            for th in threading.enumerate()
        )

    def test_consumed_stream_raises_at_consumer(self):
        from flink_ml_tpu.utils.prefetch import prefetch_iter

        def items():
            yield 1
            raise ValueError("boom")

        it = prefetch_iter(items(), depth=1)
        assert next(it) == 1
        with pytest.raises(ValueError, match="boom"), \
                warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            list(it)
        # surfaced by RAISING — no duplicate warning
        assert not [w for w in caught
                    if issubclass(w.category, RuntimeWarning)]

    def test_clean_stream_passes_through(self):
        from flink_ml_tpu.utils.prefetch import prefetch_iter

        assert list(prefetch_iter(iter(range(5)), depth=2)) == list(range(5))
