"""Online serving runtime (flink_ml_tpu/serving/) — dynamic micro-batching,
admission control, demux, hot swap — plus the PR's satellites (breaker
probe concurrency, registry thread-safety, the shared batch-shape ladder).

The serving contract under test: a request served through the
micro-batching server is BIT-IDENTICAL to a solo ``transform`` of the
same rows — coalescing, ladder padding, and demux are invisible to the
caller — while overload degrades by reason-coded shedding instead of
unbounded queueing, and a hot swap or corrupt deploy never fails a
request.
"""

import threading
import time

import numpy as np
import pytest

from flink_ml_tpu import obs, serve
from flink_ml_tpu.api.pipeline import Pipeline
from flink_ml_tpu.lib import LogisticRegression
from flink_ml_tpu.lib.feature import MinMaxScaler, StandardScaler
from flink_ml_tpu.serve import quarantine
from flink_ml_tpu.serving import (
    ModelServer,
    ServerClosedError,
    ServerOverloadedError,
    ServingConfig,
)
from flink_ml_tpu.serving.batcher import ServeRequest, coalesce, demux
from flink_ml_tpu.table.schema import DataTypes, Schema
from flink_ml_tpu.table.table import Table
from flink_ml_tpu.utils import compile_cache

N, D = 256, 5
SCHEMA = Schema.of(("features", DataTypes.DENSE_VECTOR), ("label", "double"))
WAIT = 30  # generous future timeout: a hang fails loudly, not flakily


@pytest.fixture(scope="module")
def dense_table():
    rng = np.random.RandomState(7)
    X = (2.0 * rng.randn(N, D) + 1.0).astype(np.float32)
    w = rng.randn(D).astype(np.float32)
    y = ((X - 1.0) @ w > 0).astype(np.float64)
    return Table.from_columns(SCHEMA, {"features": X, "label": y})


@pytest.fixture(scope="module")
def model(dense_table):
    return Pipeline([
        StandardScaler().set_selected_col("features"),
        MinMaxScaler().set_selected_col("features"),
        LogisticRegression().set_vector_col("features")
        .set_label_col("label").set_prediction_col("pred")
        .set_prediction_detail_col("proba").set_max_iter(3)
        .set_learning_rate(0.5),
    ]).fit(dense_table)


@pytest.fixture
def obs_on():
    obs.enable()
    obs.reset()
    yield
    obs.reset()
    obs.disable()


def _requests(table, sizes, start=0):
    """Consecutive row slices of the given sizes."""
    out, lo = [], start
    for s in sizes:
        out.append(table.slice_rows(lo, lo + s))
        lo += s
    return out


def _assert_rows_equal(a: Table, b: Table, cols=("pred", "label")):
    for col in cols:
        np.testing.assert_array_equal(
            np.asarray(a.col(col), dtype=np.float64),
            np.asarray(b.col(col), dtype=np.float64), err_msg=col,
        )


# -- the shared batch-shape ladder (satellite) --------------------------------


class TestBucketLadder:
    def test_ladder_rungs(self):
        rows = [1, 2, 8, 9, 32, 33, 128, 129, 256, 257, 512, 513, 3000]
        got = [compile_cache.bucket_batch_rows(n) for n in rows]
        assert got == [1, 8, 8, 32, 32, 128, 128, 256, 256, 512, 512,
                       1024, 4096]

    def test_ladder_never_pads_wider_than_the_old_rule(self):
        """No padded-compute regression vs the pre-ladder power-of-two
        rule (min 256): the ladder must never choose a LARGER bucket."""
        from flink_ml_tpu.lib.common import bucket_rows

        for n in range(1, 2049):
            assert compile_cache.bucket_batch_rows(n) <= bucket_rows(n), n

    def test_row_multiple_rounding(self):
        assert compile_cache.bucket_batch_rows(1, row_multiple=8) == 8
        assert compile_cache.bucket_batch_rows(128, row_multiple=8) == 128
        assert compile_cache.bucket_batch_rows(130, row_multiple=8) == 256
        assert compile_cache.bucket_batch_rows(5, row_multiple=3) == 9

    def test_bucket_counter_flat_across_100_mixed_sizes(self, obs_on):
        """100 requests of mixed sizes land on <= len(ladder) fresh shapes
        — the recompile-flatness contract dynamic batching relies on."""
        compile_cache.reset_bucket_stats()
        rng = np.random.RandomState(3)
        for n in rng.randint(1, 513, size=100):
            compile_cache.bucket_batch_rows(int(n))
        c = obs.registry().snapshot()["counters"]
        assert c.get("compile_cache.bucket_new", 0) <= len(
            compile_cache.BATCH_BUCKET_LADDER
        )
        assert (
            c.get("compile_cache.bucket_new", 0)
            + c.get("compile_cache.bucket_reuse", 0)
        ) == 100

    def test_staged_transform_shares_the_ladder(self, obs_on, model,
                                                dense_table, monkeypatch):
        """A staged (unfused) transform pads through the same ladder as
        serving: transforming a 3-row slice must log ladder activity."""
        monkeypatch.setenv("FMT_FUSE_TRANSFORM", "0")
        compile_cache.reset_bucket_stats()
        model.transform(dense_table.slice_rows(0, 3))
        c = obs.registry().snapshot()["counters"]
        assert (
            c.get("compile_cache.bucket_new", 0)
            + c.get("compile_cache.bucket_reuse", 0)
        ) >= 1

    def test_bucket_padding_parity(self, model, dense_table, monkeypatch):
        """Different request sizes hit different buckets; every row's
        prediction is bit-identical to the whole-table transform's."""
        monkeypatch.setenv("FMT_FUSE_TRANSFORM", "1")
        (whole,) = model.transform(dense_table)
        for lo, hi in ((0, 3), (10, 210)):
            (part,) = model.transform(dense_table.slice_rows(lo, hi))
            np.testing.assert_array_equal(
                np.asarray(part.col("pred")),
                np.asarray(whole.col("pred"))[lo:hi],
            )


# -- batcher: coalesce + demux ------------------------------------------------


def _req(table):
    from concurrent.futures import Future

    return ServeRequest(table=table, future=Future(), enqueued_at=0.0)


class TestBatcher:
    def test_coalesce_spans(self, dense_table):
        reqs = [_req(t) for t in _requests(dense_table, [3, 5, 2])]
        batch, spans = coalesce(reqs)
        assert batch.num_rows() == 10
        assert spans == [(0, 3), (3, 8), (8, 10)]

    def test_demux_splits_rows_per_request(self, dense_table):
        reqs = [_req(t) for t in _requests(dense_table, [4, 6])]
        batch, spans = coalesce(reqs)
        results = demux(batch, [], spans, "v1")
        assert [r.num_rows for r in results] == [4, 6]
        _assert_rows_equal(results[1].table, reqs[1].table, cols=("label",))

    def test_demux_quarantine_offsets_become_request_local(self,
                                                           dense_table):
        spans = [(0, 3), (3, 6)]
        batch = dense_table.slice_rows(0, 6)
        # mapper flagged global rows 1 and 4 (request A row 1, B row 1)
        side = batch.take_rows([1, 4]).with_column(
            quarantine.QUARANTINE_REASON_COL, DataTypes.STRING,
            ["nan_inf", "nan_inf"],
        ).with_column(
            quarantine.QUARANTINE_ROW_COL, DataTypes.LONG, [1, 4],
        )
        out = batch.take_rows([0, 2, 3, 5])  # survivors in order
        results = demux(out, [("M", side, 6)], spans, "v1")
        for res in results:
            assert res.num_rows == 2
            q = res.quarantine["M"]
            assert [int(r) for r in q.col(quarantine.QUARANTINE_ROW_COL)] \
                == [1]
        _assert_rows_equal(results[1].table, batch.take_rows([3, 5]),
                           cols=("label",))

    def test_demux_misalignment_raises(self, dense_table):
        reqs = [_req(t) for t in _requests(dense_table, [4])]
        batch, spans = coalesce(reqs)
        short = batch.slice_rows(0, 3)  # one row vanished, no quarantine
        with pytest.raises(RuntimeError, match="misaligned"):
            demux(short, [], spans, "v1")

    def test_demux_remaps_staged_reduced_space_emissions(self,
                                                        dense_table):
        """A staged chain's stage 2 validates a table ALREADY reduced by
        stage 1's quarantine, so its offsets are local to that smaller
        table: stage 1 flags global row 2, stage 2 flags its local row 5
        — which is global row 6.  The space-tracking remap must attribute
        both correctly instead of marking global row 5 dead."""
        spans = [(0, 5), (5, 10)]
        batch = dense_table.slice_rows(0, 10)

        def side_of(src, rows_local, n_emit):
            return src.take_rows(rows_local).with_column(
                quarantine.QUARANTINE_REASON_COL, DataTypes.STRING,
                ["nan_inf"] * len(rows_local),
            ).with_column(
                quarantine.QUARANTINE_ROW_COL, DataTypes.LONG, rows_local,
            ), n_emit

        s1, b1 = side_of(batch, [2], 10)          # stage 1: global coords
        reduced = batch.take_rows([0, 1, 3, 4, 5, 6, 7, 8, 9])
        s2, b2 = side_of(reduced, [5], 9)         # stage 2: reduced coords
        out = batch.take_rows([0, 1, 3, 4, 5, 7, 8, 9])  # minus 2 and 6
        results = demux(out, [("S1", s1, b1), ("S2", s2, b2)], spans, "v1")
        a, b = results
        assert a.num_rows == 4 and b.num_rows == 4
        assert [int(r) for r in
                a.quarantine["S1"].col(quarantine.QUARANTINE_ROW_COL)] == [2]
        # stage 2's flag lands on request B's local row 1 (global 6)
        assert [int(r) for r in
                b.quarantine["S2"].col(quarantine.QUARANTINE_ROW_COL)] == [1]
        _assert_rows_equal(b.table, batch.take_rows([5, 7, 8, 9]),
                           cols=("label",))

    def test_staged_transform_quarantine_demux_end_to_end(self, obs_on,
                                                          monkeypatch):
        """The live staged path (FMT_FUSE_TRANSFORM=0): two validating
        stages on DIFFERENT columns; the second stage's emission happens
        in post-filter coordinates and must still reach the right caller
        with the right request-local offset."""
        from flink_ml_tpu.lib import KMeans

        rng = np.random.RandomState(11)
        f = rng.randn(64, 3).astype(np.float32)
        g = rng.randn(64, 3).astype(np.float32)
        schema = Schema.of(("f", DataTypes.DENSE_VECTOR),
                           ("g", DataTypes.DENSE_VECTOR),
                           ("label", "double"))
        y = (g[:, 0] > 0).astype(np.float64)
        clean = Table.from_columns(schema, {"f": f, "g": g, "label": y})
        chain = Pipeline([
            KMeans().set_vector_col("f").set_k(3)
            .set_prediction_col("cluster").set_max_iter(2),
            LogisticRegression().set_vector_col("g").set_label_col("label")
            .set_prediction_col("pred").set_max_iter(2),
        ]).fit(clean)
        fbad, gbad = f.copy(), g.copy()
        fbad[2, 0] = np.nan   # stage 1 (KMeans on 'f') flags global row 2
        gbad[6, 1] = np.inf   # stage 2 (LR on 'g') flags feed row 6 —
        bad = Table.from_columns(schema, {  # local row 5 after filtering
            "f": fbad, "g": gbad, "label": y})
        monkeypatch.setenv("FMT_FUSE_TRANSFORM", "0")
        quarantine.reset()
        server = ModelServer(chain, max_batch=64, max_wait_ms=20,
                             start=False)
        fa = server.submit(bad.slice_rows(0, 5))   # owns global rows 0-4
        fb = server.submit(bad.slice_rows(5, 10))  # owns global rows 5-9
        server.start()
        ra, rb = fa.result(WAIT), fb.result(WAIT)
        server.shutdown()
        assert ra.num_rows == 4 and rb.num_rows == 4
        (qa,) = ra.quarantine.values()   # KMeans flag: A's local row 2
        assert [int(r) for r in
                qa.col(quarantine.QUARANTINE_ROW_COL)] == [2]
        (qb,) = rb.quarantine.values()   # LR flag: B's local row 1
        assert [int(r) for r in
                qb.col(quarantine.QUARANTINE_ROW_COL)] == [1]
        quarantine.reset()


# -- coalescing / flush timing ------------------------------------------------


class TestCoalesceFlush:
    def test_concurrent_requests_coalesce_into_one_batch(self, model,
                                                         dense_table,
                                                         obs_on):
        server = ModelServer(model, max_batch=64, max_wait_ms=20,
                             start=False)
        futs = [server.submit(r)
                for r in _requests(dense_table, [4, 4, 4, 4])]
        server.start()
        for f in futs:
            f.result(WAIT)
        server.shutdown()
        c = obs.registry().snapshot()["counters"]
        assert c.get("serving.batches") == 1
        assert c.get("serving.coalesced_requests") == 4
        assert c.get("serving.served_rows") == 16

    def test_flush_on_max_batch_rows_not_wait(self, model, dense_table):
        """max_wait is huge; hitting max_batch rows must flush anyway."""
        server = ModelServer(model, max_batch=8, max_wait_ms=60_000)
        t0 = time.perf_counter()
        futs = [server.submit(r) for r in _requests(dense_table, [4, 4])]
        for f in futs:
            f.result(WAIT)
        assert time.perf_counter() - t0 < 20  # nowhere near max_wait
        server.shutdown()

    def test_flush_on_max_wait_partial_batch(self, model, dense_table,
                                             obs_on):
        """One small request must be served after ~max_wait even though
        the batch is nowhere near full."""
        server = ModelServer(model, max_batch=512, max_wait_ms=10)
        res = server.predict(dense_table.slice_rows(0, 2), timeout=WAIT)
        assert res.num_rows == 2
        server.shutdown()
        g = obs.registry().snapshot()["gauges"]
        assert g.get("serving.batch_occupancy", 1.0) < 0.5

    def test_oversized_request_serves_alone(self, model, dense_table):
        server = ModelServer(model, max_batch=8, queue_cap=128)
        res = server.predict(dense_table.slice_rows(0, 32), timeout=WAIT)
        assert res.num_rows == 32
        server.shutdown()

    def test_mixed_schema_requests_never_share_a_batch(self, model,
                                                       dense_table,
                                                       obs_on):
        unlabeled = Table.from_columns(
            Schema.of(("features", DataTypes.DENSE_VECTOR)),
            {"features": dense_table.features_dense("features")[:4]},
        )
        server = ModelServer(model, max_batch=64, max_wait_ms=20,
                             start=False)
        fa = server.submit(dense_table.slice_rows(0, 4))
        fb = server.submit(unlabeled)
        server.start()
        ra, rb = fa.result(WAIT), fb.result(WAIT)
        server.shutdown()
        assert ra.table.schema.contains("label")
        assert not rb.table.schema.contains("label")
        assert obs.registry().snapshot()["counters"]["serving.batches"] == 2


# -- admission control + shedding ---------------------------------------------


class TestAdmission:
    def test_queue_cap_rejection_is_reason_coded(self, model, dense_table,
                                                 obs_on):
        server = ModelServer(model, queue_cap=8, start=False)
        server.submit(dense_table.slice_rows(0, 8))
        with pytest.raises(ServerOverloadedError) as err:
            server.submit(dense_table.slice_rows(8, 10))
        assert err.value.reason == "queue_full"
        server.shutdown()  # drains the admitted request
        c = obs.registry().snapshot()["counters"]
        assert c.get("serving.shed.queue_full") == 1

    def test_full_queue_sheds_oldest_past_deadline_first(self, model,
                                                         dense_table):
        server = ModelServer(model, queue_cap=8, start=False)
        doomed = server.submit(dense_table.slice_rows(0, 4), deadline_ms=1)
        alive = server.submit(dense_table.slice_rows(4, 8))  # no deadline
        time.sleep(0.01)  # doomed's deadline passes in the queue
        admitted = server.submit(dense_table.slice_rows(8, 12))
        with pytest.raises(ServerOverloadedError) as err:
            doomed.result(WAIT)
        assert err.value.reason == "deadline_expired"
        server.start()
        assert alive.result(WAIT).num_rows == 4
        assert admitted.result(WAIT).num_rows == 4
        server.shutdown()

    def test_expired_request_sheds_at_dispatch(self, model, dense_table):
        server = ModelServer(model, start=False)
        doomed = server.submit(dense_table.slice_rows(0, 4), deadline_ms=1)
        served = server.submit(dense_table.slice_rows(4, 8))
        time.sleep(0.01)
        server.start()
        with pytest.raises(ServerOverloadedError) as err:
            doomed.result(WAIT)
        assert err.value.reason == "deadline_expired"
        assert served.result(WAIT).num_rows == 4
        server.shutdown()

    def test_breaker_open_sheds_instead_of_queueing(self, model,
                                                    dense_table, obs_on,
                                                    monkeypatch):
        monkeypatch.setenv("FMT_SERVE_BREAKER_THRESHOLD", "1")
        serve.reset_breakers()
        # one of THIS pipeline's dispatch surfaces (the LR stage's mapper)
        serve.breaker("LogisticRegressionModel").record_failure()
        server = ModelServer(model, start=False)
        with pytest.raises(ServerOverloadedError) as err:
            server.submit(dense_table.slice_rows(0, 4))
        assert err.value.reason == "breaker_open"
        assert "LogisticRegressionModel" in str(err.value)
        server.shutdown()
        serve.reset_breakers()
        c = obs.registry().snapshot()["counters"]
        assert c.get("serving.shed.breaker_open") == 1

    def test_unrelated_open_breaker_does_not_shed(self, model, dense_table,
                                                  monkeypatch):
        """Another pipeline's dead device must not reject THIS server's
        traffic: only breakers on the served model's own dispatch
        surfaces (stage mappers / its fused plans) shed at admission."""
        monkeypatch.setenv("FMT_SERVE_BREAKER_THRESHOLD", "1")
        serve.reset_breakers()
        serve.breaker("SomeOtherModel").record_failure()
        serve.breaker("FusedPlan[SomeOtherModel+KnnModel]").record_failure()
        server = ModelServer(model, max_wait_ms=5)
        assert server.predict(dense_table.slice_rows(0, 4),
                              timeout=WAIT).num_rows == 4
        server.shutdown()
        serve.reset_breakers()

    def test_own_fused_plan_breaker_sheds(self, model, dense_table,
                                          monkeypatch):
        monkeypatch.setenv("FMT_SERVE_BREAKER_THRESHOLD", "1")
        serve.reset_breakers()
        serve.breaker(
            "FusedPlan[StandardScalerModel+MinMaxScalerModel"
            "+LogisticRegressionModel]"
        ).record_failure()
        server = ModelServer(model, start=False)
        with pytest.raises(ServerOverloadedError) as err:
            server.submit(dense_table.slice_rows(0, 4))
        assert err.value.reason == "breaker_open"
        server.shutdown()
        serve.reset_breakers()

    def test_shed_on_breaker_off_keeps_serving(self, model, dense_table,
                                               monkeypatch):
        monkeypatch.setenv("FMT_SERVE_BREAKER_THRESHOLD", "1")
        serve.reset_breakers()
        serve.breaker("LogisticRegressionModel").record_failure()
        server = ModelServer(model, shed_on_breaker=False, max_wait_ms=5)
        assert server.predict(dense_table.slice_rows(0, 4),
                              timeout=WAIT).num_rows == 4
        server.shutdown()
        serve.reset_breakers()

    def test_empty_request_rejected(self, model, dense_table):
        server = ModelServer(model, start=False)
        with pytest.raises(ValueError, match="empty request"):
            server.submit(dense_table.slice_rows(0, 0))
        server.shutdown()

    def test_config_env_knobs_with_overrides(self, monkeypatch):
        monkeypatch.setenv("FMT_SERVING_MAX_BATCH", "64")
        monkeypatch.setenv("FMT_SERVING_MAX_WAIT_MS", "7.5")
        monkeypatch.setenv("FMT_SERVING_QUEUE_CAP", "100")
        monkeypatch.setenv("FMT_SERVING_DEADLINE_MS", "250")
        cfg = ServingConfig.from_env()
        assert (cfg.max_batch, cfg.max_wait_ms, cfg.queue_cap,
                cfg.deadline_ms) == (64, 7.5, 100, 250.0)
        cfg = ServingConfig.from_env(max_batch=8, deadline_ms=0)
        assert cfg.max_batch == 8 and cfg.deadline_ms == 0.0
        assert cfg.deadline_at(10.0, None) is None
        assert cfg.deadline_at(10.0, 500) == pytest.approx(10.5)


# -- server lifecycle ---------------------------------------------------------


class TestServerLifecycle:
    def test_predict_parity_vs_solo_transform(self, model, dense_table):
        server = ModelServer(model, max_wait_ms=5)
        req = dense_table.slice_rows(32, 40)
        res = server.predict(req, timeout=WAIT)
        server.shutdown()
        (solo,) = model.transform(req)
        assert res.table.schema == solo.schema
        _assert_rows_equal(res.table, solo, cols=("pred", "label"))
        np.testing.assert_allclose(
            np.asarray(res.table.col("proba")),
            np.asarray(solo.col("proba")), rtol=1e-6,
        )

    def test_coalesced_callers_each_get_their_own_rows(self, model,
                                                       dense_table):
        server = ModelServer(model, max_batch=64, max_wait_ms=20,
                             start=False)
        reqs = _requests(dense_table, [3, 5, 7])
        futs = [server.submit(r) for r in reqs]
        server.start()
        results = [f.result(WAIT) for f in futs]
        server.shutdown()
        for req, res in zip(reqs, results):
            assert res.num_rows == req.num_rows()
            _assert_rows_equal(res.table, req, cols=("label",))

    def test_shutdown_drains_inflight_futures(self, model, dense_table):
        server = ModelServer(model, max_batch=512, max_wait_ms=60_000,
                             start=False)
        futs = [server.submit(r) for r in _requests(dense_table, [4, 4])]
        server.start()
        # dispatcher is parked on the 60s flush window; shutdown must
        # flush-and-serve, not abandon the futures
        server.shutdown(drain=True)
        assert all(f.result(WAIT).num_rows == 4 for f in futs)

    def test_shutdown_without_drain_sheds_queue(self, model, dense_table,
                                                obs_on):
        server = ModelServer(model, start=False)
        fut = server.submit(dense_table.slice_rows(0, 4))
        server.shutdown(drain=False)
        with pytest.raises(ServerOverloadedError) as err:
            fut.result(WAIT)
        assert err.value.reason == "shutdown"
        c = obs.registry().snapshot()["counters"]
        assert c.get("serving.shed.shutdown") == 1

    def test_submit_after_shutdown_raises_closed(self, model, dense_table):
        server = ModelServer(model)
        server.shutdown()
        with pytest.raises(ServerClosedError):
            server.submit(dense_table.slice_rows(0, 4))

    def test_context_manager(self, model, dense_table):
        with ModelServer(model, max_wait_ms=5, start=False) as server:
            assert server.predict(dense_table.slice_rows(0, 4),
                                  timeout=WAIT).num_rows == 4
        assert not server.running

    def test_transform_exception_propagates_to_futures(self, dense_table,
                                                       obs_on):
        class Boom:
            def transform(self, *_tables):
                raise RuntimeError("kaput")

        server = ModelServer(Boom(), max_batch=64, max_wait_ms=20,
                             start=False)
        futs = [server.submit(r) for r in _requests(dense_table, [4, 4])]
        server.start()
        for f in futs:
            with pytest.raises(RuntimeError, match="kaput"):
                f.result(WAIT)
        server.shutdown()
        c = obs.registry().snapshot()["counters"]
        assert c.get("serving.failed_requests") == 2
        assert c.get("serving.failed_batches") == 1

    def test_cancelled_future_never_kills_the_dispatcher(self, model,
                                                         dense_table):
        """A caller cancelling its queued future (e.g. cleanup after a
        timeout) must drop that request, not crash the dispatcher with
        InvalidStateError and orphan everyone behind it."""
        server = ModelServer(model, max_batch=64, max_wait_ms=20,
                             start=False)
        doomed = server.submit(dense_table.slice_rows(0, 4))
        alive = server.submit(dense_table.slice_rows(4, 8))
        assert doomed.cancel()
        server.start()
        assert alive.result(WAIT).num_rows == 4
        # the dispatcher survived: a fresh request still serves
        assert server.predict(dense_table.slice_rows(8, 12),
                              timeout=WAIT).num_rows == 4
        server.shutdown()

    def test_request_larger_than_env_batch_rejected(self, model,
                                                    dense_table,
                                                    monkeypatch):
        """Past the environment batch size the fused path moves work onto
        its prefetch thread, which the demux capture cannot see — such a
        request is refused at submit with a pointer to transform."""
        from flink_ml_tpu.utils.environment import MLEnvironmentFactory

        env = MLEnvironmentFactory.get_default()
        monkeypatch.setattr(env, "default_batch_size", 64)
        server = ModelServer(model, max_batch=32, start=False)
        with pytest.raises(ValueError, match="transform directly"):
            server.submit(dense_table.slice_rows(0, 100))
        server.shutdown()

    def test_max_batch_clamps_to_env_batch_size(self, model, monkeypatch):
        from flink_ml_tpu.utils.environment import MLEnvironmentFactory

        env = MLEnvironmentFactory.get_default()
        monkeypatch.setattr(env, "default_batch_size", 64)
        with pytest.warns(UserWarning, match="clamping"):
            server = ModelServer(model, max_batch=1024, start=False)
        assert server.config.max_batch == 64
        server.shutdown()

    def test_latency_histogram_and_gauges_recorded(self, model,
                                                   dense_table, obs_on):
        server = ModelServer(model, max_wait_ms=5)
        server.predict(dense_table.slice_rows(0, 4), timeout=WAIT)
        server.shutdown()
        t = obs.registry().timing("serving.request_latency_ms")
        assert t and t["count"] == 1 and t["p99_s"] >= t["p50_s"] > 0
        g = obs.registry().snapshot()["gauges"]
        assert "serving.queue_depth" in g
        stats = server.stats()
        assert stats["serving.requests"] == 1
        assert stats["latency_p99_ms"] > 0


# -- quarantine demux through the live server (satellite red test) ------------


class TestServingQuarantine:
    def test_concurrent_bad_row_request_gets_local_offset(self, model,
                                                          dense_table):
        """Two coalesced 3-row requests; B's row 1 is NaN.  B must see
        ``nan_inf@1`` (request-local), A must see clean rows, and both
        must serve bit-identically to solo serving."""
        a_req = dense_table.slice_rows(0, 3)
        Xb = np.asarray(
            dense_table.features_dense("features")[3:6]
        ).copy()
        Xb[1, 0] = np.nan
        b_req = Table.from_columns(SCHEMA, {
            "features": Xb,
            "label": dense_table.col("label")[3:6],
        })
        quarantine.reset()
        server = ModelServer(model, max_batch=64, max_wait_ms=20,
                             start=False)
        fa, fb = server.submit(a_req), server.submit(b_req)
        server.start()
        ra, rb = fa.result(WAIT), fb.result(WAIT)
        server.shutdown()
        assert ra.num_rows == 3 and ra.num_quarantined == 0
        assert rb.num_rows == 2 and rb.num_quarantined == 1
        (q,) = rb.quarantine.values()
        assert list(q.col(quarantine.QUARANTINE_REASON_COL)) == ["nan_inf"]
        assert [int(r) for r in q.col(quarantine.QUARANTINE_ROW_COL)] == [1]
        # bit-identical to solo serving of the same requests
        quarantine.reset()
        (solo_a,) = model.transform(a_req)
        (solo_b,) = model.transform(b_req)
        quarantine.reset()
        _assert_rows_equal(ra.table, solo_a)
        _assert_rows_equal(rb.table, solo_b)

    def test_server_traffic_stays_out_of_global_side_tables(self, model,
                                                            dense_table):
        """Captured (served-back) quarantine rows must not ALSO pile up in
        the process-wide store — callers own their bad rows."""
        X = np.asarray(dense_table.features_dense("features")[:4]).copy()
        X[2, 1] = np.inf
        bad = Table.from_columns(SCHEMA, {
            "features": X, "label": dense_table.col("label")[:4]})
        quarantine.reset()
        server = ModelServer(model, max_wait_ms=5)
        res = server.predict(bad, timeout=WAIT)
        server.shutdown()
        assert res.num_quarantined == 1
        assert quarantine.quarantined_counts() == {}
        quarantine.reset()


# -- hot swap -----------------------------------------------------------------


class TestHotSwap:
    def _fit(self, table, max_iter):
        return Pipeline([
            StandardScaler().set_selected_col("features"),
            LogisticRegression().set_vector_col("features")
            .set_label_col("label").set_prediction_col("pred")
            .set_max_iter(max_iter).set_learning_rate(0.5),
        ]).fit(table)

    def test_deploy_swaps_versions_between_batches(self, dense_table,
                                                   obs_on):
        m1, m2 = self._fit(dense_table, 2), self._fit(dense_table, 3)
        server = ModelServer(m1, version="v1", max_wait_ms=5)
        assert server.predict(dense_table.slice_rows(0, 4),
                              timeout=WAIT).version == "v1"
        server.deploy(m2, "v2")
        assert server.active_version == "v2"
        assert server.predict(dense_table.slice_rows(0, 4),
                              timeout=WAIT).version == "v2"
        server.shutdown()
        assert server.versions == ["v1", "v2"]
        c = obs.registry().snapshot()["counters"]
        assert c.get("serving.swaps") == 1

    def test_deploy_prewarms_before_swap(self, dense_table):
        m1, m2 = self._fit(dense_table, 2), self._fit(dense_table, 3)
        warm_calls = []
        orig = m2.transform
        m2.transform = lambda *t: warm_calls.append(1) or orig(*t)
        server = ModelServer(m1, version="v1", max_wait_ms=5)
        server.predict(dense_table.slice_rows(0, 8), timeout=WAIT)
        server.deploy(m2, "v2")  # warmup defaults to live-traffic sample
        assert warm_calls, "deploy must pre-warm the new version"
        server.shutdown()

    def test_corrupt_deploy_leaves_old_version_serving(self, dense_table,
                                                       tmp_path, obs_on):
        from flink_ml_tpu.serve import ModelIntegrityError

        m1, m2 = self._fit(dense_table, 2), self._fit(dense_table, 3)
        bad_dir = str(tmp_path / "v2")
        m2.save(bad_dir)
        mdf = tmp_path / "v2" / "stage_001" / "model_data.jsonl"
        blob = bytearray(mdf.read_bytes())
        blob[len(blob) // 2] ^= 0xFF
        mdf.write_bytes(bytes(blob))
        server = ModelServer(m1, version="v1", max_wait_ms=5,
                             warmup=dense_table.slice_rows(0, 4))
        with pytest.raises(ModelIntegrityError):
            server.deploy(bad_dir, "v2")
        assert server.active_version == "v1"
        assert server.predict(dense_table.slice_rows(0, 4),
                              timeout=WAIT).version == "v1"
        server.shutdown()
        c = obs.registry().snapshot()["counters"]
        assert c.get("serving.deploy_failures") == 1
        assert "serving.swaps" not in c

    def test_deploy_from_path_verifies_and_serves(self, dense_table,
                                                  tmp_path):
        m2 = self._fit(dense_table, 3)
        path = str(tmp_path / "m2")
        m2.save(path)
        server = ModelServer(path=path, version="v1", max_wait_ms=5,
                             warmup=dense_table.slice_rows(0, 4))
        res = server.predict(dense_table.slice_rows(0, 8), timeout=WAIT)
        server.shutdown()
        (solo,) = m2.transform(dense_table.slice_rows(0, 8))
        _assert_rows_equal(res.table, solo)

    def test_queued_requests_serve_on_the_version_at_batch_start(
        self, dense_table
    ):
        m1, m2 = self._fit(dense_table, 2), self._fit(dense_table, 3)
        server = ModelServer(m1, version="v1", start=False)
        fut = server.submit(dense_table.slice_rows(0, 4))
        server.deploy(m2, "v2", warmup=dense_table.slice_rows(0, 4))
        server.start()
        # the batch had not started when the swap landed: it serves on v2
        assert fut.result(WAIT).version == "v2"
        server.shutdown()


# -- satellite: breaker + registry thread-safety ------------------------------


class TestBreakerProbeConcurrency:
    def test_single_half_open_probe_under_concurrency(self, monkeypatch):
        """RED on the pre-PR breaker: every thread arriving after the
        cooldown flipped to half-open AND rode through as its own probe —
        a probe stampede against a device the breaker had declared dead.
        Exactly ONE caller may own the half-open probe."""
        monkeypatch.setenv("FMT_SERVE_BREAKER_THRESHOLD", "1")
        monkeypatch.setenv("FMT_SERVE_BREAKER_COOLDOWN_S", "30")
        b = serve.CircuitBreaker("probe-race")
        b.record_failure()
        assert b.state == 1.0
        b._opened_at -= 60.0  # cooldown long since elapsed
        n_threads = 8
        barrier = threading.Barrier(n_threads)
        allowed = []

        def prober():
            barrier.wait()
            if b.allow_device():
                allowed.append(threading.get_ident())

        threads = [threading.Thread(target=prober) for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(allowed) == 1, (
            f"{len(allowed)} concurrent half-open probes rode through"
        )
        assert b.state == 0.5

    def test_probe_resolution_reopens_or_closes_for_next_caller(
        self, monkeypatch
    ):
        monkeypatch.setenv("FMT_SERVE_BREAKER_THRESHOLD", "1")
        monkeypatch.setenv("FMT_SERVE_BREAKER_COOLDOWN_S", "30")
        b = serve.CircuitBreaker("probe-seq")
        b.record_failure()
        b._opened_at -= 60.0
        assert b.allow_device()       # the probe
        assert not b.allow_device()   # a second caller: fallback
        b.record_success()            # probe succeeded
        assert b.state == 0.0 and b.allow_device()

    def test_wedged_probe_hands_over_after_cooldown(self, monkeypatch):
        monkeypatch.setenv("FMT_SERVE_BREAKER_THRESHOLD", "1")
        monkeypatch.setenv("FMT_SERVE_BREAKER_COOLDOWN_S", "30")
        b = serve.CircuitBreaker("probe-wedge")
        b.record_failure()
        b._opened_at -= 60.0
        assert b.allow_device()      # probe taken... and its owner dies
        b._probe_started -= 60.0     # a full cooldown passes
        assert b.allow_device()      # the probe hands over, not wedged


class TestRegistryThreadSafety:
    def test_concurrent_counter_and_timing_updates_are_exact(self):
        obs.enable()
        obs.reset()
        n_threads, per_thread = 8, 500
        barrier = threading.Barrier(n_threads)

        def hammer():
            barrier.wait()
            for _ in range(per_thread):
                obs.counter_add("conc.c")
                obs.observe("conc.t", 0.001)

        threads = [threading.Thread(target=hammer) for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        snap = obs.registry().snapshot()
        total = n_threads * per_thread
        assert snap["counters"]["conc.c"] == total
        assert snap["timings"]["conc.t"]["count"] == total
        assert snap["timings"]["conc.t"]["total_s"] == pytest.approx(
            total * 0.001
        )
        obs.reset()
        obs.disable()

    def test_timing_quantiles_over_samples(self):
        obs.enable()
        obs.reset()
        for v in range(1, 101):
            obs.observe("q.t", float(v))
        t = obs.registry().timing("q.t")
        assert t["p50_s"] == pytest.approx(50.0, abs=1.0)
        assert t["p99_s"] == pytest.approx(99.0, abs=1.0)
        assert t["min_s"] == 1.0 and t["max_s"] == 100.0
        obs.reset()
        obs.disable()
