"""End-to-end request tracing + flight recorder (ISSUE 8).

The contracts under test:

* **off-by-default** — with ``FMT_TRACE`` off every hook is one
  module-bool check (``span()`` returns the SHARED nullcontext object)
  and nothing is recorded;
* **explicit handoff** — spans attach to the context their thread was
  explicitly handed (dispatcher coalesced batches, ``prefetch_iter``
  producer threads), NEVER to a racing sibling's trace;
* **the request waterfall** — one served request yields one trace whose
  ``submit -> queue_wait -> coalesce -> transform -> fused_dispatch ->
  device_sync -> demux`` spans nest correctly and account within the
  request's measured wall time;
* **black box** — the flight recorder's bounded ring records sheds and
  breaker transitions at near-zero cost, dumps a redacted JSONL file on
  breaker-open, and sheds/quarantines carry the request's ``trace_id``.
"""

import json
import threading
import time

import numpy as np
import pytest

from flink_ml_tpu import obs, serve
from flink_ml_tpu.api.pipeline import Pipeline
from flink_ml_tpu.lib import LogisticRegression
from flink_ml_tpu.lib.feature import StandardScaler
from flink_ml_tpu.obs import flight, trace
from flink_ml_tpu.serve import quarantine
from flink_ml_tpu.serving import ModelServer, ServerOverloadedError
from flink_ml_tpu.table.schema import DataTypes, Schema
from flink_ml_tpu.table.table import Table
from flink_ml_tpu.utils.prefetch import prefetch_iter

N, D = 192, 5
SCHEMA = Schema.of(("features", DataTypes.DENSE_VECTOR), ("label", "double"))
WAIT = 60  # generous future timeout: a hang fails loudly, not flakily


@pytest.fixture(scope="module")
def dense_table():
    rng = np.random.RandomState(11)
    X = (2.0 * rng.randn(N, D) + 1.0).astype(np.float32)
    w = rng.randn(D).astype(np.float32)
    y = ((X - 1.0) @ w > 0).astype(np.float64)
    return Table.from_columns(SCHEMA, {"features": X, "label": y})


@pytest.fixture(scope="module")
def model(dense_table):
    return Pipeline([
        StandardScaler().set_selected_col("features"),
        LogisticRegression().set_vector_col("features")
        .set_label_col("label").set_prediction_col("pred")
        .set_learning_rate(0.5).set_max_iter(3),
    ]).fit(dense_table)


@pytest.fixture
def traced(tmp_path, monkeypatch):
    """Tracing on at sample=1.0, spans to a per-test sink; clean exit."""
    monkeypatch.setenv("FMT_TRACE_DIR", str(tmp_path))
    trace.reset()
    trace.enable(True, sample=1.0)
    yield tmp_path
    trace.enable(False, sample=1.0)
    trace.reset()


@pytest.fixture
def flight_dir(tmp_path, monkeypatch):
    monkeypatch.setenv("FMT_FLIGHT_DIR", str(tmp_path / "flight"))
    monkeypatch.setenv("FMT_FLIGHT_MIN_S", "0")
    flight.reset()
    yield tmp_path / "flight"
    flight.reset()


def _spans_by_name(spans, trace_id):
    return {s["name"]: s for s in spans if s["trace_id"] == trace_id}


# -- core ---------------------------------------------------------------------


class TestTraceCore:
    def test_off_by_default_is_one_shared_nullcontext(self):
        """The disabled hot-path contract, structurally: the SAME shared
        nullcontext object comes back (no allocation, one bool check)."""
        assert not trace.enabled()
        a = trace.span("anything")
        b = trace.span("else", {"k": 1})
        assert a is b
        assert trace.root_span("fit") is a
        assert trace.start_request("r") is None
        assert trace.current() == ()
        trace.record_span((), "x", 0.1)  # no parents: records nothing
        assert trace.recent_spans() == []

    def test_enabled_but_no_active_trace_records_nothing(self, traced):
        with trace.span("orphan"):
            pass
        assert trace.recent_spans() == []

    def test_root_and_child_nesting_attrs_and_sink(self, traced):
        with trace.root_span("fit", {"est": "LR"}):
            with trace.span("pack", {"rows": 8}):
                trace.attr("bucket", 32)
        spans = trace.load_spans()
        assert [s["name"] for s in spans] == ["pack", "fit"]
        child, root = spans
        assert child["trace_id"] == root["trace_id"]
        assert child["parent_id"] == root["span_id"]
        assert root["parent_id"] == ""
        assert child["attrs"] == {"rows": 8, "bucket": 32}
        assert root["status"] == "ok" and root["dur_s"] >= child["dur_s"]

    def test_root_span_degrades_to_child_inside_active_trace(self, traced):
        with trace.root_span("outer"):
            with trace.root_span("inner"):
                pass
        spans = trace.load_spans()
        assert len({s["trace_id"] for s in spans}) == 1
        inner = next(s for s in spans if s["name"] == "inner")
        outer = next(s for s in spans if s["name"] == "outer")
        assert inner["parent_id"] == outer["span_id"]

    def test_error_status_and_reraise(self, traced):
        with pytest.raises(ValueError):
            with trace.root_span("fit"):
                raise ValueError("boom")
        (root,) = trace.load_spans()
        assert root["status"] == "error"
        assert root["attrs"]["error"] == "ValueError"

    def test_head_sampling_zero_mints_nothing(self, traced):
        trace.enable(True, sample=0.0)
        assert trace.start_request("r") is None
        assert trace.root_span("fit") is trace.span("x")  # shared null
        assert trace.recent_spans() == []

    def test_fanout_records_one_span_per_parent_trace(self, traced):
        a = trace.start_request("req_a")
        b = trace.start_request("req_b")
        with trace.use((a.ctx, b.ctx)):
            with trace.span("coalesce"):
                pass
        a.end()
        b.end()
        spans = [s for s in trace.recent_spans() if s["name"] == "coalesce"]
        assert {s["trace_id"] for s in spans} == {a.trace_id, b.trace_id}
        # same span identity and timestamps, one per parent trace
        assert len({s["span_id"] for s in spans}) == 1
        assert len({s["ts"] for s in spans}) == 1
        for s in spans:
            parent = a if s["trace_id"] == a.trace_id else b
            assert s["parent_id"] == parent.ctx.span_id

    def test_record_span_explicit_duration(self, traced):
        rt = trace.start_request("req")
        trace.record_span((rt.ctx,), "queue_wait", 0.25, {"n": 1})
        rt.end()
        qw = next(s for s in trace.recent_spans()
                  if s["name"] == "queue_wait")
        assert qw["dur_s"] == pytest.approx(0.25)
        assert qw["parent_id"] == rt.ctx.span_id

    def test_request_trace_end_is_single_shot(self, traced):
        rt = trace.start_request("req")
        rt.end("ok")
        rt.end("error")  # benign double-end: first outcome wins
        roots = [s for s in trace.recent_spans() if s["name"] == "req"]
        assert len(roots) == 1 and roots[0]["status"] == "ok"

    def test_waterfall_renders_nesting_and_orphans(self, traced):
        with trace.root_span("fit"):
            with trace.span("pack"):
                pass
        spans = trace.load_spans()
        tid = spans[0]["trace_id"]
        out = trace.render_waterfall(spans, tid)
        assert "fit" in out and "pack" in out and "ms" in out
        fit_line = next(line for line in out.splitlines()
                        if " fit " in f" {line} ")
        pack_line = next(line for line in out.splitlines() if "pack" in line)
        # children indent under parents
        assert pack_line.index("pack") > fit_line.index("fit")
        assert "no spans" in trace.render_waterfall(spans, "absent")


# -- cross-thread propagation (the satellite) ---------------------------------


class TestCrossThreadPropagation:
    def test_prefetch_producer_attaches_to_consumer_trace(self, traced):
        """The producer thread's spans must land in the CONSUMER's trace
        — even with two racing consumers prefetching concurrently, each
        producer inherits exactly its own consumer's context."""
        barrier = threading.Barrier(2)
        results = {}

        def consumer(name):
            def gen():
                for i in range(4):
                    with trace.span("produce", {"who": name, "i": i}):
                        pass
                    yield i
            with trace.root_span(f"consume_{name}"):
                barrier.wait(timeout=10)
                list(prefetch_iter(gen(), depth=1, name=f"pf-{name}"))
                results[name] = trace.current_trace_ids()[0]

        threads = [threading.Thread(target=consumer, args=(n,))
                   for n in ("a", "b")]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert set(results) == {"a", "b"}
        assert results["a"] != results["b"]
        produced = [s for s in trace.recent_spans()
                    if s["name"] == "produce"]
        assert len(produced) == 8
        for s in produced:
            # the span's trace is its OWN consumer's, never the sibling's
            assert s["trace_id"] == results[s["attrs"]["who"]], s

    def test_untraced_consumer_prefetch_records_nothing(self, traced):
        def gen():
            for i in range(3):
                with trace.span("produce"):
                    pass
                yield i

        assert list(prefetch_iter(gen(), depth=1)) == [0, 1, 2]
        assert trace.recent_spans() == []

    def test_coalesced_batch_spans_fan_out_per_request(self, traced, model,
                                                       dense_table):
        """Two requests coalesced into ONE dispatcher batch: the batch-
        scope spans appear in BOTH traces; per-request spans stay in
        their own."""
        server = ModelServer(model, max_batch=64, max_wait_ms=50,
                             start=False)
        fa = server.submit(dense_table.slice_rows(0, 3))
        fb = server.submit(dense_table.slice_rows(3, 8))
        server.start()
        ra, rb = fa.result(WAIT), fb.result(WAIT)
        server.shutdown()
        assert ra.num_rows == 3 and rb.num_rows == 5
        spans = trace.load_spans()
        roots = [s for s in spans if s["name"] == "serving.request"]
        assert len(roots) == 2
        (ta, tb) = [r["trace_id"] for r in roots]
        by_a, by_b = _spans_by_name(spans, ta), _spans_by_name(spans, tb)
        for name in ("submit", "queue_wait", "coalesce", "transform",
                     "demux"):
            assert name in by_a and name in by_b, name
        # ONE coalesced dispatch: the shared batch spans are the same
        # span identity recorded into each trace
        assert by_a["coalesce"]["span_id"] == by_b["coalesce"]["span_id"]
        assert by_a["coalesce"]["attrs"]["requests"] == 2
        # per-request spans never cross: each submit carries its own rows
        assert {by_a["submit"]["attrs"]["rows"],
                by_b["submit"]["attrs"]["rows"]} == {3, 5}
        assert by_a["submit"]["span_id"] != by_b["submit"]["span_id"]


# -- the served-request waterfall (acceptance) --------------------------------


class TestServingTrace:
    def test_single_request_waterfall_nests_within_wall(self, traced,
                                                        model, dense_table):
        with ModelServer(model, max_wait_ms=1,
                         warmup=dense_table.slice_rows(0, 4)) as server:
            trace.reset()  # drop the warmup transform's trace
            t0 = time.perf_counter()
            res = server.predict(dense_table.slice_rows(0, 8),
                                 timeout=WAIT)
            wall_s = time.perf_counter() - t0
        assert res.num_rows == 8
        spans = trace.load_spans()
        (root,) = [s for s in spans if s["name"] == "serving.request"]
        mine = _spans_by_name(spans, root["trace_id"])
        for name in ("submit", "queue_wait", "coalesce", "transform",
                     "fused_dispatch", "device_sync", "demux"):
            assert name in mine, (name, sorted(mine))
        for child in ("submit", "queue_wait", "coalesce", "transform",
                      "demux"):
            assert mine[child]["parent_id"] == root["span_id"], child
        assert mine["device_sync"]["parent_id"] == \
            mine["fused_dispatch"]["span_id"]
        # fused_dispatch sits under serve.dispatch inside the transform
        by_id = {s["span_id"]: s
                 for s in spans if s["trace_id"] == root["trace_id"]}
        hops, cur = [], mine["fused_dispatch"]
        while cur["parent_id"]:
            cur = by_id[cur["parent_id"]]
            hops.append(cur["name"])
        assert hops[0] == "serve.dispatch" and "transform" in hops, hops
        # the accounted hops sum within the measured request wall
        accounted = mine["queue_wait"]["dur_s"] + mine["transform"]["dur_s"]
        assert accounted <= wall_s * 1.05
        assert root["dur_s"] <= wall_s * 1.05
        assert root["attrs"]["version"] == "v1"
        assert mine["serve.dispatch"]["attrs"]["retries"] == 0

    def test_shed_carries_trace_id_everywhere(self, traced, flight_dir,
                                              model, dense_table):
        server = ModelServer(model, queue_cap=8, max_wait_ms=1,
                             start=False)
        server.submit(dense_table.slice_rows(0, 8))  # fills the cap
        with pytest.raises(ServerOverloadedError) as ei:
            server.submit(dense_table.slice_rows(8, 16))
        assert ei.value.reason == "queue_full"
        assert ei.value.trace_id  # the error names its trace
        root = next(s for s in trace.recent_spans()
                    if s["name"] == "serving.request"
                    and s["trace_id"] == ei.value.trace_id)
        assert root["status"] == "shed"
        assert root["attrs"]["shed_reason"] == "queue_full"
        shed_events = [e for e in flight.events()
                       if e["kind"] == "serving.shed"]
        assert shed_events and \
            shed_events[-1]["trace_id"] == ei.value.trace_id
        server.shutdown()

    def test_quarantined_rows_stamp_the_request_trace(self, traced, model,
                                                      dense_table):
        rows = np.asarray(dense_table.col("features")[:4],
                          dtype=np.float32).copy()
        rows[2, 0] = np.nan
        bad = Table.from_columns(SCHEMA, {
            "features": rows,
            "label": np.zeros(4, dtype=np.float64),
        })
        with ModelServer(model, max_wait_ms=1) as server:
            trace.reset()
            res = server.predict(bad, timeout=WAIT)
        assert res.num_rows == 3 and res.num_quarantined == 1
        (root,) = [s for s in trace.load_spans()
                   if s["name"] == "serving.request"]
        assert root["attrs"]["quarantined"] == 1
        assert root["attrs"]["quarantine_reasons"] == "nan_inf"
        (side,) = res.quarantine.values()
        assert list(side.col(quarantine.QUARANTINE_TRACE_COL)) == [
            root["trace_id"]
        ]

    def test_cancelled_while_queued_still_ends_its_trace(self, traced,
                                                         model,
                                                         dense_table):
        """Cancellation is a terminal outcome: a sampled request whose
        caller cancels it while queued must still land its root span
        (status ``cancelled``), not leak an unterminated trace."""
        server = ModelServer(model, max_wait_ms=1, start=False)
        fut = server.submit(dense_table.slice_rows(0, 4))
        assert fut.cancel()
        server.start()
        server.shutdown()
        trace.flush()
        roots = [s for s in trace.load_spans()
                 if s["name"] == "serving.request"]
        assert len(roots) == 1
        assert roots[0]["status"] == "cancelled"

    def test_untraced_serving_is_unaffected(self, model, dense_table):
        assert not trace.enabled()
        with ModelServer(model, max_wait_ms=1) as server:
            res = server.predict(dense_table.slice_rows(0, 4),
                                 timeout=WAIT)
        assert res.num_rows == 4
        assert trace.recent_spans() == []


# -- guarded-fit traces -------------------------------------------------------


class TestFitTrace:
    def test_guarded_fit_roots_a_trace_with_train_spans(self, traced,
                                                        dense_table):
        (LogisticRegression().set_vector_col("features")
         .set_label_col("label").set_prediction_col("pred")
         .set_learning_rate(0.5).set_max_iter(2).fit(dense_table))
        spans = trace.load_spans()
        roots = [s for s in spans if s["name"] == "fit"]
        assert roots, [s["name"] for s in spans]
        mine = _spans_by_name(spans, roots[-1]["trace_id"])
        assert "train.dispatch" in mine and "train.sync" in mine
        assert mine["train.dispatch"]["parent_id"] == \
            roots[-1]["span_id"]


# -- flight recorder ----------------------------------------------------------


class TestFlightRecorder:
    def test_ring_is_bounded(self, flight_dir, monkeypatch):
        monkeypatch.setenv("FMT_FLIGHT_EVENTS", "16")
        for i in range(64):
            flight.record("tick", i=i)
        events = flight.events()
        assert len(events) == 16
        assert events[-1]["i"] == 63 and events[0]["i"] == 48
        assert events[-1]["seq"] == 64  # true totals survive the ring

    def test_capacity_zero_disables(self, flight_dir, monkeypatch):
        monkeypatch.setenv("FMT_FLIGHT_EVENTS", "0")
        flight.record("tick")
        assert flight.events() == []
        assert flight.dump("anything", force=True) is None

    def test_redaction_masks_secrets_and_truncates(self, flight_dir):
        flight.record("deploy", api_key="sk-very-secret",
                      detail="x" * 1000, count=3)
        (e,) = flight.events()
        assert e["api_key"] == "<redacted>"
        assert len(e["detail"]) == 256 and e["detail"].endswith("...")
        assert e["count"] == 3

    def test_dump_writes_jsonl_and_rate_limits(self, flight_dir,
                                               monkeypatch):
        monkeypatch.setenv("FMT_FLIGHT_MIN_S", "9999")
        flight.record("tick", i=1)
        path = flight.dump("unit_test")
        assert path and str(flight_dir) in path
        lines = [json.loads(line) for line in open(path)]
        assert lines[0]["kind"] == "flight.dump"
        assert lines[0]["reason"] == "unit_test"
        assert lines[1]["kind"] == "tick"
        assert flight.dump("unit_test") is None  # rate-limited
        assert flight.dump("unit_test", force=True) is not None

    def test_breaker_open_dumps_black_box(self, flight_dir, monkeypatch):
        monkeypatch.setenv("FMT_SERVE_BREAKER_THRESHOLD", "1")
        serve.reset_breakers()
        try:
            serve.breaker("TraceTestMapper").record_failure()
        finally:
            serve.reset_breakers()
        path = flight.last_dump_path()
        assert path and str(flight_dir) in path
        events = [json.loads(line) for line in open(path)][1:]
        opens = [e for e in events if e["kind"] == "breaker.state"
                 and e.get("state") == 1.0
                 and e.get("name") == "TraceTestMapper"]
        assert opens, events

    def test_record_never_raises_on_weird_values(self, flight_dir):
        flight.record("odd", obj=object(), arr=np.arange(3))
        (e,) = flight.events()
        assert isinstance(e["obj"], str) and isinstance(e["arr"], str)


# -- report satellites --------------------------------------------------------


class TestReportSatellites:
    def test_fit_delta_timings_carry_quantiles(self):
        from flink_ml_tpu.obs import report

        obs.enable()
        obs.reset()
        try:
            # consume any pending delta state, then observe fresh samples
            report._fit_delta_snapshot()
            for ms in (1, 2, 3, 4, 100):
                obs.observe("unit.test_stat", ms / 1e3)
            delta = report._fit_delta_snapshot()
        finally:
            obs.reset()
            obs.disable()
        stat = delta["timings"]["unit.test_stat"]
        assert stat["count"] == 5
        assert stat["p50_s"] == pytest.approx(0.003)
        assert stat["p99_s"] == pytest.approx(0.1)

    def test_check_json_emits_machine_readable_gates(self, tmp_path,
                                                     capsys):
        from flink_ml_tpu.obs import report

        baseline = tmp_path / "BASELINE.json"
        baseline.write_text(json.dumps({"measured": {
            "m_ratio": {"value": 1.0, "unit": "ratio (lower is better)",
                        "direction": "lower"},
            "m_tput": {"value": 100.0, "unit": "rows/sec"},
        }}))
        reports = [
            {"kind": "bench", "name": "m_ratio", "ts": 1.0, "git_sha": "x",
             "device": {"backend": "cpu"}, "extra": {"value": 1.2,
                                                     "unit": "ratio"}},
            {"kind": "bench", "name": "m_tput", "ts": 2.0, "git_sha": "x",
             "device": {"backend": "cpu"}, "extra": {"value": 95.0,
                                                     "unit": "rows/sec"}},
        ]
        (tmp_path / "runs.jsonl").write_text(
            "".join(json.dumps(r) + "\n" for r in reports)
        )
        rc = report.main(["--check", "--json", "--reports", str(tmp_path),
                          "--baseline", str(baseline)])
        out = json.loads(capsys.readouterr().out)
        assert rc == 1 and out["ok"] is False
        rows = {r["metric"]: r for r in out["metrics"]}
        # lower-is-better gate blown by 0.2 - threshold 0.1 = 0.1 margin
        assert rows["m_ratio"]["status"] == "regression"
        assert rows["m_ratio"]["direction"] == "lower"
        assert rows["m_ratio"]["margin"] == pytest.approx(-0.1)
        # throughput within the band, slack to the boundary
        assert rows["m_tput"]["status"] == "ok"
        assert rows["m_tput"]["direction"] == "higher"
        assert rows["m_tput"]["margin"] == pytest.approx(0.05)

    def test_transform_report_carries_timings_and_trace(self, tmp_path,
                                                        traced):
        from flink_ml_tpu.obs.report import load_reports, transform_report

        obs.enable()
        obs.reset()
        try:
            obs.observe("serve.deadline_ms", 0.004)
            with trace.root_span("pipeline"):
                transform_report("UnitModel", rows=8,
                                 serve_delta={"serve.device_ok": 1},
                                 directory=str(tmp_path))
                tid = trace.current_trace_ids()[0]
        finally:
            obs.reset()
            obs.disable()
        (rep,) = load_reports(str(tmp_path))
        assert rep["extra"]["trace_id"] == tid
        assert rep["extra"]["timings"]["serve.deadline_ms"]["count"] == 1
