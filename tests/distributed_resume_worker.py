"""Worker for the multi-process kill-and-resume test (VERDICT r4 #4).

Run as: python distributed_resume_worker.py <pid> <nprocs> <port> <phase> <ckpt_root>

Phase ``crash``: both processes run a checkpointed out-of-core sparse fit;
process 1 simulates a machine failure (``os._exit``) right after its second
snapshot commits, mid-fit — process 0 is left owing collectives and is
killed by the parent.  Phase ``resume``: a fresh pair of processes re-runs
the same fit over the same sources; each finds its own newest snapshot,
the fleet agrees on the common resume epoch
(``agreed_latest_checkpoint``'s one collective), and training continues to
completion.  The parent asserts the final model equals the uninterrupted
single-process reference bit-for-float — the Flink checkpoint/restart
story (`/root/reference/pom.xml:396-401` randomizes exactly this in every
reference test) on the jax.distributed data plane.
"""

import os
import sys

process_id = int(sys.argv[1])
num_processes = int(sys.argv[2])
port = sys.argv[3]
phase = sys.argv[4]
ckpt_root = sys.argv[5]

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=4"
)

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")

import numpy as np  # noqa: E402

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
from flink_ml_tpu.parallel.mesh import (  # noqa: E402
    initialize_distributed,
    shutdown_distributed,
)

initialize_distributed(
    coordinator_address=f"localhost:{port}",
    num_processes=num_processes,
    process_id=process_id,
)

if phase == "crash" and process_id == 1:
    # simulated machine failure: die hard right after the SECOND snapshot
    # commits (mid-fit; the fit runs more epochs than that)
    import flink_ml_tpu.iteration.checkpoint as ck

    _orig_save = ck.save_checkpoint
    _saves = {"n": 0}

    def _killing_save(*args, **kwargs):
        path = _orig_save(*args, **kwargs)
        _saves["n"] += 1
        if _saves["n"] >= 2:
            os._exit(17)
        return path

    ck.save_checkpoint = _killing_save

try:
    from tests._distributed_common import (
        fit_sparse_shard_table,
        make_sparse_shard_rows,
        sparse_shard_schema,
    )
    from flink_ml_tpu.table.sources import ChunkedTable, CollectionSource

    svecs, sy = make_sparse_shard_rows(num_processes)[process_id]
    table = ChunkedTable(
        CollectionSource(list(zip(svecs, sy)), sparse_shard_schema()),
        chunk_rows=64,
    )
    w, b = fit_sparse_shard_table(
        table,
        checkpoint_dir=os.path.join(ckpt_root, f"p{process_id}"),
        max_iter=6,
    )
    digest = [float(np.sum(w)), float(np.sum(w * w))]
    probe = [float(v) for v in w[:8]]
    print(
        "FITRESUME " + " ".join(f"{v:.9e}" for v in digest + probe + [b]),
        flush=True,
    )
finally:
    shutdown_distributed()
