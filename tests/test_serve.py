"""Serving robustness layer (ISSUE 4): input quarantine at the mapper
boundary, model-integrity verification, the inference circuit breaker with
its NumPy CPU fallback, and the per-transform serve accounting."""

import json
import os

import numpy as np
import pytest

from flink_ml_tpu import fault, obs, serve
from flink_ml_tpu.common.mapper import Mapper
from flink_ml_tpu.fault import injection
from flink_ml_tpu.ops.vector import DenseVector, SparseVector
from flink_ml_tpu.serve import (
    MapperOutputMisalignedError,
    ModelIntegrityError,
    quarantine,
)
from flink_ml_tpu.table.schema import DataTypes, Schema
from flink_ml_tpu.table.table import Table
from flink_ml_tpu.utils import persistence
from flink_ml_tpu.utils.persistence import load_table, save_table


@pytest.fixture(autouse=True)
def _clean_serve_state(tmp_path, monkeypatch):
    # transform RunReports must land in a per-test dir, never the
    # committed reports/; breakers, quarantine tables, and injection
    # schedules are process-wide and must not leak across tests
    monkeypatch.setenv("FMT_OBS_REPORTS", str(tmp_path / "_reports"))
    monkeypatch.setenv("FMT_RETRY_BASE_S", "0.001")
    injection.reset()
    serve.reset_breakers()
    quarantine.reset()
    obs.disable()
    obs.reset()
    yield
    injection.reset()
    serve.reset_breakers()
    quarantine.reset()
    obs.disable()
    obs.reset()


def _dense_table(X, y):
    return Table.from_columns(
        Schema.of(("features", DataTypes.DENSE_VECTOR), ("label", "double")),
        {"features": X, "label": y},
    )


def _xy(n=64, d=4, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float64)
    return X, y


def _logreg_model(X, y, detail=None):
    from flink_ml_tpu.lib import LogisticRegression

    est = (
        LogisticRegression().set_vector_col("features")
        .set_label_col("label").set_prediction_col("p")
        .set_learning_rate(0.5).set_max_iter(3)
    )
    if detail:
        est.set_prediction_detail_col(detail)
    return est.fit(_dense_table(X, y))


# -- quarantine ---------------------------------------------------------------


class TestQuarantine:
    def test_nan_row_is_masked_and_good_rows_serve_exactly(self):
        X, y = _xy()
        model = _logreg_model(X, y)
        (clean,) = model.transform(_dense_table(X, y))
        ref = np.asarray(clean.col("p"))

        Xbad = X.copy()
        Xbad[5, 2] = np.nan
        Xbad[17, 0] = np.inf
        (out,) = model.transform(_dense_table(Xbad, y))
        assert out.num_rows() == X.shape[0] - 2
        np.testing.assert_array_equal(
            np.asarray(out.col("p")), np.delete(ref, [5, 17])
        )
        qt = quarantine.quarantine_table("LogisticRegressionModel")
        assert qt is not None and qt.num_rows() == 2
        assert list(qt.col(quarantine.QUARANTINE_REASON_COL)) == [
            "nan_inf", "nan_inf",
        ]
        assert list(qt.col(quarantine.QUARANTINE_ROW_COL)) == [5, 17]

    def test_quarantine_counters_land_in_registry(self):
        obs.enable()
        X, y = _xy()
        model = _logreg_model(X, y)
        Xbad = X.copy()
        Xbad[3, 0] = np.nan
        model.transform(_dense_table(Xbad, y))
        c = obs.registry().snapshot()["counters"]
        assert c.get("serve.quarantined_rows") == 1
        assert c.get("serve.quarantined.nan_inf") == 1

    def test_object_column_reason_codes(self):
        """Null, wrong type, over-wide dense, out-of-range sparse, and
        non-finite sparse rows each carry their own reason code."""
        dim = 3
        good = DenseVector(np.ones(dim))
        rows = [
            (good, 1.0),
            (None, 0.0),                                   # null
            (DenseVector(np.ones(dim + 2)), 0.0),          # bad_dim (wide)
            (SparseVector(8, [7], [1.0]), 0.0),            # bad_dim (index)
            (SparseVector(dim, [1], [np.nan]), 0.0),       # nan_inf
            (good, 0.0),
        ]
        t = Table.from_rows(
            rows,
            Schema.of(("features", DataTypes.VECTOR), ("label", "double")),
        )
        verdict = quarantine.validate_feature_batch(
            t, dim=dim, vector_col="features"
        )
        assert verdict is not None
        good_mask, reasons = verdict
        assert list(good_mask) == [True, False, False, False, False, True]
        assert list(reasons[1:5]) == [
            "null", "bad_dim", "bad_dim", "nan_inf",
        ]

    def test_csr_column_vectorized_validation(self):
        from flink_ml_tpu.ops.batch import CsrRows

        col = CsrRows(
            dim=4,
            indptr=[0, 2, 3, 5],
            indices=[0, 1, 9, 2, 3],       # row 1 holds index 9 >= dim
            values=[1.0, 2.0, 1.0, np.inf, 1.0],  # row 2 holds an inf
        )
        t = Table.from_columns(
            Schema.of(("v", DataTypes.SPARSE_VECTOR), ("y", "double")),
            {"v": col, "y": np.zeros(3)},
        )
        good_mask, reasons = quarantine.validate_feature_batch(
            t, dim=4, vector_col="v"
        )
        assert list(good_mask) == [True, False, False]
        assert reasons[1] == "bad_dim" and reasons[2] == "nan_inf"

    def test_sparse_csr_batch_quarantines_through_transform(self):
        """End to end on the CSR-backed sparse inference path: the NaN row
        leaves the segment-CSR matvec, survivors score exactly."""
        from flink_ml_tpu.lib import LogisticRegression
        from flink_ml_tpu.ops.batch import CsrRows

        rng = np.random.RandomState(1)
        dim, n = 16, 32
        indptr = np.arange(0, 2 * n + 1, 2)
        indices = rng.randint(0, dim, 2 * n)
        values = rng.randn(2 * n)
        schema = Schema.of(("features", DataTypes.SPARSE_VECTOR),
                           ("label", "double"))
        y = (rng.randn(n) > 0).astype(np.float64)
        clean_col = CsrRows(dim, indptr, indices, values)
        t = Table.from_columns(schema, {"features": clean_col, "label": y})
        model = (
            LogisticRegression().set_vector_col("features")
            .set_label_col("label").set_prediction_col("p")
            .set_num_features(dim).set_max_iter(2).fit(t)
        )
        (clean,) = model.transform(t)
        ref = np.asarray(clean.col("p"))

        bad_values = values.copy()
        bad_values[indptr[9]] = np.nan  # poison row 9's first entry
        tb = Table.from_columns(
            schema,
            {"features": CsrRows(dim, indptr, indices, bad_values),
             "label": y},
        )
        (out,) = model.transform(tb)
        assert out.num_rows() == n - 1
        np.testing.assert_array_equal(
            np.asarray(out.col("p")), np.delete(ref, 9)
        )
        qt = quarantine.quarantine_table("LogisticRegressionModel")
        assert list(qt.col(quarantine.QUARANTINE_ROW_COL)) == [9]
        assert qt.col(quarantine.QUARANTINE_REASON_COL)[0] == "nan_inf"

    def test_feature_cols_nan_detection(self):
        t = Table.from_columns(
            Schema.of(("a", "double"), ("b", "double")),
            {"a": [1.0, np.nan, 3.0], "b": [1.0, 1.0, 1.0]},
        )
        good_mask, reasons = quarantine.validate_feature_batch(
            t, dim=2, feature_cols=["a", "b"]
        )
        assert list(good_mask) == [True, False, True]
        assert reasons[1] == "nan_inf"

    def test_clean_batch_returns_none_and_original_object_serves(self):
        X, y = _xy(16)
        t = _dense_table(X, y)
        assert quarantine.validate_feature_batch(
            t, dim=X.shape[1], vector_col="features"
        ) is None

    def test_all_rows_quarantined_yields_empty_result(self):
        X, y = _xy(8)
        model = _logreg_model(X, y)
        Xbad = np.full_like(X, np.nan)
        (out,) = model.transform(_dense_table(Xbad, y))
        assert out.num_rows() == 0
        assert out.schema.contains("p")
        qt = quarantine.quarantine_table("LogisticRegressionModel")
        assert qt.num_rows() == 8

    def test_quarantine_off_restores_failopen(self, monkeypatch):
        monkeypatch.setenv("FMT_SERVE_QUARANTINE", "0")
        X, y = _xy(16)
        model = _logreg_model(X, y)
        Xbad = X.copy()
        Xbad[2, 0] = np.nan
        (out,) = model.transform(_dense_table(Xbad, y))
        # legacy behavior: the bad row flows through and poisons only its
        # own prediction-score row (scores > 0 on NaN -> False -> 0.0)
        assert out.num_rows() == X.shape[0]
        assert quarantine.quarantine_table("LogisticRegressionModel") is None

    def test_batched_apply_records_table_level_row_offsets(self):
        X, y = _xy(64)
        model = _logreg_model(X, y)
        mapper = model._make_mapper(_dense_table(X, y).schema)
        mapper.load_model(*model.get_model_data())
        Xbad = X.copy()
        Xbad[5, 0] = np.nan
        Xbad[40, 1] = np.nan
        out = mapper.apply(_dense_table(Xbad, y), batch_size=16)
        assert out.num_rows() == 62
        qt = quarantine.quarantine_table("LogisticRegressionModel")
        assert sorted(qt.col(quarantine.QUARANTINE_ROW_COL)) == [5, 40]

    def test_side_table_cap_bounds_memory_not_counters(self, monkeypatch):
        monkeypatch.setenv("FMT_SERVE_QUARANTINE_CAP", "3")
        obs.enable()
        X, y = _xy(16)
        model = _logreg_model(X, y)
        Xbad = X.copy()
        Xbad[:8, 0] = np.nan
        model.transform(_dense_table(Xbad, y))
        qt = quarantine.quarantine_table("LogisticRegressionModel")
        assert qt.num_rows() == 3  # capped
        c = obs.registry().snapshot()["counters"]
        assert c.get("serve.quarantined_rows") == 8  # true total

    def test_validation_survives_a_device_outage(self, monkeypatch):
        """The finite check guards the path that HAS a CPU fallback, so a
        device blip during validation must degrade to the host isfinite,
        never fail the batch before the fallback could serve it."""
        import jax

        def dead_jit(fn):
            def raises(*a, **kw):
                raise RuntimeError("UNAVAILABLE: device unreachable")

            return raises

        monkeypatch.setattr(jax, "jit", dead_jit)
        quarantine._FINITE_FNS.clear()
        try:
            obs.enable()
            X, _ = _xy(8)
            X[2, 1] = np.nan
            t = Table.from_columns(
                Schema.of(("features", DataTypes.DENSE_VECTOR)),
                {"features": X},
            )
            good_mask, reasons = quarantine.validate_feature_batch(
                t, dim=X.shape[1], vector_col="features"
            )
            assert list(good_mask) == [i != 2 for i in range(8)]
            assert reasons[2] == "nan_inf"
            c = obs.registry().snapshot()["counters"]
            assert c.get("serve.validation_fallbacks") == 1
        finally:
            quarantine._FINITE_FNS.clear()

    def test_drain_clears_the_side_table(self):
        X, y = _xy(8)
        model = _logreg_model(X, y)
        Xbad = X.copy()
        Xbad[1, 0] = np.nan
        model.transform(_dense_table(Xbad, y))
        drained = quarantine.drain("LogisticRegressionModel")
        assert drained["LogisticRegressionModel"].num_rows() == 1
        assert quarantine.quarantine_table("LogisticRegressionModel") is None


# -- map_batch row-alignment contract ----------------------------------------


class _ShearMapper(Mapper):
    """A buggy mapper: drops the last row of its output column.  With no
    reserved input cols the merge would silently build a shorter table."""

    def output_cols(self):
        return ["out"], [DataTypes.DOUBLE]

    def reserved_cols(self):
        return []

    def map_batch(self, batch):
        return {"out": np.zeros(batch.num_rows() - 1)}


class TestOutputAlignment:
    def test_misaligned_output_column_raises_named_error(self):
        t = Table.from_columns(
            Schema.of(("a", "double")), {"a": np.arange(4.0)}
        )
        mapper = _ShearMapper(t.schema)
        with pytest.raises(MapperOutputMisalignedError) as ei:
            mapper.apply(t)
        msg = str(ei.value)
        assert "_ShearMapper" in msg and "'out'" in msg
        assert ei.value.got == 3 and ei.value.expected == 4

    def test_missing_output_column_still_loud(self):
        class _Missing(Mapper):
            def output_cols(self):
                return ["out"], [DataTypes.DOUBLE]

            def map_batch(self, batch):
                return {}

        t = Table.from_columns(
            Schema.of(("a", "double")), {"a": np.arange(4.0)}
        )
        with pytest.raises(ValueError, match="did not produce"):
            _Missing(t.schema).apply(t)


# -- circuit breaker + dispatch -----------------------------------------------


class TestCircuitBreaker:
    def test_opens_after_threshold_consecutive_failures(self, monkeypatch):
        monkeypatch.setenv("FMT_SERVE_BREAKER_THRESHOLD", "3")
        b = serve.CircuitBreaker("t")
        for _ in range(2):
            b.record_failure()
        assert b.state == 0.0 and b.allow_device()
        b.record_failure()
        assert b.state == 1.0 and not b.allow_device()

    def test_half_open_probe_then_close_or_reopen(self, monkeypatch):
        monkeypatch.setenv("FMT_SERVE_BREAKER_THRESHOLD", "1")
        monkeypatch.setenv("FMT_SERVE_BREAKER_COOLDOWN_S", "0")
        b = serve.CircuitBreaker("t")
        b.record_failure()
        assert b.state == 1.0
        assert b.allow_device()  # cooldown elapsed -> half-open probe
        assert b.state == 0.5
        b.record_failure()       # the probe failed -> re-open immediately
        assert b.state == 1.0
        assert b.allow_device()
        b.record_success()
        assert b.state == 0.0

    def test_success_resets_consecutive_failures(self, monkeypatch):
        monkeypatch.setenv("FMT_SERVE_BREAKER_THRESHOLD", "2")
        b = serve.CircuitBreaker("t")
        b.record_failure()
        b.record_success()
        b.record_failure()
        assert b.state == 0.0  # never two consecutive


class TestDispatch:
    def test_transient_failure_degrades_to_fallback(self, monkeypatch):
        monkeypatch.setenv("FMT_RETRY_ATTEMPTS", "2")
        obs.enable()
        injection.configure("serve.dispatch@1+")
        with pytest.warns(RuntimeWarning, match="CPU fallback"):
            out = serve.dispatch(
                "t", device=lambda: "device", fallback=lambda: "cpu"
            )
        assert out == "cpu"
        c = obs.registry().snapshot()["counters"]
        assert c.get("serve.fallbacks") == 1
        assert c.get("fault.retries.serve.dispatch") == 1

    def test_transient_failure_without_fallback_reraises(self, monkeypatch):
        monkeypatch.setenv("FMT_RETRY_ATTEMPTS", "1")
        injection.configure("serve.dispatch@1+")
        with pytest.raises(fault.InjectedFault):
            serve.dispatch("t", device=lambda: "device")

    def test_deterministic_bug_is_never_papered_over(self):
        def buggy():
            raise ValueError("shape mismatch")

        with pytest.raises(ValueError, match="shape mismatch"):
            serve.dispatch("t", device=buggy, fallback=lambda: "cpu")
        assert serve.breaker("t").state == 0.0  # bugs are not breaker food

    def test_open_breaker_skips_device_entirely(self, monkeypatch):
        monkeypatch.setenv("FMT_SERVE_BREAKER_THRESHOLD", "1")
        monkeypatch.setenv("FMT_RETRY_ATTEMPTS", "1")
        calls = {"n": 0}

        def device():
            calls["n"] += 1
            raise RuntimeError("UNAVAILABLE: device gone")

        import warnings

        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            serve.dispatch("t", device=device, fallback=lambda: "cpu")
        assert serve.breaker("t").state == 1.0
        out = serve.dispatch("t", device=device, fallback=lambda: "cpu")
        assert out == "cpu" and calls["n"] == 1  # device not re-attempted

    def test_call_time_lands_in_deadline_histogram(self):
        obs.enable()
        serve.dispatch("t", device=lambda: 42, fallback=None)
        snap = obs.registry().snapshot()["timings"]
        assert snap["serve.deadline_ms"]["count"] == 1

    def test_deadline_overrun_feeds_the_breaker(self, monkeypatch):
        import time

        monkeypatch.setenv("FMT_SERVE_DEADLINE_MS", "1")
        monkeypatch.setenv("FMT_SERVE_BREAKER_THRESHOLD", "2")
        obs.enable()

        def slow():
            time.sleep(0.01)
            return "late"

        assert serve.dispatch("t", device=slow, fallback=lambda: "cpu") == "late"
        assert serve.dispatch("t", device=slow, fallback=lambda: "cpu") == "late"
        # two overruns opened the breaker: the third call serves from CPU
        assert serve.breaker("t").state == 1.0
        assert serve.dispatch("t", device=slow, fallback=lambda: "cpu") == "cpu"
        c = obs.registry().snapshot()["counters"]
        assert c.get("serve.deadline_exceeded") == 2


class TestFallbackParity:
    """The NumPy CPU path must agree with the device path: discrete
    outputs exactly, raw scores to float-accumulation tolerance."""

    def _force_fallback(self, fn, monkeypatch):
        monkeypatch.setenv("FMT_SERVE_BREAKER_THRESHOLD", "1")
        monkeypatch.setenv("FMT_RETRY_ATTEMPTS", "1")
        import warnings

        injection.configure("serve.dispatch@1+")
        try:
            with warnings.catch_warnings():
                warnings.simplefilter("ignore", RuntimeWarning)
                fn()          # absorbs the failure, opens the breaker
                return fn()   # fully degraded
        finally:
            injection.reset()

    def test_logreg_dense(self, monkeypatch):
        X, y = _xy()
        model = _logreg_model(X, y, detail="prob")
        t = _dense_table(X, y)
        (ref,) = model.transform(t)
        (out,) = self._force_fallback(lambda: model.transform(t), monkeypatch)
        np.testing.assert_array_equal(
            np.asarray(out.col("p")), np.asarray(ref.col("p"))
        )
        np.testing.assert_allclose(
            np.asarray(out.col("prob")), np.asarray(ref.col("prob")),
            rtol=1e-5, atol=1e-6,
        )

    def test_logreg_sparse(self, monkeypatch):
        rng = np.random.RandomState(3)
        dim, n = 32, 48
        rows = []
        for i in range(n):
            idx = rng.choice(dim, 4, replace=False)
            rows.append(
                (SparseVector(dim, np.sort(idx), rng.randn(4)),
                 float(i % 2))
            )
        t = Table.from_rows(
            rows,
            Schema.of(("features", DataTypes.SPARSE_VECTOR),
                      ("label", "double")),
        )
        from flink_ml_tpu.lib import LogisticRegression

        model = (
            LogisticRegression().set_vector_col("features")
            .set_label_col("label").set_prediction_col("p")
            .set_num_features(dim).set_max_iter(2).fit(t)
        )
        (ref,) = model.transform(t)
        (out,) = self._force_fallback(lambda: model.transform(t), monkeypatch)
        np.testing.assert_array_equal(
            np.asarray(out.col("p")), np.asarray(ref.col("p"))
        )

    def test_kmeans_assignment(self, monkeypatch):
        from flink_ml_tpu.lib import KMeans

        X, y = _xy(n=96, d=3, seed=5)
        t = _dense_table(X, y)
        model = (
            KMeans().set_vector_col("features").set_k(5)
            .set_prediction_col("c").set_prediction_detail_col("dist")
            .set_max_iter(4).fit(t)
        )
        (ref,) = model.transform(t)
        (out,) = self._force_fallback(lambda: model.transform(t), monkeypatch)
        np.testing.assert_array_equal(
            np.asarray(out.col("c")), np.asarray(ref.col("c"))
        )
        np.testing.assert_allclose(
            np.asarray(out.col("dist")), np.asarray(ref.col("dist")),
            rtol=1e-4, atol=1e-5,
        )

    def test_knn_vote(self, monkeypatch):
        from flink_ml_tpu.lib import Knn

        X, y = _xy(n=48, d=3, seed=7)
        t = _dense_table(X, y)
        model = (
            Knn().set_vector_col("features").set_label_col("label")
            .set_k(3).set_prediction_col("p").fit(t)
        )
        (ref,) = model.transform(t)
        (out,) = self._force_fallback(lambda: model.transform(t), monkeypatch)
        np.testing.assert_array_equal(
            np.asarray(out.col("p")), np.asarray(ref.col("p"))
        )

    def test_knn_fallback_chunks_the_reference_set(self, monkeypatch):
        """The CPU fallback must carry its top-k across reference chunks
        (memory bound O(batch x chunk)) and still match the device path —
        exercised with a chunk far smaller than the training set."""
        from flink_ml_tpu.lib import Knn
        from flink_ml_tpu.lib.knn import KnnModelMapper

        monkeypatch.setattr(KnnModelMapper, "CPU_FALLBACK_CHUNK", 16)
        X, y = _xy(n=80, d=3, seed=11)
        t = _dense_table(X, y)
        model = (
            Knn().set_vector_col("features").set_label_col("label")
            .set_k(5).set_prediction_col("p")
            .set_prediction_detail_col("d").fit(t)
        )
        (ref,) = model.transform(t)
        (out,) = self._force_fallback(lambda: model.transform(t), monkeypatch)
        np.testing.assert_array_equal(
            np.asarray(out.col("p")), np.asarray(ref.col("p"))
        )
        # compare SQUARED distances: the self-match's true distance is 0,
        # where sqrt turns a ~5e-7 f32 cancellation residue into ~7e-4
        np.testing.assert_allclose(
            np.asarray(out.col("d")) ** 2, np.asarray(ref.col("d")) ** 2,
            rtol=1e-4, atol=1e-5,
        )

    def test_online_predict_fallback_serves_without_device_reads(
        self, monkeypatch
    ):
        """The streaming predict fallback must not require a D2H pull: when
        even the param fetch dies, the last-reachable host mirror serves."""
        monkeypatch.setenv("FMT_SERVE_BREAKER_THRESHOLD", "1")
        monkeypatch.setenv("FMT_RETRY_ATTEMPTS", "1")
        import warnings

        from flink_ml_tpu.lib import OnlineLogisticRegression

        X, y = _xy(n=96, d=3, seed=13)
        t = _dense_table(X, y)
        est = (
            OnlineLogisticRegression().set_vector_col("features")
            .set_label_col("label").set_prediction_col("p")
            .set_global_batch_size(32).set_window_ms(100)
        )
        injection.configure("serve.dispatch@1+")
        try:
            with warnings.catch_warnings():
                warnings.simplefilter("ignore", RuntimeWarning)
                from flink_ml_tpu.table.sources import GeneratorSource

                rows = t.to_rows()
                source = GeneratorSource.linear_timestamps(
                    rows, 4, t.schema
                )
                pred_source = GeneratorSource.linear_timestamps(
                    rows, 4, t.schema
                )
                model, result = est.fit_unbounded(
                    source, prediction_source=pred_source
                )
        finally:
            injection.reset()
        # every batch predicted through the fallback, none dropped
        assert len(result.predictions) == len(rows)

    def test_standard_scaler_exact(self, monkeypatch):
        from flink_ml_tpu.lib import StandardScaler

        X, y = _xy(n=32)
        t = _dense_table(X, y)
        model = (
            StandardScaler().set_selected_col("features")
            .set_output_col("s").fit(t)
        )
        (ref,) = model.transform(t)
        (out,) = self._force_fallback(lambda: model.transform(t), monkeypatch)
        # elementwise math: the fallback is bit-exact, not just close
        np.testing.assert_array_equal(
            np.asarray(out.features_dense("s")),
            np.asarray(ref.features_dense("s")),
        )


# -- multi-process agreement (satellite: mirror the slab pool's rules) --------


class TestMultiProcessAgreement:
    def _two_process(self, monkeypatch, peer_row):
        """Simulate a 2-process fleet: allgather returns our row stacked
        with a fixed peer row (the test_fault dead-peer idiom)."""
        import jax
        from jax.experimental import multihost_utils

        monkeypatch.setattr(jax, "process_count", lambda: 2)
        monkeypatch.setattr(
            multihost_utils, "process_allgather",
            lambda x, **kw: np.stack(
                [np.asarray(x), np.asarray(peer_row, dtype=np.asarray(x).dtype)]
            ),
        )

    def test_agreed_bad_mask_bad_wins(self, monkeypatch):
        local = np.array([False, True, False, False])
        peer = [1, 0, 0, 1]  # the peer flagged rows 0 and 3
        self._two_process(monkeypatch, peer)
        agreed = quarantine.agreed_bad_mask(local)
        assert list(agreed) == [True, True, False, True]

    def test_agreed_mask_identity_single_process(self):
        local = np.array([True, False])
        assert list(quarantine.agreed_bad_mask(local)) == [True, False]

    def test_validate_agreed_stamps_peer_flagged_rows(self, monkeypatch):
        X, y = _xy(4)
        t = _dense_table(X, y)
        self._two_process(monkeypatch, [0, 1, 0, 0])  # peer flags row 1
        verdict = quarantine.validate_feature_batch(
            t, dim=X.shape[1], vector_col="features", agreed=True
        )
        assert verdict is not None
        good_mask, reasons = verdict
        assert list(good_mask) == [True, False, True, True]
        assert reasons[1] == "peer_flagged"

    def test_breaker_agreed_open_wins(self, monkeypatch):
        b = serve.CircuitBreaker("t")
        assert b.allow_device()          # locally closed
        self._two_process(monkeypatch, [1])  # peer reports blocked
        assert not b.allow_device(agreed=True)
        self._two_process(monkeypatch, [0])  # peer reports open-for-device
        assert b.allow_device(agreed=True)


# -- model integrity ----------------------------------------------------------


def _small_table():
    return Table.from_columns(
        Schema.of(("w", DataTypes.DENSE_VECTOR), ("b", "double")),
        {"w": np.arange(12.0).reshape(4, 3), "b": np.arange(4.0)},
    )


class TestModelIntegrity:
    def test_save_load_round_trip_with_commit_record(self, tmp_path):
        t = _small_table()
        path = str(tmp_path / "m.jsonl")
        save_table(t, path)
        assert os.path.exists(path + ".commit.json")
        back = load_table(path)
        np.testing.assert_array_equal(
            back.features_dense("w"), t.features_dense("w")
        )

    def test_interrupted_save_never_leaves_truncated_file(
        self, tmp_path, monkeypatch
    ):
        """RED (satellite): pre-atomic-save an interrupted write left a
        truncated model at the final path; now the committed version
        survives untouched and no .tmp debris remains."""
        t = _small_table()
        path = str(tmp_path / "m.jsonl")
        save_table(t, path)
        committed = open(path).read()

        original = persistence.encode_row
        calls = {"n": 0}

        def dying_encode(row, schema):
            calls["n"] += 1
            if calls["n"] > 2:
                raise OSError(5, "I/O error mid-write")  # the kill
            return original(row, schema)

        monkeypatch.setattr(persistence, "encode_row", dying_encode)
        with pytest.raises(OSError):
            save_table(t, path)
        monkeypatch.setattr(persistence, "encode_row", original)
        assert open(path).read() == committed  # previous commit intact
        assert not os.path.exists(path + ".tmp")
        load_table(path)  # and it still verifies

    def test_corrupted_byte_raises_model_integrity_error(self, tmp_path):
        t = _small_table()
        path = str(tmp_path / "m.jsonl")
        save_table(t, path)
        blob = bytearray(open(path, "rb").read())
        blob[len(blob) // 2] ^= 0xFF
        with open(path, "wb") as f:
            f.write(bytes(blob))
        with pytest.raises(ModelIntegrityError, match="CRC32"):
            load_table(path)

    def test_truncation_with_commit_record_is_a_length_mismatch(
        self, tmp_path
    ):
        t = _small_table()
        path = str(tmp_path / "m.jsonl")
        save_table(t, path)
        lines = open(path).read().splitlines(keepends=True)
        with open(path, "w") as f:
            f.writelines(lines[:-1])  # drop a whole trailing row, cleanly
        with pytest.raises(ModelIntegrityError, match="bytes"):
            load_table(path)

    def test_truncated_jsonl_tail_without_sidecar_still_loud(self, tmp_path):
        """RED (satellite): a legacy file (no commit record) truncated
        mid-row must raise the integrity diagnostic, not half-load."""
        t = _small_table()
        path = str(tmp_path / "m.jsonl")
        save_table(t, path)
        os.remove(path + ".commit.json")
        raw = open(path).read()
        with open(path, "w") as f:
            f.write(raw[: int(len(raw) * 0.93)])
        with pytest.raises(ModelIntegrityError, match="line"):
            load_table(path)

    def test_legacy_file_without_sidecar_loads(self, tmp_path):
        t = _small_table()
        path = str(tmp_path / "m.jsonl")
        save_table(t, path)
        os.remove(path + ".commit.json")
        back = load_table(path)
        assert back.num_rows() == t.num_rows()

    def test_row_schema_arity_mismatch_is_integrity_error(self, tmp_path):
        path = str(tmp_path / "m.jsonl")
        schema = Schema.of(("a", "double"), ("b", "double"))
        with open(path, "w") as f:
            f.write(json.dumps({"schema": schema.to_dict()}) + "\n")
            f.write("[1.0]\n")  # arity 1 for a 2-column schema
        with pytest.raises(ModelIntegrityError, match="mismatch"):
            load_table(path)

    def test_file_model_source_verifies_at_open(self, tmp_path):
        from flink_ml_tpu.common.model_source import FileModelSource

        t = _small_table()
        path = str(tmp_path / "m.jsonl")
        save_table(t, path)
        (back,) = FileModelSource(path).get_model_tables()
        assert back.num_rows() == 4
        blob = bytearray(open(path, "rb").read())
        blob[-3] ^= 0x55
        with open(path, "wb") as f:
            f.write(bytes(blob))
        with pytest.raises(ModelIntegrityError):
            FileModelSource(path).get_model_tables()

    def test_corrupt_stage_descriptor_is_integrity_error(self, tmp_path):
        from flink_ml_tpu.api.core import load_stage
        from flink_ml_tpu.lib import StandardScaler

        X, y = _xy(16)
        model = (
            StandardScaler().set_selected_col("features")
            .set_output_col("s").fit(_dense_table(X, y))
        )
        stage_dir = str(tmp_path / "stage")
        model.save(stage_dir)
        with open(os.path.join(stage_dir, "stage.json"), "w") as f:
            f.write('{"module": "x", ')  # truncated descriptor
        with pytest.raises(ModelIntegrityError, match="unreadable"):
            load_stage(stage_dir)

    def test_parseable_but_wrong_descriptor_is_integrity_error(
        self, tmp_path
    ):
        """A partially-overwritten descriptor that still parses as JSON
        (missing keys, a list) must follow the same ModelIntegrityError
        contract as an unparseable one — supervisors fail over on that
        type, not on a stray KeyError."""
        from flink_ml_tpu.api.core import load_stage
        from flink_ml_tpu.api.pipeline import PipelineModel
        from flink_ml_tpu.lib import StandardScaler

        X, y = _xy(16)
        model = (
            StandardScaler().set_selected_col("features")
            .set_output_col("s").fit(_dense_table(X, y))
        )
        stage_dir = str(tmp_path / "stage")
        model.save(stage_dir)
        for payload in ('{"params": "{}"}', "[1, 2, 3]"):
            with open(os.path.join(stage_dir, "stage.json"), "w") as f:
                f.write(payload)
            with pytest.raises(ModelIntegrityError):
                load_stage(stage_dir)

        pd = str(tmp_path / "pipe")
        PipelineModel([model]).save(pd)
        with open(os.path.join(pd, "pipeline.json"), "w") as f:
            f.write('{"kind": "PipelineModel"}')  # num_stages lost
        with pytest.raises(ModelIntegrityError):
            PipelineModel.load(pd)

    def test_pipeline_missing_stage_dir_is_integrity_error(self, tmp_path):
        import shutil

        from flink_ml_tpu.api.pipeline import PipelineModel

        X, y = _xy(16)
        model = _logreg_model(X, y)
        pd = str(tmp_path / "pipe")
        PipelineModel([model]).save(pd)
        shutil.rmtree(os.path.join(pd, "stage_000"))
        with pytest.raises(ModelIntegrityError, match="missing"):
            PipelineModel.load(pd)

    def test_nan_and_none_round_trip_double_vs_int(self):
        """persistence.py null special cases (satellite): NaN encodes as
        null; null decodes to NaN for float columns and stays None for
        int/string columns."""
        schema = Schema.of(("d", "double"), ("i", "int"), ("s", "string"))
        assert persistence.encode_row((np.nan, 3, "x"), schema) == [
            None, 3, "x",
        ]
        assert persistence.encode_row((np.float64("nan"), 1, None),
                                      schema) == [None, 1, None]
        d, i, s = persistence.decode_row([None, None, None], schema)
        assert np.isnan(d) and i is None and s is None

    def test_double_column_nan_round_trips_through_files(self, tmp_path):
        t = Table.from_columns(
            Schema.of(("d", "double")), {"d": [1.5, np.nan, -2.0]}
        )
        path = str(tmp_path / "nan.jsonl")
        save_table(t, path)
        back = np.asarray(load_table(path).col("d"))
        assert back[0] == 1.5 and np.isnan(back[1]) and back[2] == -2.0


# -- per-transform serve accounting -------------------------------------------


class TestServeReports:
    def test_transform_writes_serve_report(self, tmp_path, monkeypatch):
        monkeypatch.setenv("FMT_OBS_REPORTS", str(tmp_path))
        obs.enable()
        X, y = _xy()
        model = _logreg_model(X, y)
        Xbad = X.copy()
        Xbad[1, 0] = np.nan
        model.transform(_dense_table(Xbad, y))
        from flink_ml_tpu.obs.report import load_reports

        transforms = [
            r for r in load_reports(str(tmp_path))
            if r["kind"] == "transform"
        ]
        assert transforms, "transform wrote no RunReport"
        r = transforms[-1]
        assert r["name"] == "LogisticRegressionModel"
        assert r["extra"]["rows"] == X.shape[0]
        assert r["extra"]["serve"]["serve.quarantined_rows"] == 1
        assert r["extra"]["serve"]["serve.device_ok"] >= 1

    def test_fallback_only_transform_is_serve_degraded(
        self, tmp_path, monkeypatch
    ):
        import warnings

        monkeypatch.setenv("FMT_OBS_REPORTS", str(tmp_path))
        monkeypatch.setenv("FMT_SERVE_BREAKER_THRESHOLD", "1")
        monkeypatch.setenv("FMT_RETRY_ATTEMPTS", "1")
        obs.enable()
        X, y = _xy()
        model = _logreg_model(X, y)
        t = _dense_table(X, y)
        model.transform(t)  # healthy: device_ok > 0 -> not degraded
        injection.configure("serve.dispatch@1+")
        try:
            with warnings.catch_warnings():
                warnings.simplefilter("ignore", RuntimeWarning)
                model.transform(t)  # opens the breaker
                model.transform(t)  # fallback-only
        finally:
            injection.reset()
        from flink_ml_tpu.obs.report import load_reports, serve_degraded_runs

        flagged = serve_degraded_runs(load_reports(str(tmp_path)))
        assert len(flagged) == 1
        assert flagged[0]["name"] == "LogisticRegressionModel"
        assert flagged[0]["serve"]["serve.fallbacks"] >= 1

    def test_healthy_transform_is_not_degraded(self, tmp_path, monkeypatch):
        monkeypatch.setenv("FMT_OBS_REPORTS", str(tmp_path))
        obs.enable()
        X, y = _xy()
        model = _logreg_model(X, y)
        model.transform(_dense_table(X, y))
        from flink_ml_tpu.obs.report import load_reports, serve_degraded_runs

        assert serve_degraded_runs(load_reports(str(tmp_path))) == []
