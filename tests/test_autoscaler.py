"""FleetAutoscaler (ISSUE 19) — the elastic control loop's decision
policy, driven tick-by-tick against a scripted fleet and an injected
clock: every decision is a pure function of the sample history and the
clock, so hysteresis/flap-freedom are PROVED, not slept for.

Two tiers again: policy against ``_FakeFleet`` (scripted health, logged
membership calls), and the fail-closed satellites against the REAL
collaborators (a real ``SLOMonitor`` thin window, a real
``ReplicaRouter`` with a broken probe) — the autoscaler must never read
"no data" as "safe to shrink".
"""

import time

import pytest

from flink_ml_tpu import obs
from flink_ml_tpu.obs import telemetry
from flink_ml_tpu.obs.slo import SLOMonitor
from flink_ml_tpu.serving import FleetAutoscaler, ReplicaRouter, ScalerConfig
from flink_ml_tpu.serving.batcher import ServeResult

WAIT = 60


@pytest.fixture
def obs_on():
    obs.enable()
    obs.reset()
    yield
    obs.reset()
    obs.disable()


class _Clock:
    """An injectable monotonic clock: ``tick`` advances, calls read."""

    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def tick(self, dt):
        self.t += dt
        return self.t


class _FakeFleet:
    """The router surface the autoscaler consumes: scripted health,
    logged membership calls.  Mutate ``health`` between steps to script
    a scenario."""

    def __init__(self, size=1):
        self.size = size
        self.adds = []
        self.removes = []
        self.decline = False
        self.health = {
            "quarantined": 0,
            "queued_rows": 0,
            "requests": 0.0,
            "shed": 0.0,
            "max_burn_rate": 0.0,
            "burn_seen": False,
            "probe_suspect": 0,
        }

    def fleet_size(self):
        return self.size

    def fleet_health(self):
        out = dict(self.health)
        out["size"] = self.size
        out["live"] = self.size
        out["ready"] = self.size
        return out

    def add_replica(self):
        if self.decline:
            return None
        self.size += 1
        name = f"replica-{self.size}-g{self.size}"
        self.adds.append(name)
        return name

    def remove_replica(self):
        if self.decline or self.size <= 1:
            return None
        self.size -= 1
        name = f"removed-{len(self.removes)}"
        self.removes.append(name)
        return name


def _scaler(fleet, clock, **kw):
    kw.setdefault("min_replicas", 1)
    kw.setdefault("max_replicas", 4)
    kw.setdefault("window_s", 10.0)
    kw.setdefault("idle_windows", 3)
    kw.setdefault("cooldown_s", 30.0)
    kw.setdefault("up_burn", 1.0)
    kw.setdefault("down_burn", 0.5)
    kw.setdefault("warm_spares", 0)
    return FleetAutoscaler(fleet, now_fn=clock, **kw)


class TestScalerConfig:
    def test_env_defaults(self):
        cfg = ScalerConfig.from_env()
        assert cfg.min_replicas == 1
        assert cfg.max_replicas == 8
        assert cfg.up_burn == 1.0
        assert cfg.down_burn == 0.5
        assert cfg.window_s == 30.0
        assert cfg.idle_windows == 3
        assert cfg.cooldown_s == 60.0
        assert cfg.warm_spares == 0

    def test_overrides_win(self, monkeypatch):
        monkeypatch.setenv("FMT_SCALE_MAX", "16")
        assert ScalerConfig.from_env().max_replicas == 16
        assert ScalerConfig.from_env(max_replicas=2).max_replicas == 2

    def test_validation(self):
        with pytest.raises(ValueError):
            ScalerConfig.from_env(min_replicas=0)
        with pytest.raises(ValueError):
            ScalerConfig.from_env(min_replicas=4, max_replicas=2)
        with pytest.raises(ValueError):
            ScalerConfig.from_env(window_s=0.0)
        with pytest.raises(ValueError):
            ScalerConfig.from_env(warm_spares=-1)

    def test_hysteresis_thresholds_are_separate_knobs(self):
        cfg = ScalerConfig.from_env()
        assert cfg.down_burn < cfg.up_burn  # the hysteresis band


class TestScaleUp:
    def test_burn_scales_up_on_the_first_sample(self):
        """An SLO already burning pays for every tick of delay: the up
        trigger acts on the LATEST sample, no window wait."""
        fleet = _FakeFleet(size=1)
        fleet.health.update(burn_seen=True, max_burn_rate=2.0)
        scaler = _scaler(fleet, _Clock())
        decision = scaler.step()
        assert decision["action"] == "up"
        assert decision["reason"] == "slo_burn"
        assert len(fleet.adds) == 1
        assert scaler.target == 2

    def test_queue_growth_needs_window_coverage(self):
        """One bursty queue sample must not grow the fleet — the trend
        has to sustain across the whole window first."""
        fleet = _FakeFleet(size=1)
        fleet.health.update(queued_rows=5)
        clock = _Clock()
        scaler = _scaler(fleet, clock)
        scaler.step()
        assert fleet.adds == []  # history doesn't span the window yet
        decisions = []
        for _ in range(6):  # 12 s of sustained non-draining queue
            clock.tick(2.0)
            fleet.health["queued_rows"] += 1
            decisions.append(scaler.step())
        ups = [d for d in decisions if d["action"] == "up"]
        assert len(ups) == 1  # fired once the window was covered...
        assert ups[0]["reason"] == "queue_growth"
        assert len(fleet.adds) == 1  # ...then the cooldown held

    def test_sheds_inside_the_window_scale_up(self):
        fleet = _FakeFleet(size=1)
        clock = _Clock()
        scaler = _scaler(fleet, clock)
        scaler.step()
        decisions = []
        for _ in range(6):
            clock.tick(2.0)
            fleet.health["shed"] += 3.0
            decisions.append(scaler.step())
        ups = [d for d in decisions if d["action"] == "up"]
        assert len(ups) == 1
        assert ups[0]["reason"] == "shed"

    def test_at_max_is_a_counted_block(self):
        fleet = _FakeFleet(size=1)
        fleet.health.update(burn_seen=True, max_burn_rate=2.0)
        scaler = _scaler(fleet, _Clock(), max_replicas=1)
        decision = scaler.step()
        assert decision["action"] == "hold"
        assert "at_max" in decision["blocked"]
        assert fleet.adds == []

    def test_cooldown_rate_limits_consecutive_ups(self):
        fleet = _FakeFleet(size=1)
        fleet.health.update(burn_seen=True, max_burn_rate=2.0)
        clock = _Clock()
        scaler = _scaler(fleet, clock)
        assert scaler.step()["action"] == "up"
        clock.tick(2.0)
        decision = scaler.step()  # still burning, but inside cooldown
        assert decision["action"] == "hold"
        assert "cooldown" in decision["blocked"]
        assert len(fleet.adds) == 1
        assert scaler.target == 2  # the TARGET is cooldown-gated too
        clock.tick(30.0)
        assert scaler.step()["action"] == "up"
        assert len(fleet.adds) == 2


class TestScaleDown:
    def _idle_through_horizon(self, scaler, fleet, clock, steps=16,
                              dt=2.0):
        decisions = []
        for _ in range(steps):
            decisions.append(scaler.step())
            clock.tick(dt)
        return decisions

    def test_sustained_idle_scales_down_with_cooldown(self):
        fleet = _FakeFleet(size=3)
        clock = _Clock()
        scaler = _scaler(fleet, clock)
        assert scaler.target == 3
        decisions = self._idle_through_horizon(scaler, fleet, clock,
                                               steps=17)
        downs = [d for d in decisions if d["action"] == "down"]
        # exactly ONE shrink: the horizon (30 s) gates the first, the
        # cooldown (30 s) gates the second
        assert len(downs) == 1
        assert downs[0]["reason"] == "sustained_idle"
        assert fleet.removes and len(fleet.removes) == 1
        blocked_cooldown = [d for d in decisions
                            if "cooldown" in d.get("blocked", [])]
        assert blocked_cooldown  # the second shrink WANTED to happen
        clock.tick(30.0)
        assert scaler.step()["action"] == "down"
        assert len(fleet.removes) == 2
        assert scaler.target == 1

    def test_never_shrinks_below_min(self):
        fleet = _FakeFleet(size=1)
        clock = _Clock()
        scaler = _scaler(fleet, clock)
        for _ in range(40):
            decision = scaler.step()
            clock.tick(2.0)
        assert decision["action"] == "hold"
        assert fleet.removes == []
        assert scaler.target == 1

    def test_thin_slo_window_blocks_scale_down(self):
        """Satellite 3, policy half: traffic flowed but NO replica has a
        judged burn window — "no data" must read as a veto, never as
        "all clear, shrink"."""
        fleet = _FakeFleet(size=2)
        fleet.health.update(burn_seen=False)
        clock = _Clock()
        scaler = _scaler(fleet, clock)
        decision = None
        for _ in range(17):
            fleet.health["requests"] += 10.0  # traffic is flowing
            decision = scaler.step()
            clock.tick(2.0)
        assert fleet.removes == []
        assert "no_burn_signal" in decision["blocked"]
        assert decision["action"] == "hold"

    def test_burn_above_down_threshold_blocks_quietly(self):
        """The hysteresis band: burn between DOWN and UP thresholds is
        plain traffic — no action either way, and not a counted block
        (a busy fleet isn't "blocked from shrinking")."""
        fleet = _FakeFleet(size=2)
        fleet.health.update(burn_seen=True, max_burn_rate=0.7)
        clock = _Clock()
        scaler = _scaler(fleet, clock)
        for _ in range(17):
            fleet.health["requests"] += 10.0
            decision = scaler.step()
            clock.tick(2.0)
        assert fleet.adds == [] and fleet.removes == []
        assert decision["action"] == "hold"
        assert "blocked" not in decision

    def test_probe_suspect_blocks_scale_down(self):
        """A replica unready because its PROBE broke is a fail-closed
        veto: the fleet may be idle only because we can't see it."""
        fleet = _FakeFleet(size=2)
        fleet.health.update(probe_suspect=1)
        clock = _Clock()
        scaler = _scaler(fleet, clock)
        for _ in range(17):
            decision = scaler.step()
            clock.tick(2.0)
        assert fleet.removes == []
        assert "probe_error" in decision["blocked"]

    def test_quarantined_slot_blocks_scale_down(self):
        fleet = _FakeFleet(size=3)
        fleet.health.update(quarantined=1)
        clock = _Clock()
        scaler = _scaler(fleet, clock, cooldown_s=1.0)
        for _ in range(17):
            decision = scaler.step()
            clock.tick(2.0)
        assert fleet.removes == []
        assert "quarantine" in decision.get("blocked", [])


class TestHysteresis:
    def test_square_wave_is_flap_free(self):
        """The acceptance scenario: a square-wave burn signal (20 s at
        2.0, 20 s at 0.0, traffic flowing throughout) over 5 periods
        produces AT MOST one scale event per period and zero shrinks —
        hysteresis by construction, not by luck."""
        fleet = _FakeFleet(size=1)
        clock = _Clock()
        scaler = _scaler(fleet, clock, max_replicas=8)
        period, t0 = 40.0, clock.t
        events_by_period = {}
        for step in range(100):  # 5 periods at a 2 s tick
            phase = (clock.t - t0) % period
            fleet.health.update(
                burn_seen=True,
                max_burn_rate=2.0 if phase < 20.0 else 0.0,
            )
            fleet.health["requests"] += 10.0
            decision = scaler.step()
            if decision["action"] != "hold":
                key = int((clock.t - t0) // period)
                events_by_period[key] = events_by_period.get(key, 0) + 1
            clock.tick(2.0)
        assert fleet.removes == []  # never a shrink inside the wave
        assert events_by_period, "the burn half never scaled up at all"
        assert max(events_by_period.values()) <= 1

    def test_brief_burst_does_not_ratchet_the_target(self):
        """One burning tick inside a cooldown must not quietly push the
        target toward max — otherwise capacity keeps growing after the
        traffic is gone."""
        fleet = _FakeFleet(size=1)
        fleet.health.update(burn_seen=True, max_burn_rate=2.0)
        clock = _Clock()
        scaler = _scaler(fleet, clock)
        scaler.step()  # up: target 2, cooldown starts
        for _ in range(10):  # burn persists through the cooldown
            clock.tick(2.0)
            scaler.step()
        assert scaler.target == 2  # one step per cooldown, not a race


class TestCapacityConvergence:
    def test_quarantined_slot_reads_as_capacity_loss(self):
        """A crash-looping slot parked by the router is serving capacity
        the fleet no longer has: the autoscaler compensates through the
        standard spawn path."""
        fleet = _FakeFleet(size=2)
        fleet.health.update(quarantined=1)
        scaler = _scaler(fleet, _Clock())
        decision = scaler.step()
        assert decision["action"] == "up"
        assert decision["reason"] == "capacity_loss"
        assert len(fleet.adds) == 1

    def test_warm_spares_ride_above_target(self):
        fleet = _FakeFleet(size=1)
        clock = _Clock()
        scaler = _scaler(fleet, clock, warm_spares=1)
        decision = scaler.step()
        assert decision["action"] == "up"
        assert decision["reason"] == "capacity_loss"
        assert fleet.size == 2  # target 1 + spare 1
        # a long idle stretch never eats the spare
        for _ in range(40):
            clock.tick(2.0)
            scaler.step()
        assert fleet.removes == []
        assert fleet.size == 2

    def test_router_decline_is_a_counted_block_and_retried(self):
        fleet = _FakeFleet(size=1)
        fleet.health.update(burn_seen=True, max_burn_rate=2.0)
        fleet.decline = True  # a rolling deploy holds the fleet
        clock = _Clock()
        scaler = _scaler(fleet, clock)
        decision = scaler.step()
        assert decision["action"] == "hold"
        assert "router_busy" in decision["blocked"]
        assert fleet.adds == []
        fleet.decline = False  # the roll finished; no new trigger needed
        clock.tick(2.0)
        assert scaler.step()["action"] == "up"
        assert len(fleet.adds) == 1


class TestRealCollaborators:
    def test_slo_monitor_thin_window_never_reads_as_safe(self):
        """Satellite 3, end-to-end half: a REAL ``SLOMonitor`` fed fewer
        than ``min_arrivals`` judges nothing (``burning() == {}``) —
        consumed by the autoscaler that absence must block the shrink,
        not permit it."""
        mon = SLOMonitor(window=30.0, p99_ms=50.0, min_arrivals=10)
        obs.counter_add("serving.requests", 3)  # a thin trickle
        mon.sample_once()
        assert mon.burning() == {}  # under min_arrivals: no judgment
        fleet = _FakeFleet(size=2)
        clock = _Clock()
        scaler = _scaler(fleet, clock)
        decision = None
        for _ in range(17):
            burning = mon.burning()
            fleet.health.update(
                burn_seen=bool(burning),
                max_burn_rate=max(burning.values()) if burning else 0.0,
            )
            fleet.health["requests"] += 5.0
            decision = scaler.step()
            clock.tick(2.0)
        assert fleet.removes == []
        assert "no_burn_signal" in decision["blocked"]

    def test_broken_probe_on_a_real_router_blocks_shrink(self):
        """Fail-closed across the real boundary: a replica whose
        ``/readyz`` probe errors (the readiness plane's ``probe_error``
        verdict) surfaces through ``fleet_health`` as ``probe_suspect``
        and vetoes the scale-down."""

        class _Client:
            def __init__(self, probe_result):
                self._probe = probe_result

            def submit(self, table, deadline_ms=None, timeout_s=120.0):
                return ServeResult(table=table, quarantine={},
                                   version="v1")

            def deploy(self, path, version, timeout_s=600.0):
                return version

            def probe(self, timeout_s=2.0, depth=True):
                out = dict(self._probe)
                if depth:
                    out["queue_depth"] = 0.0
                return out

        clients = {
            "replica-0-g1": _Client({"ready": True, "reasons": []}),
            # the broken-probe replica: /readyz fail-closed verdict
            "replica-1-g2": _Client({"ready": False,
                                     "reasons": ["probe_error"]}),
        }
        router = ReplicaRouter(
            "/nonexistent", replicas=2, poll_ms=600_000.0,
            replica_factory=lambda name, p, v: (clients[name], None))
        try:
            clock = _Clock()
            scaler = _scaler(router, clock)
            decision = None
            for _ in range(17):
                decision = scaler.step()
                clock.tick(2.0)
            assert router.fleet_size() == 2  # nothing was removed
            assert "probe_error" in decision["blocked"]
        finally:
            router.shutdown()


class TestObservability:
    def test_statusz_section_registers_and_unregisters(self):
        fleet = _FakeFleet(size=1)
        scaler = _scaler(fleet, _Clock())
        scaler.start()
        try:
            section = telemetry.status_snapshot()["autoscaler"]
            assert section["target"] == 1
            assert section["bounds"] == [1, 4]
            assert "in_cooldown" in section
        finally:
            scaler.stop()
        assert "autoscaler" not in telemetry.status_snapshot()

    def test_decisions_are_counted_and_recorded(self, obs_on):
        from flink_ml_tpu.obs import flight
        from flink_ml_tpu.obs.registry import registry

        fleet = _FakeFleet(size=1)
        fleet.health.update(burn_seen=True, max_burn_rate=2.0)
        scaler = _scaler(fleet, _Clock())
        ups_before = registry().counter("autoscaler.scale_ups")
        scaler.step()
        assert registry().counter("autoscaler.scale_ups") == \
            ups_before + 1
        events = [e for e in flight.events()
                  if e.get("kind") == "autoscaler.scale"]
        assert events
        latest = events[-1]
        assert latest["direction"] == "up"
        assert latest["reason"] == "slo_burn"
        # the flight event carries the triggering signal snapshot
        # (the ring stores nested payloads in repr form)
        assert "'burn': 2.0" in str(latest["signal"])
        assert scaler.stats()["scale_ups"] == 1

    def test_control_loop_runs_and_stops(self):
        """The threaded path: a real start() loop against a burning
        fleet acts within a few ticks, then stop() joins cleanly."""
        fleet = _FakeFleet(size=1)
        fleet.health.update(burn_seen=True, max_burn_rate=2.0)
        with FleetAutoscaler(fleet, min_replicas=1, max_replicas=2,
                             window_s=10.0, cooldown_s=0.1,
                             tick_s=0.02) as scaler:
            deadline = time.monotonic() + WAIT
            while time.monotonic() < deadline:
                if fleet.adds:
                    break
                time.sleep(0.01)
        assert fleet.adds  # the loop observed, decided, and acted
        assert scaler.target == 2
