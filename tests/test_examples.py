"""Example end-to-end fixture tests — the ITCase analog (SURVEY.md §4):
run each example's main() and compare its behavior against expected
characteristics (seeded, so deterministic)."""

import io
import re
import sys
from contextlib import redirect_stdout

import numpy as np


def run_main(module, argv=None):
    old_argv = sys.argv
    sys.argv = [module.__name__] + (argv or [])
    buf = io.StringIO()
    try:
        with redirect_stdout(buf):
            module.main()
    finally:
        sys.argv = old_argv
    return buf.getvalue()


class TestLinearRegressionExample:
    def test_fits_the_reference_line(self):
        from examples import linear_regression

        out = run_main(linear_regression, ["--iterations", "300"])
        # dataset is y = 2x + 1; the example prints the fitted line
        m = re.search(r"fitted: y = ([-\d.]+) \+ ([-\d.]+) \* x", out)
        assert m, out[:200]
        theta0, theta1 = float(m.group(1)), float(m.group(2))
        assert abs(theta1 - 2.0) < 0.1
        # per-point table printed like the reference's result.print()
        assert out.count("pred=") == 21

    def test_predictions_track_labels(self):
        from examples import linear_regression

        out = run_main(linear_regression, ["--iterations", "300"])
        rows = re.findall(r"y=\s*([-\d.]+)\s+pred=\s*([-\d.]+)", out)
        assert len(rows) == 21
        err = [abs(float(y) - float(p)) for y, p in rows]
        assert np.mean(err) < 1.5


class TestIncrementalLearningExample:
    def test_streaming_topology_runs(self):
        from examples import incremental_learning

        out = run_main(incremental_learning)
        m = re.search(r"windows fired: (\d+)", out)
        assert m and int(m.group(1)) == 20  # 2000 records / 100-per-window
        m = re.search(r"accuracy ([\d.]+)", out)
        assert m and float(m.group(1)) > 0.9


class TestOnlineServingExample:
    def test_concurrent_traffic_with_hot_swap(self):
        from examples import online_serving

        out = run_main(online_serving, ["--requests", "40", "--threads", "4"])
        m = re.search(r"served (\d+) requests \((\d+) rows\)", out)
        assert m and int(m.group(1)) == 40, out[:400]
        m = re.search(r"versions served: \['v1', 'v2'\]; failed requests: (\d+)", out)
        assert m, out
        assert int(m.group(1)) == 0  # hot swap drops nothing
        m = re.search(r"into (\d+) dispatch batches \(swaps: 1\)", out)
        assert m, out
        assert int(m.group(1)) < 40  # genuinely coalesced
        assert re.search(r"p99 [\d.]+ ms", out)


class TestRouterServingExample:
    def test_fleet_deploy_and_kill_without_failures(self):
        from examples import router_serving

        out = run_main(router_serving, ["--requests", "45", "--threads", "4"])
        m = re.search(r"fleet up: 3/3 replicas ready", out)
        assert m, out[:400]
        m = re.search(r"served (\d+) requests \((\d+) rows\)", out)
        assert m and int(m.group(1)) == 45, out
        m = re.search(
            r"rolling deploy: 3/3 replicas on v2; versions served: "
            r"\['v1', 'v2'\]; failed requests: (\d+)", out)
        assert m, out
        assert int(m.group(1)) == 0  # deploy + kill drop nothing
        m = re.search(r"fleet back to 3/3 ready \(deaths: 1, "
                      r"respawns: 1", out)
        assert m, out
        assert re.search(r"p99 [\d.]+ ms", out)


class TestOutOfCoreExample:
    def test_streams_part_files_and_recovers_direction(self):
        from examples import out_of_core_training

        out = run_main(
            out_of_core_training, ["--rows", "20000", "--chunk-rows", "2048"]
        )
        assert "host residency capped at 2048 rows/chunk" in out
        fitted = re.search(r"fitted \(rescaled\): \[(.*)\]", out)
        assert fitted, out
        w = np.array([float(v) for v in fitted.group(1).split()])
        truth = re.search(r"true weights:\s+\[(.*)\]", out)
        assert truth, out
        true_w = np.array([float(v) for v in truth.group(1).split()])
        # logistic loss recovers the direction of the separating hyperplane
        # (the example's data comes from the seeded generator script)
        np.testing.assert_allclose(w, true_w, atol=0.35)
        assert re.search(r"throughput: \d+ samples/sec", out)
