"""Pallas-fused serving chain (ops/pallas_kernels.serve_chain) — kernel
parity, the planner's Pallas hot path, low-precision inference, and the
bundled/donated train-step dispatch (ISSUE 17).

Every kernel test here runs in INTERPRET mode on the CPU mesh — the
serve-chain kernel deliberately avoids the vma plumbing that gates the
older grad kernels, so no environment skip applies.  The contract under
test: the Pallas path returns bit-identical discrete predictions and
quarantine side-tables to the XLA fused path, affine stages bit-exact,
scores inside float tolerance; anything ineligible (csr, kNN, int8) falls
back to the XLA program and counts a ``fused.pallas_fallbacks``.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from flink_ml_tpu import obs
from flink_ml_tpu.api.pipeline import Pipeline
from flink_ml_tpu.common import fused
from flink_ml_tpu.lib import Knn, LogisticRegression
from flink_ml_tpu.lib.feature import MinMaxScaler, StandardScaler
from flink_ml_tpu.ops.pallas_kernels import SERVE_CHAIN_OPS, serve_chain
from flink_ml_tpu.parallel.mesh import default_mesh
from flink_ml_tpu.serve import quarantine
from flink_ml_tpu.table.schema import DataTypes, Schema
from flink_ml_tpu.table.table import Table
from flink_ml_tpu.utils.environment import MLEnvironmentFactory

N, D = 1024, 6
D_PAD = 128  # serve_chain pads the lane axis to the 128 multiple
SCHEMA = Schema.of(("features", DataTypes.DENSE_VECTOR), ("label", "double"))


@pytest.fixture
def dense_table():
    rng = np.random.RandomState(7)
    X = (2.0 * rng.randn(N, D) + 1.0).astype(np.float32)
    w = rng.randn(D).astype(np.float32)
    y = ((X - 1.0) @ w > 0).astype(np.float64)
    return Table.from_columns(SCHEMA, {"features": X, "label": y})


@pytest.fixture
def obs_on():
    obs.enable()
    obs.reset()
    yield
    obs.reset()
    obs.disable()


@pytest.fixture
def batch_size():
    env = MLEnvironmentFactory.get_default()
    old = env.default_batch_size
    env.default_batch_size = 256
    yield 256
    env.default_batch_size = old


def _pad(X):
    out = np.zeros((X.shape[0], D_PAD), np.float32)
    out[:, : X.shape[1]] = X
    return out


def _stage_params(rng, kinds, d):
    params = []
    for kind in kinds:
        if kind == "glm_score":
            params.append((rng.randn(d).astype(np.float32),
                           np.float32(rng.randn())))
        else:
            params.append((rng.randn(d).astype(np.float32),
                           rng.randn(d).astype(np.float32)))
    return params


def _ref_chain(kinds, fetch, X, params):
    """The chain as ONE jitted XLA program, padded exactly like the kernel
    (zero pads are exact through every stage), outputs sliced like the
    caller.  Jitted, not eager numpy: the parity contract is kernel == XLA
    elementwise, and compiled XLA fuses ``h * a + b`` into an FMA that a
    separate mul/add rounds differently."""
    padded = []
    for kind, (pa, pb) in zip(kinds, params):
        if kind == "glm_score":
            w = np.zeros((D_PAD, 1), np.float32)
            w[: pa.size, 0] = pa
            padded.append((w, np.float32(pb)))
        else:
            a = np.zeros((D_PAD,), np.float32)
            a[: pa.size] = pa
            b = np.zeros((D_PAD,), np.float32)
            b[: pb.size] = pb
            padded.append((a, b))

    @jax.jit
    def chain(h, stage_params):
        outs = []
        for kind, (pa, pb), keep in zip(kinds, stage_params, fetch):
            if kind == "glm_score":
                h = h @ pa + pb
            else:
                h = (h - pa) * pb if kind == "affine_sub_mul" else h * pa + pb
            if keep:
                outs.append(h)
        return outs

    return [np.asarray(o) for o in chain(jnp.asarray(_pad(X)), padded)]


class TestServeChainKernel:
    @pytest.mark.parametrize("kind", SERVE_CHAIN_OPS)
    def test_single_stage_matches_reference(self, kind):
        rng = np.random.RandomState(3)
        X = rng.randn(256, D).astype(np.float32)
        params = _stage_params(rng, [kind], D)
        fn = serve_chain([kind], [True], D)
        (got,) = fn(jnp.asarray(_pad(X)), tuple(map(jnp.asarray, params[0])))
        (ref,) = _ref_chain([kind], [True], X, params)
        got = np.asarray(got)
        if kind == "glm_score":
            np.testing.assert_allclose(got[:, 0], ref[:, 0],
                                       rtol=1e-5, atol=1e-6)
        else:
            # affine stages are bit-exact: same elementwise f32 ops
            np.testing.assert_array_equal(got, ref)

    def test_three_stage_chain_matches_reference(self):
        rng = np.random.RandomState(4)
        X = rng.randn(512, D).astype(np.float32)
        kinds = ["affine_sub_mul", "affine_mul_add", "glm_score"]
        fetch = [True, True, True]
        params = _stage_params(rng, kinds, D)
        fn = serve_chain(kinds, fetch, D)
        got = fn(jnp.asarray(_pad(X)),
                 *[tuple(map(jnp.asarray, p)) for p in params])
        refs = _ref_chain(kinds, fetch, X, params)
        np.testing.assert_array_equal(np.asarray(got[0]), refs[0])
        np.testing.assert_array_equal(np.asarray(got[1]), refs[1])
        np.testing.assert_allclose(np.asarray(got[2])[:, 0], refs[2][:, 0],
                                   rtol=1e-5, atol=1e-6)

    def test_zero_padding_is_exact(self):
        """Pad lanes [d:] stay exactly zero through affine stages — the
        guarantee that lets the planner slice [:, :d] without a mask."""
        rng = np.random.RandomState(5)
        X = rng.randn(64, D).astype(np.float32)
        kinds = ["affine_sub_mul", "affine_mul_add"]
        params = _stage_params(rng, kinds, D)
        fn = serve_chain(kinds, [True, True], D)
        got = fn(jnp.asarray(_pad(X)),
                 *[tuple(map(jnp.asarray, p)) for p in params])
        for o in got:
            assert not np.asarray(o)[:, D:].any()

    @pytest.mark.parametrize("n", [1, 5, 7, 96, 250, 1000])
    def test_ragged_row_counts(self, n):
        """Bisection slices and tails hit row counts with gcd(n, tile) < 8;
        the kernel pads rows to a legal tile and slices back."""
        rng = np.random.RandomState(n)
        X = rng.randn(n, D).astype(np.float32)
        kinds = ["affine_sub_mul", "glm_score"]
        params = _stage_params(rng, kinds, D)
        fn = serve_chain(kinds, [False, True], D)
        (got,) = fn(jnp.asarray(_pad(X)),
                    *[tuple(map(jnp.asarray, p)) for p in params])
        (ref,) = _ref_chain(kinds, [False, True], X, params)
        assert got.shape[0] == n
        np.testing.assert_allclose(np.asarray(got)[:, 0], ref[:, 0],
                                   rtol=1e-5, atol=1e-6)

    def test_masked_variant_flags_and_zeroes_adversarial_rows(self):
        """NaN, +/-Inf rows mask to 0 and are zeroed before the chain;
        denormal (tiny but finite) rows stay servable and exact."""
        rng = np.random.RandomState(6)
        X = rng.randn(40, D).astype(np.float32)
        X[3, 0] = np.nan
        X[11, 2] = np.inf
        X[17, 5] = -np.inf
        X[23] = np.float32(1e-42)  # denormal: finite, must NOT quarantine
        kinds = ["affine_sub_mul", "glm_score"]
        params = _stage_params(rng, kinds, D)
        fn = serve_chain(kinds, [False, True], D, masked=True)
        mask, score = fn(jnp.asarray(_pad(X)),
                         *[tuple(map(jnp.asarray, p)) for p in params])
        mask = np.asarray(mask)[:, 0] > 0
        bad = {3, 11, 17}
        assert set(np.nonzero(~mask)[0]) == bad
        assert mask[23]
        Xz = X.copy()
        Xz[list(bad)] = 0.0
        (ref,) = _ref_chain(kinds, [False, True], Xz, params)
        np.testing.assert_allclose(np.asarray(score)[:, 0], ref[:, 0],
                                   rtol=1e-5, atol=1e-6)

    @pytest.mark.parametrize("width", [1, 2, 4, 8])
    def test_shard_map_parity_across_mesh_widths(self, width):
        """The collective-free kernel composes inside shard_map row
        sharding: any mesh width returns the width-1 answer bitwise."""
        from jax.sharding import PartitionSpec as P

        from flink_ml_tpu.parallel.collectives import shard_map

        rng = np.random.RandomState(8)
        X = rng.randn(256, D).astype(np.float32)
        kinds = ["affine_sub_mul", "affine_mul_add", "glm_score"]
        params = _stage_params(rng, kinds, D)
        fn = serve_chain(kinds, [False, False, True], D)
        jp = [tuple(map(jnp.asarray, p)) for p in params]
        (base,) = fn(jnp.asarray(_pad(X)), *jp)
        mesh = default_mesh(devices=jax.devices()[:width])
        flat = [a for p in jp for a in p]

        def local(x, *margs):
            pairs = [tuple(margs[i : i + 2]) for i in range(0, len(margs), 2)]
            (out,) = fn(x, *pairs)
            return out

        sharded = shard_map(
            local, mesh,
            in_specs=(P("data"),) + (P(),) * len(flat),
            out_specs=P("data"),
            check_vma=getattr(fn, "shard_map_check_vma", True),
        )
        got = sharded(jnp.asarray(_pad(X)), *flat)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(base))

    def test_rejects_unknown_op(self):
        with pytest.raises(ValueError):
            serve_chain(["affine_sub_mul", "relu"], [True, True], D)


def _transform(model, table, monkeypatch, *, pallas, precision="f32"):
    monkeypatch.setenv("FMT_FUSE_TRANSFORM", "1")
    monkeypatch.setenv("FMT_SERVE_PALLAS", "1" if pallas else "0")
    monkeypatch.setenv("FMT_SERVE_PRECISION", precision)
    (out,) = model.transform(table)
    return out


def _lr_pipeline(dense_table, max_iter=3, lr=0.5):
    return Pipeline([
        StandardScaler().set_selected_col("features"),
        MinMaxScaler().set_selected_col("features"),
        LogisticRegression().set_vector_col("features")
        .set_label_col("label").set_prediction_col("pred")
        .set_prediction_detail_col("proba").set_max_iter(max_iter)
        .set_learning_rate(lr),
    ]).fit(dense_table)


class TestPallasServePath:
    def test_pipeline_parity_and_one_kernel_per_dispatch(
            self, dense_table, obs_on, batch_size, monkeypatch):
        """The acceptance shape: with FMT_SERVE_PALLAS=1 every fused
        dispatch is exactly ONE Pallas launch, predictions bit-identical
        to the XLA chain, floats inside tolerance, zero fallbacks."""
        model = _lr_pipeline(dense_table)
        xla = _transform(model, dense_table, monkeypatch, pallas=False)
        obs.reset()
        pal = _transform(model, dense_table, monkeypatch, pallas=True)
        c = obs.registry().snapshot()["counters"]
        assert c.get("fused.pallas_dispatches") == \
            c.get("pipeline.fused_dispatches") == -(-N // batch_size)
        assert "fused.pallas_fallbacks" not in c
        np.testing.assert_array_equal(
            np.asarray(xla.col("pred")), np.asarray(pal.col("pred")))
        np.testing.assert_allclose(
            np.asarray(xla.col("proba"), dtype=np.float64),
            np.asarray(pal.col("proba"), dtype=np.float64),
            rtol=1e-5, atol=1e-7)
        np.testing.assert_array_equal(
            np.asarray(xla.features_dense("features")),
            np.asarray(pal.features_dense("features")))

    def test_quarantine_side_table_parity(self, dense_table, obs_on,
                                          batch_size, monkeypatch):
        """The deferred in-kernel scan yields the SAME side-table (rows,
        reasons) and the same survivors as the XLA path's host scan."""
        X = np.asarray(dense_table.features_dense("features")).copy()
        for r, c in ((3, 0), (257, 2), (511, 5), (900, 1)):
            X[r, c] = np.nan if r % 2 else np.inf
        bad = Table.from_columns(SCHEMA, {
            "features": X, "label": dense_table.col("label")})
        model = _lr_pipeline(dense_table)

        def run(pallas):
            quarantine.reset()
            out = _transform(model, bad, monkeypatch, pallas=pallas)
            qt = quarantine.quarantine_table("StandardScalerModel")
            rows = sorted(int(r) for r in qt.col(quarantine.QUARANTINE_ROW_COL))
            reasons = set(qt.col(quarantine.QUARANTINE_REASON_COL))
            quarantine.reset()
            return out, rows, reasons

        xla, xrows, xreasons = run(False)
        pal, prows, preasons = run(True)
        assert prows == xrows == [3, 257, 511, 900]
        assert preasons == xreasons == {"nan_inf"}
        assert pal.num_rows() == xla.num_rows() == N - 4
        np.testing.assert_array_equal(
            np.asarray(xla.col("pred")), np.asarray(pal.col("pred")))

    def test_ineligible_plan_falls_back_and_counts(self, dense_table,
                                                   obs_on, monkeypatch):
        """kNN's kernel has no pallas_op: the knob stays honored by
        falling back to the XLA program (identical output) and counting
        a fused.pallas_fallbacks so --check can flag a degraded fleet."""
        model = Pipeline([
            StandardScaler().set_selected_col("features"),
            Knn().set_vector_col("features").set_label_col("label")
            .set_k(3).set_prediction_col("p"),
        ]).fit(dense_table)
        off = _transform(model, dense_table, monkeypatch, pallas=False)
        obs.reset()
        on = _transform(model, dense_table, monkeypatch, pallas=True)
        c = obs.registry().snapshot()["counters"]
        assert c.get("fused.pallas_fallbacks", 0) >= 1
        assert "fused.pallas_dispatches" not in c
        np.testing.assert_array_equal(
            np.asarray(off.col("p")), np.asarray(on.col("p")))

    def test_compile_ledger_records_pallas_prefix(self, dense_table, obs_on,
                                                  tmp_path, monkeypatch):
        from flink_ml_tpu.obs import trace

        monkeypatch.setenv("FMT_OBS_REPORTS", str(tmp_path / "reports"))
        trace.reset()
        fused.reset_compile_keys()
        model = _lr_pipeline(dense_table)
        _transform(model, dense_table, monkeypatch, pallas=True)
        import json

        with open(trace.compile_ledger_path()) as f:
            kernels = [json.loads(line)["kernel"] for line in f]
        assert any(k.startswith("pallas:") for k in kernels)
        trace.reset()


def _margin_table(model, table, monkeypatch, band=0.02):
    """Rows whose f32 probability clears the decision boundary by more
    than the documented low-precision tolerance band — the set on which
    discrete predictions are CONTRACTUALLY bit-identical (a row sitting
    inside the band may legitimately flip under quantization)."""
    f32 = _transform(model, table, monkeypatch, pallas=False)
    proba = np.asarray(f32.col("proba"), dtype=np.float64)
    keep = np.abs(proba - 0.5) > band
    # the strong fixture fit separates the classes well — most rows clear
    # the band, so the parity check below has real coverage
    assert keep.sum() > N * 0.85
    return table.filter_rows(keep)


class TestServePrecision:
    def test_bf16_discrete_parity(self, dense_table, obs_on, batch_size,
                                  monkeypatch):
        model = _lr_pipeline(dense_table, max_iter=50, lr=5.0)
        eval_t = _margin_table(model, dense_table, monkeypatch)
        f32 = _transform(model, eval_t, monkeypatch, pallas=False)
        obs.reset()
        bf16 = _transform(model, eval_t, monkeypatch, pallas=False,
                          precision="bf16")
        assert obs.registry().snapshot()["gauges"]["serve.precision"] == 16
        np.testing.assert_array_equal(
            np.asarray(f32.col("pred")), np.asarray(bf16.col("pred")))
        np.testing.assert_allclose(
            np.asarray(f32.col("proba"), dtype=np.float64),
            np.asarray(bf16.col("proba"), dtype=np.float64),
            rtol=2e-2, atol=2e-2)

    def test_bf16_rides_the_pallas_kernel(self, dense_table, obs_on,
                                          batch_size, monkeypatch):
        model = _lr_pipeline(dense_table, max_iter=50, lr=5.0)
        eval_t = _margin_table(model, dense_table, monkeypatch)
        f32 = _transform(model, eval_t, monkeypatch, pallas=True)
        obs.reset()
        bf16 = _transform(model, eval_t, monkeypatch, pallas=True,
                          precision="bf16")
        c = obs.registry().snapshot()["counters"]
        assert c.get("fused.pallas_dispatches") == \
            -(-eval_t.num_rows() // batch_size)
        np.testing.assert_array_equal(
            np.asarray(f32.col("pred")), np.asarray(bf16.col("pred")))

    def test_int8_discrete_parity_forces_xla(self, dense_table, obs_on,
                                             batch_size, monkeypatch):
        """int8 can't represent NaN: the planner keeps the XLA program
        (host-side validation) even with the Pallas knob on."""
        model = _lr_pipeline(dense_table, max_iter=50, lr=5.0)
        eval_t = _margin_table(model, dense_table, monkeypatch)
        f32 = _transform(model, eval_t, monkeypatch, pallas=False)
        obs.reset()
        i8 = _transform(model, eval_t, monkeypatch, pallas=True,
                        precision="int8")
        snap = obs.registry().snapshot()
        assert snap["gauges"]["serve.precision"] == 8
        assert "fused.pallas_dispatches" not in snap["counters"]
        assert snap["counters"].get("fused.pallas_fallbacks", 0) >= 1
        np.testing.assert_array_equal(
            np.asarray(f32.col("pred")), np.asarray(i8.col("pred")))
        np.testing.assert_allclose(
            np.asarray(f32.col("proba"), dtype=np.float64),
            np.asarray(i8.col("proba"), dtype=np.float64),
            rtol=5e-2, atol=5e-2)


class TestBundledTrainDispatch:
    def _fit_ingredients(self):
        from flink_ml_tpu.lib import common as C
        from flink_ml_tpu.lib.classification import _log_loss_grads

        rng = np.random.RandomState(0)
        X = rng.randn(N, D).astype(np.float32)
        w = rng.randn(D)
        y = (X @ w > 0).astype(np.float32)
        stack = C.pack_minibatches(X, y, 1, 128)
        return C, _log_loss_grads(True), stack

    @pytest.mark.parametrize("width", [1, 4, 8])
    def test_bundled_fetch_bitwise_parity(self, width):
        """The single-buffer fetch program returns bit-identical params,
        losses, epochs, and delta to the 4-tuple + fetch_flat path."""
        C, grad_fn, stack = self._fit_ingredients()
        mesh = default_mesh(devices=jax.devices()[:width])
        init = (np.zeros(D), np.zeros(()))
        batch = C._combined_view_memo(stack)
        plain = C._run_fused_train(
            C.make_glm_train_fn(grad_fn, mesh, 0.5, 0.0, 12, 0.0),
            init, batch, mesh, n_rows=N)
        bund = C._run_fused_train(
            C.make_glm_train_fn(grad_fn, mesh, 0.5, 0.0, 12, 0.0,
                                bundle=True),
            init, batch, mesh, n_rows=N)
        for a, b in zip(jax.tree_util.tree_leaves(plain.params),
                        jax.tree_util.tree_leaves(bund.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert plain.epochs == bund.epochs
        assert plain.losses == bund.losses
        assert plain.final_delta == bund.final_delta

    @pytest.mark.filterwarnings("ignore:Some donated buffers")
    def test_donated_batch_params_bitwise_equal(self):
        """A donating program (inert on CPU, hence the warning filter)
        places a fresh non-pooled batch and returns the same params."""
        C, grad_fn, stack = self._fit_ingredients()
        mesh = default_mesh(devices=jax.devices()[:1])
        batch = C._combined_view_memo(stack)
        don_fn = C.make_glm_train_fn(grad_fn, mesh, 0.5, 0.0, 12, 0.0,
                                     bundle=True, donate_batch=True)
        assert don_fn.bundle_fetch and don_fn.donates_batch
        assert don_fn.loss_hist_len == 12
        don = C._run_fused_train(don_fn, (np.zeros(D), np.zeros(())),
                                 batch, mesh, n_rows=N)
        ref = C._run_fused_train(
            C.make_glm_train_fn(grad_fn, mesh, 0.5, 0.0, 12, 0.0,
                                bundle=True),
            (np.zeros(D), np.zeros(())), batch, mesh, n_rows=N)
        for a, b in zip(jax.tree_util.tree_leaves(don.params),
                        jax.tree_util.tree_leaves(ref.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert don.losses == ref.losses

    def test_direct_caller_keeps_tuple_contract(self):
        """diagnose_perf and the graft entry unpack the raw 4-tuple: the
        default (unbundled) build must keep returning it."""
        from flink_ml_tpu.parallel.mesh import replicate, shard_batch

        C, grad_fn, stack = self._fit_ingredients()
        mesh = default_mesh(devices=jax.devices()[:1])
        fn = C.make_glm_train_fn(grad_fn, mesh, 0.5, 0.0, 3, 0.0)
        out = fn(replicate(mesh, (jnp.zeros(D), jnp.zeros(()))),
                 shard_batch(mesh, C._combined_view_memo(stack)))
        assert isinstance(out, tuple) and len(out) == 4
        assert not getattr(fn, "bundle_fetch", False)
