"""Mesh-parallel inference: Model.transform shards query rows over the
'data' axis (the TPU analog of the reference running ModelMapperAdapter at
operator parallelism, ModelMapperAdapter.java:53-61).  These tests assert the
sharded apply is numerically identical to the single-device apply — same
rows, same model, 1 vs 8 devices."""

import contextlib

import jax
import numpy as np

from flink_ml_tpu.lib import KMeans, Knn, LogisticRegression
from flink_ml_tpu.ops.vector import DenseVector
from flink_ml_tpu.parallel.mesh import create_mesh, data_parallel_size
from flink_ml_tpu.table.schema import DataTypes, Schema
from flink_ml_tpu.table.table import Table
from flink_ml_tpu.utils.environment import MLEnvironmentFactory


@contextlib.contextmanager
def mesh_of(n_devices):
    env = MLEnvironmentFactory.get_default()
    old = env.get_mesh()
    env.set_mesh(create_mesh({"data": n_devices}, jax.devices()[:n_devices]))
    try:
        yield
    finally:
        env.set_mesh(old)


SCHEMA = Schema.of(("features", DataTypes.DENSE_VECTOR), ("label", "double"))


def _table(n=300, d=6, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d)
    y = (X @ rng.randn(d) > 0).astype(np.float64)
    return Table.from_columns(
        SCHEMA, {"features": [DenseVector(r) for r in X], "label": y}
    )


def _transform_cols(model, table, *cols):
    out = model.transform(table)[0]
    return [np.asarray(out.col(c)) for c in cols]


class TestShardedTransformMatchesSingleDevice:
    def test_logistic_regression(self):
        t = _table()
        model = (
            LogisticRegression().set_vector_col("features")
            .set_label_col("label").set_prediction_col("pred")
            .set_prediction_detail_col("prob").set_learning_rate(0.5)
            .set_max_iter(5).fit(t)
        )
        with mesh_of(8):
            assert data_parallel_size(MLEnvironmentFactory.get_default().get_mesh()) == 8
            p8, d8 = _transform_cols(model, t, "pred", "prob")
        with mesh_of(1):
            p1, d1 = _transform_cols(model, t, "pred", "prob")
        np.testing.assert_array_equal(p8, p1)
        np.testing.assert_array_equal(d8, d1)

    def test_kmeans(self):
        t = _table(240, 5, seed=1)
        model = (
            KMeans().set_vector_col("features").set_prediction_col("cluster")
            .set_prediction_detail_col("dist").set_k(7).set_max_iter(5)
            .set_seed(3).fit(t)
        )
        with mesh_of(8):
            c8, d8 = _transform_cols(model, t, "cluster", "dist")
        with mesh_of(1):
            c1, d1 = _transform_cols(model, t, "cluster", "dist")
        np.testing.assert_array_equal(c8, c1)
        np.testing.assert_array_equal(d8, d1)

    def test_knn(self):
        t = _table(200, 4, seed=2)
        q = _table(77, 4, seed=5)  # row count not a multiple of 8
        model = (
            Knn().set_vector_col("features").set_label_col("label")
            .set_prediction_col("pred").set_prediction_detail_col("dist")
            .set_k(5).fit(t)
        )
        with mesh_of(8):
            p8, d8 = _transform_cols(model, q, "pred", "dist")
        with mesh_of(1):
            p1, d1 = _transform_cols(model, q, "pred", "dist")
        np.testing.assert_array_equal(p8, p1)
        np.testing.assert_array_equal(d8, d1)


class TestShardedReferenceSetKnn:
    """shardModelData=True: the reference set shards over the data axis
    (each device holds 1/n of it) and per-shard top-k candidates merge via
    all_gather — must match the replicated path bit-for-bit."""

    def _model(self, t, shard):
        return (
            Knn().set_vector_col("features").set_label_col("label")
            .set_prediction_col("pred").set_prediction_detail_col("dist")
            .set_k(5).set_shard_model_data(shard).fit(t)
        )

    def test_matches_replicated_path(self):
        t = _table(500, 4, seed=7)
        q = _table(131, 4, seed=9)
        with mesh_of(8):
            ps, ds = _transform_cols(self._model(t, True), q, "pred", "dist")
            pr, dr = _transform_cols(self._model(t, False), q, "pred", "dist")
        np.testing.assert_array_equal(ps, pr)
        np.testing.assert_array_equal(ds, dr)

    def test_model_actually_shards_over_devices(self):
        t = _table(512, 4, seed=3)
        q = _table(32, 4, seed=4)
        model = self._model(t, True)
        with mesh_of(8):
            out = model.transform(q)[0]
            assert out.num_rows() == 32
            mapper = model._mapper_cache  # loaded by transform
            shards = mapper._xt.addressable_shards
            assert len(shards) == 8
            total = mapper._xt.shape[0]
            for s in shards:
                assert s.data.shape[0] == total // 8  # 1/8 residency per device

    def test_exact_distance_ties_match_across_paths(self):
        """Duplicate reference rows (exact distance ties) spanning shards:
        both paths select canonically by (distance, global row index) — the
        copy labels are laid out so any non-canonical selection flips the
        majority vote (copies 0-2 of each point vote 1, copies 3-7 vote 0;
        canonical top-5 = copies 0-4 -> vote 1)."""
        rng = np.random.RandomState(11)
        distinct = rng.randn(64, 4) * 3
        X = np.tile(distinct, (8, 1))  # copy i of point j at index i*64 + j
        copy = np.repeat(np.arange(8), 64)
        y = (copy < 3).astype(np.float64)
        t = Table.from_columns(
            SCHEMA, {"features": [DenseVector(r) for r in X], "label": y}
        )
        q = Table.from_columns(
            SCHEMA,
            {"features": [DenseVector(r) for r in distinct],
             "label": np.zeros(len(distinct))},
        )
        with mesh_of(8):
            ps, ds = _transform_cols(self._model(t, True), q, "pred", "dist")
            pr, dr = _transform_cols(self._model(t, False), q, "pred", "dist")
        np.testing.assert_array_equal(ps, pr)
        np.testing.assert_array_equal(ds, dr)
        np.testing.assert_array_equal(ps, np.ones(len(distinct)))

    def test_single_device_mesh_falls_back_to_replicated(self):
        t = _table(100, 4, seed=1)
        q = _table(20, 4, seed=2)
        with mesh_of(8):
            p8, _ = _transform_cols(self._model(t, True), q, "pred", "dist")
        with mesh_of(1):
            p1, _ = _transform_cols(self._model(t, True), q, "pred", "dist")
        np.testing.assert_array_equal(p8, p1)

    def test_mesh_change_rebuilds_sharded_model_placement(self):
        """The mapper cache is mesh-keyed: transforming the same model under
        a different mesh must re-place the sharded reference set, not crash
        on mesh-committed buffers."""
        t = _table(256, 4, seed=6)
        q = _table(24, 4, seed=8)
        model = self._model(t, True)
        with mesh_of(8):
            p8, _ = _transform_cols(model, q, "pred", "dist")
        with mesh_of(2):
            p2, _ = _transform_cols(model, q, "pred", "dist")
        np.testing.assert_array_equal(p8, p2)

    def test_sharded_model_streams_inference(self):
        """transform_chunks x shardModelData: chunked scoring against a
        mesh-sharded reference set matches the whole-table transform."""
        from flink_ml_tpu.table.sources import ChunkedTable, CollectionSource

        t = _table(300, 4, seed=12)
        q = _table(90, 4, seed=13)
        model = self._model(t, True)
        with mesh_of(8):
            whole = model.transform(q)[0]
            chunked = ChunkedTable(
                CollectionSource(q.to_rows(), q.schema), chunk_rows=40
            )
            streamed = Table.concat(list(model.transform_chunks(chunked)))
        np.testing.assert_array_equal(
            np.asarray(streamed.col("pred")), np.asarray(whole.col("pred"))
        )
