"""Mesh-parallel inference: Model.transform shards query rows over the
'data' axis (the TPU analog of the reference running ModelMapperAdapter at
operator parallelism, ModelMapperAdapter.java:53-61).  These tests assert the
sharded apply is numerically identical to the single-device apply — same
rows, same model, 1 vs 8 devices."""

import contextlib

import jax
import numpy as np

from flink_ml_tpu.lib import KMeans, Knn, LogisticRegression
from flink_ml_tpu.ops.vector import DenseVector
from flink_ml_tpu.parallel.mesh import create_mesh, data_parallel_size
from flink_ml_tpu.table.schema import DataTypes, Schema
from flink_ml_tpu.table.table import Table
from flink_ml_tpu.utils.environment import MLEnvironmentFactory


@contextlib.contextmanager
def mesh_of(n_devices):
    env = MLEnvironmentFactory.get_default()
    old = env.get_mesh()
    env.set_mesh(create_mesh({"data": n_devices}, jax.devices()[:n_devices]))
    try:
        yield
    finally:
        env.set_mesh(old)


SCHEMA = Schema.of(("features", DataTypes.DENSE_VECTOR), ("label", "double"))


def _table(n=300, d=6, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d)
    y = (X @ rng.randn(d) > 0).astype(np.float64)
    return Table.from_columns(
        SCHEMA, {"features": [DenseVector(r) for r in X], "label": y}
    )


def _transform_cols(model, table, *cols):
    out = model.transform(table)[0]
    return [np.asarray(out.col(c)) for c in cols]


class TestShardedTransformMatchesSingleDevice:
    def test_logistic_regression(self):
        t = _table()
        model = (
            LogisticRegression().set_vector_col("features")
            .set_label_col("label").set_prediction_col("pred")
            .set_prediction_detail_col("prob").set_learning_rate(0.5)
            .set_max_iter(5).fit(t)
        )
        with mesh_of(8):
            assert data_parallel_size(MLEnvironmentFactory.get_default().get_mesh()) == 8
            p8, d8 = _transform_cols(model, t, "pred", "prob")
        with mesh_of(1):
            p1, d1 = _transform_cols(model, t, "pred", "prob")
        np.testing.assert_array_equal(p8, p1)
        np.testing.assert_array_equal(d8, d1)

    def test_kmeans(self):
        t = _table(240, 5, seed=1)
        model = (
            KMeans().set_vector_col("features").set_prediction_col("cluster")
            .set_prediction_detail_col("dist").set_k(7).set_max_iter(5)
            .set_seed(3).fit(t)
        )
        with mesh_of(8):
            c8, d8 = _transform_cols(model, t, "cluster", "dist")
        with mesh_of(1):
            c1, d1 = _transform_cols(model, t, "cluster", "dist")
        np.testing.assert_array_equal(c8, c1)
        np.testing.assert_array_equal(d8, d1)

    def test_knn(self):
        t = _table(200, 4, seed=2)
        q = _table(77, 4, seed=5)  # row count not a multiple of 8
        model = (
            Knn().set_vector_col("features").set_label_col("label")
            .set_prediction_col("pred").set_prediction_detail_col("dist")
            .set_k(5).fit(t)
        )
        with mesh_of(8):
            p8, d8 = _transform_cols(model, q, "pred", "dist")
        with mesh_of(1):
            p1, d1 = _transform_cols(model, q, "pred", "dist")
        np.testing.assert_array_equal(p8, p1)
        np.testing.assert_array_equal(d8, d1)
