"""StandardScaler + true multi-stage Pipeline (VERDICT r3 item 3).

The first concrete feature Transformer: these tests exercise the
transform-forward branch of Pipeline.fit (Pipeline.java:80-94 parity,
api/pipeline.py) with REAL stages — the colname vocabulary
(HasSelectedCol.java:33-47) and OutputColsHelper merge rules
(OutputColsHelper.java:32-52) finally serving a transformer chain ahead of
an estimator.
"""

import numpy as np
import pytest

from flink_ml_tpu.api import Pipeline, PipelineModel, load_stage
from flink_ml_tpu.lib import LogisticRegression, StandardScaler, StandardScalerModel
from flink_ml_tpu.ops.vector import DenseVector
from flink_ml_tpu.table import DataTypes, Schema, Table
from flink_ml_tpu.table.sources import ChunkedTable, CollectionSource

SCHEMA = Schema.of(
    ("id", "double"), ("features", DataTypes.DENSE_VECTOR), ("label", "double")
)


def _data(n=200, d=5, seed=0, scale=None):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d) * (scale if scale is not None else rng.rand(d) * 9 + 1)
    X += rng.randn(d) * 3
    y = (X @ rng.randn(d) > 0).astype(np.float64)
    t = Table.from_columns(
        SCHEMA,
        {"id": np.arange(n, dtype=np.float64), "features": X.copy(), "label": y},
    )
    return t, X, y


def _scaler(**flags):
    s = StandardScaler().set_selected_col("features")
    for k, v in flags.items():
        getattr(s, f"set_{k}")(v)
    return s


class TestStandardScalerFit:
    def test_statistics_match_numpy(self):
        t, X, _ = _data()
        model = _scaler().fit(t)
        (mt,) = model.get_model_data()
        np.testing.assert_allclose(
            mt.features_dense("means")[0], X.mean(axis=0), rtol=1e-5, atol=1e-5
        )
        np.testing.assert_allclose(
            mt.features_dense("stds")[0], X.std(axis=0, ddof=1), rtol=1e-4
        )
        assert float(mt.col("count")[0]) == len(X)

    def test_chunked_fit_matches_materialized(self):
        t, X, y = _data(n=137)
        rows = [(float(i), DenseVector(r), float(lab))
                for i, (r, lab) in enumerate(zip(X, y))]
        chunked = ChunkedTable(CollectionSource(rows, SCHEMA), chunk_rows=16)
        (m_chunk,) = _scaler().fit(chunked).get_model_data()
        (m_full,) = _scaler().fit(t).get_model_data()
        # chunked partial sums round differently in f32: ulp-level agreement
        np.testing.assert_allclose(
            m_chunk.features_dense("means")[0],
            m_full.features_dense("means")[0],
            rtol=1e-5, atol=1e-5,
        )
        np.testing.assert_allclose(
            m_chunk.features_dense("stds")[0],
            m_full.features_dense("stds")[0],
            rtol=1e-5,
        )

    def test_large_mean_precision(self):
        """Regression (r4 review): unshifted f32 sum-of-squares suffered
        catastrophic cancellation — timestamp-scale features (mean ~1.7e9,
        std ~1e4) fitted a std 92x too large.  The pivot-shifted moments
        must stay accurate, chunked or not."""
        rng = np.random.RandomState(42)
        X = 1.7e9 + rng.randn(1000, 3) * np.array([9.9e3, 1.0e4, 5.0e3])
        schema = Schema.of(("features", DataTypes.DENSE_VECTOR),)
        t = Table.from_columns(schema, {"features": X})
        (mt,) = _scaler().fit(t).get_model_data()
        np.testing.assert_allclose(
            mt.features_dense("stds")[0], X.std(axis=0, ddof=1), rtol=1e-3
        )
        np.testing.assert_allclose(
            mt.features_dense("means")[0], X.mean(axis=0), rtol=1e-6
        )
        rows = [(DenseVector(r),) for r in X]
        chunked = ChunkedTable(CollectionSource(rows, schema), chunk_rows=128)
        (mc,) = _scaler().fit(chunked).get_model_data()
        np.testing.assert_allclose(
            mc.features_dense("stds")[0], X.std(axis=0, ddof=1), rtol=1e-3
        )

    def test_empty_input_raises(self):
        t, _, _ = _data()
        with pytest.raises(ValueError, match="empty"):
            _scaler().fit(t.slice_rows(0, 0))


class TestStandardScalerTransform:
    def test_normalizes_to_zero_mean_unit_std(self):
        t, X, _ = _data()
        (out,) = _scaler().fit(t).transform(t)
        Z = out.features_dense("features")
        np.testing.assert_allclose(Z.mean(axis=0), 0.0, atol=1e-4)
        np.testing.assert_allclose(Z.std(axis=0, ddof=1), 1.0, rtol=1e-3)

    def test_overwrites_selected_col_in_place_by_default(self):
        t, _, _ = _data()
        (out,) = _scaler().fit(t).transform(t)
        # OutputColsHelper collision rule: same name, same position
        assert out.schema.field_names == ["id", "features", "label"]
        np.testing.assert_array_equal(out.col("id"), t.col("id"))
        np.testing.assert_array_equal(out.col("label"), t.col("label"))

    def test_output_col_appends(self):
        t, X, _ = _data()
        (out,) = _scaler().set_output_col("scaled").fit(t).transform(t)
        assert out.schema.field_names == ["id", "features", "label", "scaled"]
        np.testing.assert_array_equal(
            out.features_dense("features"), t.features_dense("features")
        )
        Z = out.features_dense("scaled")
        np.testing.assert_allclose(Z.mean(axis=0), 0.0, atol=1e-4)

    def test_reserved_cols_prune(self):
        t, _, _ = _data()
        model = _scaler().set_output_col("scaled").set_reserved_cols(["label"]).fit(t)
        (out,) = model.transform(t)
        assert out.schema.field_names == ["label", "scaled"]

    def test_with_mean_only(self):
        t, X, _ = _data()
        (out,) = _scaler(with_std=False).fit(t).transform(t)
        Z = out.features_dense("features")
        np.testing.assert_allclose(Z.mean(axis=0), 0.0, atol=1e-4)
        np.testing.assert_allclose(Z.std(axis=0), X.std(axis=0), rtol=1e-3)

    def test_with_std_only(self):
        t, X, _ = _data()
        (out,) = _scaler(with_mean=False).fit(t).transform(t)
        Z = out.features_dense("features")
        np.testing.assert_allclose(
            Z.std(axis=0, ddof=1), 1.0, rtol=1e-3
        )
        assert np.abs(Z.mean(axis=0)).max() > 1e-2  # means preserved (off-center data)

    def test_zero_variance_dim_passes_through(self):
        t, X, y = _data()
        Xc = X.copy()
        Xc[:, 2] = 7.0
        tc = Table.from_columns(
            SCHEMA,
            {"id": t.col("id"), "features": Xc, "label": y},
        )
        (out,) = _scaler().fit(tc).transform(tc)
        Z = out.features_dense("features")
        assert np.all(np.isfinite(Z))
        np.testing.assert_allclose(Z[:, 2], 0.0, atol=1e-6)  # centered, unscaled

    def test_model_save_load_round_trip(self, tmp_path):
        t, _, _ = _data()
        model = _scaler().fit(t)
        model.save(str(tmp_path / "scaler"))
        loaded = load_stage(str(tmp_path / "scaler"))
        assert isinstance(loaded, StandardScalerModel)
        (a,) = model.transform(t)
        (b,) = loaded.transform(t)
        np.testing.assert_array_equal(
            a.features_dense("features"), b.features_dense("features")
        )


class TestMinMaxScaler:
    def test_scales_to_unit_range(self):
        t, X, _ = _data()
        from flink_ml_tpu.lib import MinMaxScaler

        (out,) = (
            MinMaxScaler().set_selected_col("features").fit(t).transform(t)
        )
        Z = out.features_dense("features")
        np.testing.assert_allclose(Z.min(axis=0), 0.0, atol=1e-5)
        np.testing.assert_allclose(Z.max(axis=0), 1.0, atol=1e-5)

    def test_custom_range_and_constant_dim(self):
        from flink_ml_tpu.lib import MinMaxScaler

        t, X, y = _data()
        Xc = X.copy()
        Xc[:, 1] = 4.0  # constant dimension -> range midpoint
        tc = Table.from_columns(
            SCHEMA, {"id": t.col("id"), "features": Xc, "label": y}
        )
        model = (
            MinMaxScaler().set_selected_col("features")
            .set_output_min(-1.0).set_output_max(1.0).fit(tc)
        )
        (out,) = model.transform(tc)
        Z = out.features_dense("features")
        np.testing.assert_allclose(Z.min(axis=0)[[0, 2, 3, 4]], -1.0, atol=1e-5)
        np.testing.assert_allclose(Z.max(axis=0)[[0, 2, 3, 4]], 1.0, atol=1e-5)
        np.testing.assert_allclose(Z[:, 1], 0.0, atol=1e-6)

    def test_chunked_fit_matches_materialized(self):
        from flink_ml_tpu.lib import MinMaxScaler

        t, X, y = _data(n=100)
        rows = [(float(i), DenseVector(r), float(lab))
                for i, (r, lab) in enumerate(zip(X, y))]
        chunked = ChunkedTable(CollectionSource(rows, SCHEMA), chunk_rows=16)
        (mc,) = MinMaxScaler().set_selected_col("features").fit(chunked).get_model_data()
        (mf,) = MinMaxScaler().set_selected_col("features").fit(t).get_model_data()
        np.testing.assert_allclose(
            mc.features_dense("mins")[0], mf.features_dense("mins")[0], rtol=1e-6
        )
        np.testing.assert_allclose(
            mc.features_dense("maxs")[0], mf.features_dense("maxs")[0], rtol=1e-6
        )

    def test_bad_range_rejected(self):
        from flink_ml_tpu.lib import MinMaxScaler

        t, _, _ = _data(n=20)
        with pytest.raises(ValueError, match="outputMin"):
            (MinMaxScaler().set_selected_col("features")
             .set_output_min(1.0).set_output_max(0.0).fit(t))

    def test_save_load(self, tmp_path):
        from flink_ml_tpu.lib import MinMaxScaler, MinMaxScalerModel

        t, _, _ = _data()
        model = MinMaxScaler().set_selected_col("features").fit(t)
        model.save(str(tmp_path / "mm"))
        loaded = load_stage(str(tmp_path / "mm"))
        assert isinstance(loaded, MinMaxScalerModel)
        (a,) = model.transform(t)
        (b,) = loaded.transform(t)
        np.testing.assert_array_equal(
            a.features_dense("features"), b.features_dense("features")
        )


class TestVectorAssembler:
    def test_assembles_numeric_and_vector_cols(self):
        from flink_ml_tpu.lib import VectorAssembler

        rng = np.random.RandomState(0)
        X = rng.randn(50, 3)
        a = rng.randn(50)
        schema = Schema.of(
            ("a", "double"), ("vec", DataTypes.DENSE_VECTOR), ("label", "double")
        )
        t = Table.from_columns(
            schema, {"a": a, "vec": X, "label": np.zeros(50)}
        )
        (out,) = (
            VectorAssembler().set_selected_cols(["a", "vec"])
            .set_output_col("features").transform(t)
        )
        assert out.schema.field_names == ["a", "vec", "label", "features"]
        Z = out.features_dense("features")
        np.testing.assert_array_equal(Z[:, 0], a)
        np.testing.assert_array_equal(Z[:, 1:], X)

    def test_assembler_heads_a_pipeline(self, tmp_path):
        """assembler -> scaler -> LR: a three-stage pipeline over plain
        numeric columns, save/load reproducing predictions."""
        from flink_ml_tpu.lib import VectorAssembler

        rng = np.random.RandomState(1)
        n = 300
        cols = {f"c{i}": rng.randn(n) * (10.0 ** i) for i in range(4)}
        X = np.stack([cols[f"c{i}"] for i in range(4)], axis=1)
        y = (X[:, 0] + 0.3 * X[:, 1] / 10 > 0).astype(np.float64)
        schema = Schema.of(*[(f"c{i}", "double") for i in range(4)],
                           ("label", "double"))
        t = Table.from_columns(schema, {**cols, "label": y})
        lr = (
            LogisticRegression().set_vector_col("features")
            .set_label_col("label").set_prediction_col("pred")
            .set_learning_rate(0.5).set_max_iter(15)
        )
        pm = Pipeline([
            VectorAssembler().set_selected_cols([f"c{i}" for i in range(4)])
            .set_output_col("features"),
            _scaler(),
            lr,
        ]).fit(t)
        (out,) = pm.transform(t)
        acc = float(np.mean(np.asarray(out.col("pred")) == y))
        assert acc > 0.9, acc
        pm.save(str(tmp_path / "pm"))
        loaded = PipelineModel.load(str(tmp_path / "pm"))
        (redo,) = loaded.transform(t)
        np.testing.assert_array_equal(out.col("pred"), redo.col("pred"))


class TestScalerPipelineE2E:
    """The VERDICT r3 'done' bar: Pipeline([scaler, lr]).fit exercises the
    transform-forward branch with real tables; the loaded PipelineModel
    reproduces predictions."""

    def _pipeline(self):
        lr = (
            LogisticRegression().set_vector_col("features")
            .set_label_col("label").set_prediction_col("pred")
            .set_learning_rate(0.5).set_max_iter(10)
        )
        return Pipeline([_scaler(), lr])

    def test_fit_forwards_scaled_features_to_estimator(self):
        t, X, y = _data(n=400, seed=3, scale=np.array([1e3, 1e-3, 1.0, 50.0, 0.1]))
        pm = self._pipeline().fit(t)
        (out,) = pm.transform(t)
        acc_scaled = float(np.mean(np.asarray(out.col("pred")) == y))
        assert acc_scaled > 0.9
        # the transform-forward branch fed the estimator SCALED features:
        # manually scaling with the fitted stage-0 model and fitting a fresh
        # identical LR reproduces the pipeline's predictions bit-for-bit
        (scaled,) = pm.stages[0].transform(t)
        lr2 = (
            LogisticRegression().set_vector_col("features")
            .set_label_col("label").set_prediction_col("pred")
            .set_learning_rate(0.5).set_max_iter(10)
        )
        (manual,) = lr2.fit(scaled).transform(scaled)
        np.testing.assert_array_equal(out.col("pred"), manual.col("pred"))

    def test_save_load_reproduces_predictions(self, tmp_path):
        t, _, y = _data(n=300, seed=5)
        pm = self._pipeline().fit(t)
        (orig,) = pm.transform(t)
        pm.save(str(tmp_path / "pm"))
        loaded = PipelineModel.load(str(tmp_path / "pm"))
        (redo,) = loaded.transform(t)
        np.testing.assert_array_equal(orig.col("pred"), redo.col("pred"))
        assert float(np.mean(np.asarray(redo.col("pred")) == y)) > 0.9

    def test_unfitted_pipeline_save_load_then_fit(self, tmp_path):
        t, _, y = _data(n=300, seed=7)
        p = self._pipeline()
        p.save(str(tmp_path / "p"))
        p2 = Pipeline.load(str(tmp_path / "p"))
        pm = p2.fit(t)
        (out,) = pm.transform(t)
        assert float(np.mean(np.asarray(out.col("pred")) == y)) > 0.9

    def test_chunked_multi_stage_pipeline_out_of_core(self):
        """Scaler -> LR over a ChunkedTable: the TransformedChunkedTable
        forward path feeds the estimator's out-of-core fit with scaled
        chunks; result matches the fully-materialized pipeline."""
        t, X, y = _data(n=256, seed=9)
        rows = [(float(i), DenseVector(r), float(lab))
                for i, (r, lab) in enumerate(zip(X, y))]
        chunked = ChunkedTable(CollectionSource(rows, SCHEMA), chunk_rows=32)

        def make():
            lr = (
                LogisticRegression().set_vector_col("features")
                .set_label_col("label").set_prediction_col("pred")
                .set_learning_rate(0.5).set_max_iter(5)
                .set_global_batch_size(32)
            )
            return Pipeline([_scaler(), lr])

        pm_ooc = make().fit(chunked)
        pm_mem = make().fit(t)
        (a,) = pm_ooc.transform(t)
        (b,) = pm_mem.transform(t)
        # the scaler's chunked moment accumulation rounds differently from
        # the one-pass fit (f32 chunk partials), so scaled features differ
        # in ulps; demand near-total prediction agreement, not bit equality
        agree = float(np.mean(np.asarray(a.col("pred")) == np.asarray(b.col("pred"))))
        assert agree >= 0.98, agree
