"""Two-process jax.distributed smoke test (SURVEY.md §2.6 comm-backend row, DCN).

The reference scales multi-node through Flink's runtime (job/task managers over
TCP; flink-ml-lib/pom.xml:40-58 provided deps).  Here the control plane is
``jax.distributed`` and the data plane is an XLA collective: two OS processes,
each owning 4 virtual CPU devices, form one 8-device mesh and jointly reduce a
globally-sharded array.  Run in subprocesses because the parent test process
already holds an initialized single-process JAX backend.
"""

import os
import socket
import subprocess
import sys
from pathlib import Path

HERE = Path(__file__).resolve().parent
WORKER = HERE / "distributed_worker.py"


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def test_two_process_mesh_psum():
    port = _free_port()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # worker sets its own device count
    procs = [
        subprocess.Popen(
            [sys.executable, str(WORKER), str(pid), "2", str(port)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
            cwd=str(HERE.parent),
        )
        for pid in range(2)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=180)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out}"
        # sum(0..7) reduced across the two-process mesh
        assert "RESULT 28.0" in out, f"worker {pid} output:\n{out}"
