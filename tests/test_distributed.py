"""Two-process jax.distributed smoke test (SURVEY.md §2.6 comm-backend row, DCN).

The reference scales multi-node through Flink's runtime (job/task managers over
TCP; flink-ml-lib/pom.xml:40-58 provided deps).  Here the control plane is
``jax.distributed`` and the data plane is an XLA collective: two OS processes,
each owning 4 virtual CPU devices, form one 8-device mesh and jointly reduce a
globally-sharded array.  Run in subprocesses because the parent test process
already holds an initialized single-process JAX backend.
"""

import os
import socket
import subprocess
import sys
from pathlib import Path

HERE = Path(__file__).resolve().parent
WORKER = HERE / "distributed_worker.py"


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def test_two_process_mesh_psum(tmp_path):
    # per-process file shards for the data-plane fit (VERDICT r3 item 2)
    from tests._distributed_common import make_shard_rows, write_shard_csv

    shards = make_shard_rows(2)
    for pid, (Xs, ys) in enumerate(shards):
        write_shard_csv(str(tmp_path / f"shard{pid}.csv"), Xs, ys)

    port = _free_port()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # worker sets its own device count
    procs = [
        subprocess.Popen(
            [sys.executable, str(WORKER), str(pid), "2", str(port),
             str(tmp_path)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
            cwd=str(HERE.parent),
        )
        for pid in range(2)
    ]
    outs = []
    for p in procs:
        try:
            # generous: the workers compile every fit variant from a cold
            # jit cache, and the suite may be sharing the host's one core
            out, _ = p.communicate(timeout=420)
        except subprocess.TimeoutExpired:
            partials = []
            for q in procs:
                q.kill()
                try:
                    partial, _ = q.communicate(timeout=10)
                except Exception:
                    partial = "<unreadable>"
                partials.append(partial)
            raise AssertionError(
                "distributed workers timed out; partial outputs:\n"
                + "\n---\n".join(partials)
            )
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out}"
        # sum(0..7) reduced across the two-process mesh
        assert "RESULT 28.0" in out, f"worker {pid} output:\n{out}"

    # the cross-process training epoch must equal the same epoch on a
    # single-process 8-device mesh (this test process, via conftest)
    import numpy as np

    from tests._distributed_common import make_epoch_inputs, make_epoch_step
    from flink_ml_tpu.parallel.mesh import default_mesh, replicate, shard_batch

    combined, params0 = make_epoch_inputs()
    mesh = default_mesh()
    params = replicate(mesh, params0)
    batch = shard_batch(
        mesh, (combined[..., :-2], combined[..., -2], combined[..., -1])
    )
    epoch_step = make_epoch_step(mesh)
    (w, b), (loss, _delta) = epoch_step(params, batch)
    expected = [float(v) for v in np.asarray(w)] + [float(b), float(loss)]

    for pid, out in enumerate(outs):
        line = [ln for ln in out.splitlines() if ln.startswith("TRAIN ")]
        assert line, f"worker {pid} printed no TRAIN line:\n{out}"
        got = [float(v) for v in line[0].split()[1:]]
        np.testing.assert_allclose(
            got, expected, rtol=1e-6, atol=1e-9,
            err_msg=f"worker {pid} diverged from single-process epoch",
        )

    # -- per-process file-shard fits (the real data plane) --------------------
    # single-process reference: the SAME estimator fit over the interleaved
    # row order (global step s = each process's s-th G/P-row window)
    from tests._distributed_common import (
        fit_shard_table,
        interleaved_rows,
        shard_schema,
    )
    from flink_ml_tpu.table.table import Table

    Xi, yi = interleaved_rows(shards, 2)
    ref_table = Table.from_columns(
        shard_schema(),
        {**{f"f{i}": Xi[:, i] for i in range(Xi.shape[1])}, "label": yi},
    )
    w_ref, b_ref = fit_shard_table(ref_table)
    expected_fit = list(w_ref) + [b_ref]

    for tag in ("FITMEM", "FITOOC"):
        for pid, out in enumerate(outs):
            line = [ln for ln in out.splitlines() if ln.startswith(tag + " ")]
            assert line, f"worker {pid} printed no {tag} line:\n{out}"
            got = [float(v) for v in line[0].split()[1:]]
            np.testing.assert_allclose(
                got, expected_fit, rtol=1e-6, atol=1e-8,
                err_msg=(
                    f"worker {pid} {tag}: per-process file-shard fit diverged "
                    "from the single-process interleaved-order fit"
                ),
            )

    # -- sparse per-process fit (cross-process nnz_pad agreement) -------------
    # the shards' nnz densities are unequal by construction, so the workers'
    # local packs disagree on the padded width until agree_max reconciles
    # them; the result must equal the single-process interleaved-order fit
    from tests._distributed_common import (
        fit_sparse_shard_table,
        interleaved_sparse_rows,
        make_sparse_shard_rows,
        sparse_shard_schema,
    )

    sshards = make_sparse_shard_rows(2)
    svecs, sy = interleaved_sparse_rows(sshards, 2)
    sref = Table.from_columns(
        sparse_shard_schema(), {"features": svecs, "label": sy}
    )
    w_sref, b_sref = fit_sparse_shard_table(sref)
    expected_sparse = (
        [float(np.sum(w_sref)), float(np.sum(w_sref * w_sref))]
        + [float(v) for v in w_sref[:8]] + [b_sref]
    )
    for pid, out in enumerate(outs):
        line = [ln for ln in out.splitlines() if ln.startswith("FITSPARSE ")]
        assert line, f"worker {pid} printed no FITSPARSE line:\n{out}"
        got = [float(v) for v in line[0].split()[1:]]
        np.testing.assert_allclose(
            got, expected_sparse, rtol=1e-5, atol=1e-7,
            err_msg=(
                f"worker {pid} FITSPARSE: per-process sparse fit diverged "
                "from the single-process interleaved-order fit"
            ),
        )

    # hot/cold across processes: hot selection from the globally-summed
    # frequency vector, pad widths from agree_max — must equal the
    # single-process hot/cold fit over the interleaved order (f32 slab
    # rounding differs only in summation grouping; the bf16 slab is used
    # on both sides, so results are bit-comparable)
    w_href, b_href = fit_sparse_shard_table(sref, hot_k=16)
    expected_hot = (
        [float(np.sum(w_href)), float(np.sum(w_href * w_href))]
        + [float(v) for v in w_href[:8]] + [b_href]
    )
    for pid, out in enumerate(outs):
        line = [ln for ln in out.splitlines() if ln.startswith("FITHOT ")]
        assert line, f"worker {pid} printed no FITHOT line:\n{out}"
        got = [float(v) for v in line[0].split()[1:]]
        np.testing.assert_allclose(
            got, expected_hot, rtol=1e-5, atol=1e-7,
            err_msg=(
                f"worker {pid} FITHOT: per-process hot/cold fit diverged "
                "from the single-process interleaved-order fit"
            ),
        )

    # sparse out-of-core: equal shards, so the streamed fit bit-matches
    # the in-memory fit and shares its expected digest
    for pid, out in enumerate(outs):
        line = [ln for ln in out.splitlines() if ln.startswith("FITSOOC ")]
        assert line, f"worker {pid} printed no FITSOOC line:\n{out}"
        got = [float(v) for v in line[0].split()[1:]]
        np.testing.assert_allclose(
            got, expected_sparse, rtol=1e-5, atol=1e-7,
            err_msg=(
                f"worker {pid} FITSOOC: per-process sparse out-of-core fit "
                "diverged from the single-process interleaved-order fit"
            ),
        )

    # hot/cold out-of-core: streamed hot/cold bit-matches the in-memory
    # hot/cold fit, so it shares FITHOT's expected digest
    for pid, out in enumerate(outs):
        line = [ln for ln in out.splitlines() if ln.startswith("FITHOOC ")]
        assert line, f"worker {pid} printed no FITHOOC line:\n{out}"
        got = [float(v) for v in line[0].split()[1:]]
        np.testing.assert_allclose(
            got, expected_hot, rtol=1e-5, atol=1e-7,
            err_msg=(
                f"worker {pid} FITHOOC: per-process hot/cold out-of-core "
                "fit diverged from the single-process in-memory fit"
            ),
        )

    # unequal shards: no single-process reference is expressible (the
    # short shard's trailing no-op windows interleave mid-stream), but the
    # two processes must land on the identical global model — and on
    # anything at all (a block-count mismatch would deadlock, caught by
    # the subprocess timeout)
    lines = []
    for pid, out in enumerate(outs):
        line = [ln for ln in out.splitlines() if ln.startswith("FITSOOCU ")]
        assert line, f"worker {pid} printed no FITSOOCU line:\n{out}"
        lines.append([float(v) for v in line[0].split()[1:]])
    assert all(np.isfinite(lines[0]))
    np.testing.assert_allclose(
        lines[1], lines[0], rtol=1e-12,
        err_msg="workers disagree on the unequal-shard out-of-core model",
    )

    # KMeans: the single-process reference runs over the shards
    # CONCATENATED in process order (contiguous device blocks — see
    # fit_kmeans_shard_table docstring), with the same seed, so the
    # allgathered init pool and the Lloyd row partition match exactly
    from tests._distributed_common import fit_kmeans_shard_table

    Xc = np.concatenate([s[0] for s in shards])
    yc = np.concatenate([s[1] for s in shards])
    km_ref_table = Table.from_columns(
        shard_schema(),
        {**{f"f{i}": Xc[:, i] for i in range(Xc.shape[1])}, "label": yc},
    )
    cents_ref, cost_ref = fit_kmeans_shard_table(km_ref_table)
    expected_km = (
        [float(np.sum(cents_ref)), float(np.sum(cents_ref * cents_ref)),
         cost_ref] + [float(v) for v in cents_ref[0]]
    )
    for pid, out in enumerate(outs):
        line = [ln for ln in out.splitlines() if ln.startswith("FITKM ")]
        assert line, f"worker {pid} printed no FITKM line:\n{out}"
        got = [float(v) for v in line[0].split()[1:]]
        np.testing.assert_allclose(
            got, expected_km, rtol=1e-5, atol=1e-7,
            err_msg=(
                f"worker {pid} FITKM: per-process KMeans fit diverged "
                "from the single-process concatenated-order fit"
            ),
        )

    # transform runs per-process on the local mesh: worker p's predictions
    # over ITS shard must match the single-process transform of that shard
    from flink_ml_tpu.lib import Knn
    from tests._distributed_common import SHARD_FEATURES

    from flink_ml_tpu.lib.classification import LogisticRegressionModel
    from flink_ml_tpu.lib.glm import make_model_table

    for pid, out in enumerate(outs):
        Xs, ys = shards[pid]
        shard_table = Table.from_columns(
            shard_schema(),
            {**{f"f{i}": Xs[:, i] for i in range(Xs.shape[1])}, "label": ys},
        )
        # the worker's GLM model is the cross-process (global) fit — the
        # same coefficients as the FITMEM reference; its transform runs on
        # the process-local mesh over the worker's own shard
        glm_ref = (
            LogisticRegressionModel().set_feature_cols(SHARD_FEATURES)
            .set_prediction_col("pred")
        )
        glm_ref.set_model_data(make_model_table(w_ref, b_ref))
        (ref_scored,) = glm_ref.transform(shard_table)
        ref_preds = np.asarray(ref_scored.col("pred"))[:32]
        line = [ln for ln in out.splitlines() if ln.startswith("XFORM ")]
        assert line, f"worker {pid} printed no XFORM line:\n{out}"
        got = np.asarray([float(v) for v in line[0].split()[1:]])
        np.testing.assert_allclose(got, ref_preds, atol=0,
                                   err_msg=f"worker {pid} XFORM diverged")
        knn_ref = (
            Knn().set_feature_cols(SHARD_FEATURES).set_label_col("label")
            .set_prediction_col("knnp").set_k(3).set_shard_model_data(True)
            .fit(shard_table)
        )
        (kref,) = knn_ref.transform(shard_table)
        kref_preds = np.asarray(kref.col("knnp"))[:32]
        line = [ln for ln in out.splitlines() if ln.startswith("XFORMKNN ")]
        assert line, f"worker {pid} printed no XFORMKNN line:\n{out}"
        got = np.asarray([float(v) for v in line[0].split()[1:]])
        np.testing.assert_allclose(got, kref_preds, atol=0,
                                   err_msg=f"worker {pid} XFORMKNN diverged")

    # 2-D (data x model) mesh: the single-process references run on the
    # same-shaped mesh over this process's 8 local devices; the workers'
    # global mesh spans both processes, with model-axis params placed via
    # global_put from each process's full host copy
    from flink_ml_tpu.parallel.mesh import create_mesh
    from flink_ml_tpu.table.sources import ChunkedTable, CollectionSource
    from flink_ml_tpu.utils.environment import MLEnvironmentFactory

    env = MLEnvironmentFactory.get_default()
    old_mesh = env.get_mesh()
    env.set_mesh(create_mesh({"data": 4, "model": 2}))
    try:
        w_d2, b_d2 = fit_shard_table(ref_table)
        expected_d2 = list(w_d2) + [b_d2]
        w_s2, b_s2 = fit_sparse_shard_table(sref)
        expected_s2 = (
            [float(np.sum(w_s2)), float(np.sum(w_s2 * w_s2))]
            + [float(v) for v in w_s2[:8]] + [b_s2]
        )
        w_h2, b_h2 = fit_sparse_shard_table(sref, hot_k=16)
        expected_h2 = (
            [float(np.sum(w_h2)), float(np.sum(w_h2 * w_h2))]
            + [float(v) for v in w_h2[:8]] + [b_h2]
        )
        w_ho2, b_ho2 = fit_sparse_shard_table(
            ChunkedTable(
                CollectionSource(list(zip(svecs, sy)), sparse_shard_schema()),
                chunk_rows=64,
            ),
            hot_k=16,
        )
        expected_ho2 = (
            [float(np.sum(w_ho2)), float(np.sum(w_ho2 * w_ho2))]
            + [float(v) for v in w_ho2[:8]] + [b_ho2]
        )
    finally:
        env.set_mesh(old_mesh)
    for tag, expected in (("FITD2D", expected_d2), ("FITS2D", expected_s2),
                          ("FITH2D", expected_h2),
                          ("FITH2DOOC", expected_ho2)):
        for pid, out in enumerate(outs):
            line = [ln for ln in out.splitlines() if ln.startswith(tag + " ")]
            assert line, f"worker {pid} printed no {tag} line:\n{out}"
            got = [float(v) for v in line[0].split()[1:]]
            np.testing.assert_allclose(
                got, expected, rtol=1e-5, atol=1e-7,
                err_msg=(
                    f"worker {pid} {tag}: cross-process 2-D fit diverged "
                    "from the single-process same-mesh fit"
                ),
            )

    # KMeans out-of-core: same init (under-cap reservoir = the dataset in
    # concatenated order on both sides), Lloyd accumulation differs only
    # in per-device grouping — looser float tolerance than the GLMs'
    # schedule-exact paths (see KMeans._fit_out_of_core docstring)
    from flink_ml_tpu.table.sources import ChunkedTable, CollectionSource

    km_rows = [tuple(Xc[i]) + (yc[i],) for i in range(len(yc))]
    cents_oref, cost_oref = fit_kmeans_shard_table(
        ChunkedTable(CollectionSource(km_rows, shard_schema()), chunk_rows=64)
    )
    expected_km_ooc = (
        [float(np.sum(cents_oref)), float(np.sum(cents_oref * cents_oref)),
         cost_oref] + [float(v) for v in cents_oref[0]]
    )
    for pid, out in enumerate(outs):
        line = [ln for ln in out.splitlines() if ln.startswith("FITKMOOC ")]
        assert line, f"worker {pid} printed no FITKMOOC line:\n{out}"
        got = [float(v) for v in line[0].split()[1:]]
        np.testing.assert_allclose(
            got, expected_km_ooc, rtol=1e-4, atol=1e-6,
            err_msg=(
                f"worker {pid} FITKMOOC: per-process out-of-core KMeans "
                "diverged from the single-process concatenated-order fit"
            ),
        )


def test_two_process_kill_and_resume(tmp_path):
    """VERDICT r4 #4: kill one worker mid-out-of-core-fit, restart both,
    resume from the chunked checkpoint, and land on the model an
    uninterrupted run produces — the Flink checkpoint/restart story
    (`/root/reference/pom.xml:396-401`) on the jax.distributed data plane."""
    import numpy as np

    RESUME_WORKER = HERE / "distributed_resume_worker.py"
    ckpt_root = tmp_path / "ck"
    ckpt_root.mkdir()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)

    def spawn(phase, port):
        return [
            subprocess.Popen(
                [sys.executable, str(RESUME_WORKER), str(pid), "2",
                 str(port), phase, str(ckpt_root)],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, env=env, cwd=str(HERE.parent),
            )
            for pid in range(2)
        ]

    # phase 1: crash.  Worker 1 os._exit(17)s right after its second
    # snapshot commits; worker 0 is left owing collectives — give it a
    # moment to finish its own epoch-2 snapshot, then kill it (the
    # "machine failure" takes out both).
    procs = spawn("crash", _free_port())
    out1, _ = procs[1].communicate(timeout=420)
    assert procs[1].returncode == 17, (
        f"worker 1 should simulate a crash (exit 17):\n{out1}"
    )
    try:
        out0, _ = procs[0].communicate(timeout=30)
    except subprocess.TimeoutExpired:
        procs[0].kill()
        out0, _ = procs[0].communicate(timeout=30)
    from flink_ml_tpu.iteration.checkpoint import latest_checkpoint

    for pid in range(2):
        assert latest_checkpoint(str(ckpt_root / f"p{pid}")) is not None, (
            f"no snapshot survived for worker {pid}:\n{out0}\n{out1}"
        )

    # phase 2: restart both; each fleet member agrees on the common resume
    # epoch and continues to completion
    procs = spawn("resume", _free_port())
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=420)
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"resume worker {pid} failed:\n{out}"

    # uninterrupted single-process reference over the interleaved order
    from tests._distributed_common import (
        fit_sparse_shard_table,
        interleaved_sparse_rows,
        make_sparse_shard_rows,
        sparse_shard_schema,
    )
    from flink_ml_tpu.table.table import Table

    sshards = make_sparse_shard_rows(2)
    svecs, sy = interleaved_sparse_rows(sshards, 2)
    sref = Table.from_columns(
        sparse_shard_schema(), {"features": svecs, "label": sy}
    )
    w_ref, b_ref = fit_sparse_shard_table(sref, max_iter=6)
    expected = (
        [float(np.sum(w_ref)), float(np.sum(w_ref * w_ref))]
        + [float(v) for v in w_ref[:8]] + [b_ref]
    )
    for pid, out in enumerate(outs):
        line = [ln for ln in out.splitlines() if ln.startswith("FITRESUME ")]
        assert line, f"worker {pid} printed no FITRESUME line:\n{out}"
        got = [float(v) for v in line[0].split()[1:]]
        np.testing.assert_allclose(
            got, expected, rtol=1e-5, atol=1e-7,
            err_msg=(
                f"worker {pid}: resumed model diverged from the "
                "uninterrupted single-process reference"
            ),
        )
