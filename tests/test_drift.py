"""Data-plane observability tests (ISSUE 11): the streaming distribution
sketches (merge associativity, rank-error bounds, NaN/null parity with
the quarantine counters), the DriftMonitor (reference snapshot at
deploy, sidecar-commit persistence, PSI/KS judgment), the tap wiring
(quarantine boundary, fused plan entry, serving demux, the owner rule),
the third SLO (``slo.burning.drift`` -> reason-coded ``/readyz`` ->
``drift_breach`` black box), the OpenMetrics histogram families, and
the ``obs drift`` CLI."""

import json
import os
import urllib.request

import numpy as np
import pytest

from flink_ml_tpu import obs
from flink_ml_tpu.obs import drift, flight, slo, telemetry
from flink_ml_tpu.obs.sketch import ColumnSketch, QuantileSketch, ks, psi
from flink_ml_tpu.serve import quarantine
from flink_ml_tpu.serve.breaker import reset_breakers
from flink_ml_tpu.serve.errors import ModelIntegrityError
from flink_ml_tpu.table.schema import DataTypes, Schema
from flink_ml_tpu.table.table import Table


@pytest.fixture(autouse=True)
def _drift_isolated(monkeypatch, tmp_path):
    """Clean process-global planes per test: registry, flight, breakers,
    quarantine store, the default drift monitor, and every registered
    telemetry source (drift monitors register histogram providers)."""
    monkeypatch.setenv("FMT_FLIGHT_DIR", str(tmp_path / "flight"))
    monkeypatch.delenv("FMT_TELEMETRY_PORT", raising=False)
    monkeypatch.delenv("FMT_DRIFT", raising=False)
    obs.enable()
    obs.reset()
    flight.reset()
    reset_breakers()
    quarantine.reset()
    drift.reset()
    yield
    drift.reset()
    obs.disable()
    obs.reset()
    flight.reset()
    reset_breakers()
    quarantine.reset()
    with telemetry._SOURCES_LOCK:
        telemetry._READINESS_SOURCES.clear()
        telemetry._STATUS_SOURCES.clear()
        telemetry._HISTOGRAM_SOURCES.clear()


def _rank_err(data, sketch, qs=(0.01, 0.05, 0.25, 0.5, 0.75, 0.95, 0.99)):
    """Worst rank error of the sketch's quantile estimates: where the
    estimate actually sits in the sorted data vs where it should."""
    srt = np.sort(data)
    worst = 0.0
    for q in qs:
        est = sketch.quantile(q)
        rank = np.searchsorted(srt, est) / len(srt)
        worst = max(worst, abs(rank - q))
    return worst


class TestQuantileSketch:
    def test_merge_equals_streaming(self):
        """merge(a, b, c) must hold exactly the points one sketch
        streaming a+b+c saw — window rotation and reference persistence
        both lean on this."""
        rng = np.random.RandomState(0)
        parts = [rng.randn(1000), rng.lognormal(0, 1, 1000),
                 rng.randn(1000) * 5 - 2]
        streamed = QuantileSketch()
        for p in parts:
            streamed.update(p)
        merged = QuantileSketch()
        for p in parts:
            s = QuantileSketch()
            s.update(p)
            merged.merge(s)
        assert merged.count == streamed.count
        assert merged.total == pytest.approx(streamed.total)
        qs = [0.05, 0.25, 0.5, 0.75, 0.95, 0.99]
        assert merged.quantiles(qs) == streamed.quantiles(qs)

    def test_merge_associativity(self):
        rng = np.random.RandomState(1)
        a, b, c = (rng.randn(500), rng.lognormal(0, 2, 500),
                   -rng.pareto(1.5, 500))
        s = [QuantileSketch() for _ in range(3)]
        for sk, d in zip(s, (a, b, c)):
            sk.update(d)

        def clone(sk):
            return QuantileSketch.from_dict(sk.to_dict())

        ab_c = clone(clone(s[0]).merge(s[1])).merge(s[2])
        a_bc = clone(s[0]).merge(clone(s[1]).merge(s[2]))
        qs = [0.1, 0.5, 0.9]
        assert ab_c.quantiles(qs) == a_bc.quantiles(qs)
        assert ab_c.count == a_bc.count

    @pytest.mark.parametrize("name,maker", [
        ("normal", lambda rng: rng.randn(40_000)),
        ("heavy_tail", lambda rng: rng.lognormal(0, 2, 40_000)),
        ("neg_heavy_tail", lambda rng: -rng.lognormal(0, 2, 40_000)),
        ("bimodal", lambda rng: np.concatenate(
            [rng.randn(20_000) - 10, rng.randn(20_000) + 10])),
        ("pareto", lambda rng: rng.pareto(1.2, 40_000) + 1),
    ])
    def test_rank_error_bound_adversarial(self, name, maker):
        """Estimated quantiles must sit within 2% rank of the target on
        adversarial shapes — heavy tails, bimodal gaps, signed data —
        fed in chunks like the serving tap does."""
        rng = np.random.RandomState(7)
        data = maker(rng)
        sketch = QuantileSketch(alpha=0.01)
        for chunk in np.array_split(data, 17):
            sketch.update(chunk)
        assert _rank_err(data, sketch) <= 0.02, name

    def test_constant_distribution_value_exact(self):
        """A constant column (rank error is meaningless — every value IS
        every quantile): the estimate must be within the alpha relative
        bound of the constant."""
        sketch = QuantileSketch(alpha=0.01)
        sketch.update(np.full(10_000, 3.7))
        for q in (0.01, 0.5, 0.99):
            assert sketch.quantile(q) == pytest.approx(3.7, rel=0.02)
        assert sketch.count == 10_000

    def test_relative_error_bound_positive(self):
        """The DDSketch contract on uncollapsed one-sided data: every
        quantile within alpha relative of the true value."""
        rng = np.random.RandomState(3)
        data = rng.pareto(1.2, 30_000) + 1
        sketch = QuantileSketch(alpha=0.01)
        sketch.update(data)
        for q in (0.05, 0.5, 0.95, 0.99):
            true = np.quantile(data, q)
            assert sketch.quantile(q) == pytest.approx(true, rel=0.025)

    def test_fixed_memory_collapse(self):
        """Magnitudes spanning 12 decades under a tight bin budget: the
        bin count must hold at the cap, with the error pushed into the
        near-zero region — the upper quantiles (where drift statistics
        live) keep their relative bound, and the collapsed low end
        degrades toward 0, never upward."""
        rng = np.random.RandomState(5)
        data = 10.0 ** rng.uniform(-6, 6, 50_000)
        sketch = QuantileSketch(alpha=0.01, max_bins=256)
        for chunk in np.array_split(data, 23):
            sketch.update(chunk)
        assert len(sketch.pos) + len(sketch.neg) + 1 <= 256
        for q in (0.9, 0.99):
            true = np.quantile(data, q)
            assert sketch.quantile(q) == pytest.approx(true, rel=0.05)
        # the low tail absorbed the collapse: estimates can only shrink
        assert sketch.quantile(0.05) <= np.quantile(data, 0.05)

    def test_rejects_non_finite(self):
        sketch = QuantileSketch()
        with pytest.raises(ValueError, match="finite"):
            sketch.update(np.array([1.0, np.nan]))
        with pytest.raises(ValueError, match="finite"):
            sketch.update(np.array([np.inf]))

    def test_serialization_round_trip(self):
        rng = np.random.RandomState(9)
        sketch = QuantileSketch()
        sketch.update(rng.randn(5000) * 3 + 1)
        again = QuantileSketch.from_dict(
            json.loads(json.dumps(sketch.to_dict()))
        )
        qs = [0.05, 0.5, 0.95]
        assert again.quantiles(qs) == sketch.quantiles(qs)
        assert again.count == sketch.count

    def test_histogram_export_compacted(self):
        rng = np.random.RandomState(11)
        sketch = QuantileSketch()
        sketch.update(rng.lognormal(0, 2, 20_000))
        bounds, cum = sketch.histogram(max_buckets=16)
        assert len(bounds) <= 16
        assert bounds == sorted(bounds)
        assert cum == sorted(cum)
        assert cum[-1] == sketch.count


class TestColumnSketch:
    def test_nan_null_parity_with_quarantine(self):
        """The sketch's bad-value tallies and the quarantine boundary's
        reason codes must agree: the same NaN/None/Inf rows, counted the
        same way, from the same batch."""
        from flink_ml_tpu.ops.vector import DenseVector

        per_row = [1.0, np.nan, None, 2.0, None, np.inf]
        vectors = np.array(
            [None if v is None else DenseVector(np.array([v]))
             for v in per_row],
            dtype=object,
        )
        table = Table.from_columns(
            Schema.of(("x", DataTypes.DENSE_VECTOR)), {"x": vectors}
        )
        verdict = quarantine.validate_feature_batch(
            table, dim=1, vector_col="x"
        )
        assert verdict is not None
        good, reasons = verdict
        quarantine.emit("parity", table, good, reasons)
        counts = {
            "nan_inf": obs.registry().counter("serve.quarantined.nan_inf"),
            "null": obs.registry().counter("serve.quarantined.null"),
        }
        cs = ColumnSketch()
        cs.update(np.array(per_row, dtype=object))
        # the quarantine validator folds NaN and Inf into one nan_inf
        # reason; the sketch keeps them separate — their sum must match
        assert cs.nans + cs.infs == counts["nan_inf"] == 2
        assert cs.nulls == counts["null"] == 2
        assert cs.n == 2  # the servable rows
        assert cs.rows == len(per_row)

    def test_moments_match_numpy(self):
        rng = np.random.RandomState(2)
        data = rng.randn(10_000) * 4 + 3
        cs = ColumnSketch()
        for chunk in np.array_split(data, 7):
            cs.update(chunk)
        assert cs.mean == pytest.approx(data.mean(), rel=1e-9)
        assert cs.var == pytest.approx(data.var(), rel=1e-9)

    def test_merge_combines_moments_and_tallies(self):
        rng = np.random.RandomState(4)
        a_data, b_data = rng.randn(3000), rng.randn(2000) + 5
        a, b = ColumnSketch(), ColumnSketch()
        a.update(a_data)
        b.update(b_data)
        b.update(np.array([np.nan]))
        a.merge(b)
        both = np.concatenate([a_data, b_data])
        assert a.n == 5000
        assert a.nans == 1
        assert a.mean == pytest.approx(both.mean(), rel=1e-9)
        assert a.var == pytest.approx(both.var(), rel=1e-9)


class TestDriftStatistics:
    def test_psi_stable_vs_shifted(self):
        rng = np.random.RandomState(6)
        ref, same = QuantileSketch(), QuantileSketch()
        shifted, scaled = QuantileSketch(), QuantileSketch()
        ref.update(rng.randn(20_000))
        same.update(rng.randn(20_000))
        shifted.update(rng.randn(20_000) + 2)
        scaled.update(rng.randn(20_000) * 3)
        assert psi(ref, same) < 0.05
        assert psi(ref, shifted) > 1.0
        assert psi(ref, scaled) > 0.5

    def test_ks_bounds_and_detection(self):
        rng = np.random.RandomState(8)
        ref, same, shifted = (QuantileSketch() for _ in range(3))
        ref.update(rng.randn(20_000))
        same.update(rng.randn(20_000))
        shifted.update(rng.randn(20_000) + 2)
        assert 0.0 <= ks(ref, same) < 0.05
        assert 0.5 < ks(ref, shifted) <= 1.0

    def test_constant_reference_degenerate(self):
        ref, live = QuantileSketch(), QuantileSketch()
        ref.update(np.full(1000, 2.0))
        live.update(np.full(1000, 2.0))
        assert psi(ref, live) == pytest.approx(0.0, abs=1e-6)
        moved = QuantileSketch()
        moved.update(np.full(1000, 9.0))
        assert psi(ref, moved) > 1.0

    def test_empty_sketches(self):
        a, b = QuantileSketch(), QuantileSketch()
        assert psi(a, b) == 0.0
        assert ks(a, b) == 0.0


def _features_table(rng, n, shift=0.0, dim=4):
    X = (rng.randn(n, dim) + shift).astype(np.float32)
    return Table.from_columns(
        Schema.of(("features", DataTypes.DENSE_VECTOR)), {"features": X}
    )


_SPEC = {"dim": 4, "vector_col": "features"}


class TestDriftMonitor:
    def _monitor(self, **kw):
        kw.setdefault("ref_target", 100)
        kw.setdefault("threshold", 0.2)
        kw.setdefault("min_window_rows", 32)
        kw.setdefault("window", 3600)
        return drift.DriftMonitor(name="test", **kw)

    def test_reference_fills_then_freezes(self):
        rng = np.random.RandomState(0)
        mon = self._monitor()
        try:
            mon.observe_input(_features_table(rng, 64), _SPEC)
            mon.roll()
            assert not mon.reference_complete
            mon.observe_input(_features_table(rng, 64), _SPEC)
            mon.roll()
            assert mon.reference_complete
            # post-freeze rows land in the live window
            mon.observe_input(_features_table(rng, 50), _SPEC)
            status = mon.status()
            assert status["reference"]["complete"]
            assert status["live_rows"] == 50
            assert status["reference"]["rows"] == 128
        finally:
            mon.close()

    def test_judge_gates_then_detects_shift(self):
        rng = np.random.RandomState(1)
        mon = self._monitor()
        try:
            assert mon.judge() is None  # reference still filling
            mon.observe_input(_features_table(rng, 128), _SPEC)
            mon.roll()
            assert mon.judge() is None  # live window below min_rows
            mon.observe_input(_features_table(rng, 16), _SPEC)
            assert mon.judge() is None
            # allow_small (the burning-SLO re-judge) still judges
            assert mon.judge(allow_small=True) is not None
            mon.observe_input(_features_table(rng, 64, shift=4.0), _SPEC)
            verdict = mon.judge()
            assert verdict is not None
            assert verdict["burn"] > 1.0
            assert verdict["worst_column"].startswith("features[")
            assert verdict["breaching"]
            worst = verdict["columns"][0]
            assert {"column", "psi", "ks", "ref", "live"} <= set(worst)
        finally:
            mon.close()

    def test_stable_traffic_does_not_burn(self):
        rng = np.random.RandomState(2)
        mon = self._monitor()
        try:
            mon.observe_input(_features_table(rng, 128), _SPEC)
            mon.roll()
            mon.observe_input(_features_table(rng, 128), _SPEC)
            verdict = mon.judge()
            assert verdict is not None
            assert verdict["burn"] < 1.0
        finally:
            mon.close()

    def test_window_rotation_forgets_old_traffic(self):
        rng = np.random.RandomState(3)
        mon = self._monitor(window=0.0)  # rotate on every roll
        try:
            mon.observe_input(_features_table(rng, 128), _SPEC)
            mon.roll()
            mon.observe_input(_features_table(rng, 64, shift=4.0), _SPEC)
            mon.roll()  # shifted rows -> previous window
            assert mon.judge(allow_small=True)["burn"] > 1.0
            mon.observe_input(_features_table(rng, 64), _SPEC)
            mon.roll()  # shifted window rotated out
            mon.observe_input(_features_table(rng, 64), _SPEC)
            assert mon.judge()["burn"] < 1.0
        finally:
            mon.close()

    def test_quarantine_reason_rates(self):
        rng = np.random.RandomState(4)
        mon = self._monitor()
        try:
            mon.observe_input(_features_table(rng, 128), _SPEC)
            mon.observe_reasons({"nan_inf": 2})
            mon.roll()
            mon.observe_input(_features_table(rng, 64), _SPEC)
            mon.observe_reasons({"nan_inf": 32})
            rates = mon.reason_rates()
            assert rates["reference"]["nan_inf"] == pytest.approx(2 / 128)
            assert rates["live"]["nan_inf"] == pytest.approx(32 / 64)
        finally:
            mon.close()

    def test_persist_and_reload(self, tmp_path):
        rng = np.random.RandomState(5)
        model_dir = tmp_path / "model"
        model_dir.mkdir()
        mon = self._monitor(persist_path=str(model_dir))
        try:
            mon.observe_input(_features_table(rng, 128), _SPEC)
            mon.roll()
            ref_path = model_dir / drift.REFERENCE_FILE
            assert ref_path.exists()
            assert (model_dir / (drift.REFERENCE_FILE
                                 + ".commit.json")).exists()
        finally:
            mon.close()
        # a restart adopts the committed baseline instead of relearning
        mon2 = self._monitor()
        try:
            assert mon2.load_reference(str(model_dir))
            assert mon2.reference_complete
            mon2.observe_input(_features_table(rng, 64, shift=4.0), _SPEC)
            assert mon2.judge()["burn"] > 1.0
        finally:
            mon2.close()

    def test_corrupt_reference_raises_integrity_error(self, tmp_path):
        rng = np.random.RandomState(6)
        model_dir = tmp_path / "model"
        model_dir.mkdir()
        mon = self._monitor(persist_path=str(model_dir))
        try:
            mon.observe_input(_features_table(rng, 128), _SPEC)
            mon.roll()
        finally:
            mon.close()
        path = model_dir / drift.REFERENCE_FILE
        with open(path, "a") as f:
            f.write("rot")
        mon2 = self._monitor()
        try:
            with pytest.raises(ModelIntegrityError):
                mon2.load_reference(str(model_dir))
        finally:
            mon2.close()

    def test_missing_reference_returns_false(self, tmp_path):
        mon = self._monitor()
        try:
            assert not mon.load_reference(str(tmp_path))
        finally:
            mon.close()

    def test_reset_reference(self):
        rng = np.random.RandomState(7)
        mon = self._monitor()
        try:
            mon.observe_input(_features_table(rng, 128), _SPEC)
            mon.roll()
            assert mon.reference_complete
            mon.reset_reference()
            assert not mon.reference_complete
            # the new population becomes the new baseline: shifted rows
            # now DEFINE normal instead of breaching
            mon.observe_input(_features_table(rng, 128, shift=4.0), _SPEC)
            mon.roll()
            mon.observe_input(_features_table(rng, 64, shift=4.0), _SPEC)
            assert mon.judge()["burn"] < 1.0
        finally:
            mon.close()

    def test_bootstrap_seeds_reference(self):
        rng = np.random.RandomState(8)
        mon = self._monitor(ref_target=64)
        try:
            warm = _features_table(rng, 64)
            mon.bootstrap(warm)
            mon.roll()
            assert mon.reference_complete
        finally:
            mon.close()

    def test_sparse_column_sketches_nnz(self):
        from flink_ml_tpu.ops.vector import SparseVector

        rng = np.random.RandomState(9)
        rows = np.empty(32, dtype=object)
        for i in range(32):
            nnz = rng.randint(1, 6)
            idx = np.sort(rng.choice(50, size=nnz, replace=False))
            rows[i] = SparseVector(50, idx, np.ones(nnz))
        table = Table.from_columns(
            Schema.of(("f", DataTypes.SPARSE_VECTOR)), {"f": rows}
        )
        mon = self._monitor(ref_target=16)
        try:
            mon.observe_input(table, {"dim": 50, "vector_col": "f"})
            mon.roll()
            status = mon.status()
            assert status["reference"]["columns"] == 1
            with mon._lock:
                assert "f.nnz" in mon._ref
        finally:
            mon.close()


class TestDriftTaps:
    """The wiring: taps at the quarantine boundary / fused entry /
    transform exit feed the scoped monitor exactly once per row."""

    def _fitted_pipeline(self, rng, n=512, dim=4):
        from flink_ml_tpu.api.pipeline import Pipeline
        from flink_ml_tpu.lib import LogisticRegression
        from flink_ml_tpu.lib.feature import StandardScaler

        X = rng.randn(n, dim).astype(np.float32)
        w = rng.randn(dim).astype(np.float32)
        y = (X @ w > 0).astype(np.float64)
        t = Table.from_columns(
            Schema.of(("features", DataTypes.DENSE_VECTOR),
                      ("label", "double")),
            {"features": X, "label": y},
        )
        model = Pipeline([
            StandardScaler().set_selected_col("features"),
            LogisticRegression().set_vector_col("features")
            .set_label_col("label").set_prediction_col("pred")
            .set_learning_rate(0.5).set_max_iter(3),
        ]).fit(t)
        return model, t

    def test_transform_taps_once_per_row(self, monkeypatch):
        """A 2-stage pipeline (both stages validate the same column)
        must sketch each row ONCE — the scope owner rule — and the
        produced prediction column must land as a score sketch."""
        rng = np.random.RandomState(0)
        model, t = self._fitted_pipeline(rng)
        monkeypatch.setenv("FMT_DRIFT", "1")
        monkeypatch.setenv("FMT_DRIFT_REF_ROWS", "100000")
        drift.reset()
        model.transform(t)
        mon = drift.default_monitor()
        status = mon.status()
        assert status["reference"]["rows"] == t.num_rows()
        with mon._lock:
            cols = dict(mon._ref)
        assert "features[0]" in cols
        assert cols["features[0]"].n == t.num_rows()
        assert "pred" in cols
        assert cols["pred"].n == t.num_rows()
        assert "label" not in cols  # input columns are not scores

    def test_zero_sketch_updates_while_off(self):
        """The off-path contract: with FMT_DRIFT unset, a transform
        performs ZERO sketch updates (the counter the bench asserts)."""
        rng = np.random.RandomState(1)
        model, t = self._fitted_pipeline(rng)
        obs.reset()
        model.transform(t)
        assert obs.registry().counter("drift.sketch_updates") == 0
        assert obs.registry().counter("drift.rows") == 0

    def test_staged_path_taps_match_fused(self, monkeypatch):
        """FMT_FUSE_TRANSFORM=0 (per-stage serving) must sketch the same
        row count as the fused path — the owner rule dedupes the second
        validating stage."""
        rng = np.random.RandomState(2)
        model, t = self._fitted_pipeline(rng)
        monkeypatch.setenv("FMT_DRIFT", "1")
        monkeypatch.setenv("FMT_DRIFT_REF_ROWS", "100000")
        monkeypatch.setenv("FMT_FUSE_TRANSFORM", "0")
        drift.reset()
        model.transform(t)
        mon = drift.default_monitor()
        with mon._lock:
            cols = dict(mon._ref)
        assert cols["features[0]"].n == t.num_rows()

    def test_server_taps_and_quarantine_rates(self, monkeypatch):
        """Through the ModelServer: live requests fill the reference,
        then the live window; a poison row is quarantined AND counted in
        the monitor's reason rates (not sketched)."""
        from flink_ml_tpu.serving import ModelServer

        rng = np.random.RandomState(3)
        model, t = self._fitted_pipeline(rng)
        monkeypatch.setenv("FMT_DRIFT_REF_ROWS", "128")
        server = ModelServer(model, drift=True, max_batch=64)
        try:
            mon = server.drift_monitor
            assert mon is not None
            for i in range(4):
                server.submit(t.slice_rows(i * 32, (i + 1) * 32)).result(
                    timeout=60)
            assert mon.reference_complete
            bad = t.slice_rows(0, 8)
            X = np.array(bad.col("features"), dtype=np.float32, copy=True)
            X[3, 1] = np.nan
            bad = Table.from_columns(bad.schema, {
                "features": X, "label": bad.col("label"),
            })
            res = server.submit(bad).result(timeout=60)
            assert res.num_quarantined == 1
            rates = mon.reason_rates()
            assert rates["live"].get("nan_inf", 0) > 0
            status = mon.status()
            assert status["live_rows"] == 7  # survivors only
        finally:
            server.shutdown()

    def test_deploy_resets_reference(self, monkeypatch):
        """A redeploy makes the new version's population the new normal:
        post-deploy shifted traffic must not burn against the OLD
        model's baseline."""
        from flink_ml_tpu.serving import ModelServer

        rng = np.random.RandomState(4)
        model, t = self._fitted_pipeline(rng)
        monkeypatch.setenv("FMT_DRIFT_REF_ROWS", "64")
        server = ModelServer(model, drift=True, max_batch=64)
        try:
            mon = server.drift_monitor
            for i in range(2):
                server.submit(t.slice_rows(i * 32, (i + 1) * 32)).result(
                    timeout=60)
            assert mon.reference_complete
            server.deploy(model, "v2")
            assert not mon.reference_complete
            assert server.active_version == "v2"
        finally:
            server.shutdown()

    def test_restart_reloads_persisted_reference(self, monkeypatch,
                                                 tmp_path):
        """A path deploy persists its frozen baseline next to the model;
        a second server over the same artifact restarts WITH it instead
        of relearning from (possibly already-shifted) traffic."""
        from flink_ml_tpu.serving import ModelServer

        rng = np.random.RandomState(5)
        model, t = self._fitted_pipeline(rng)
        model_dir = str(tmp_path / "saved")
        model.save(model_dir)
        monkeypatch.setenv("FMT_DRIFT_REF_ROWS", "64")
        server = ModelServer(path=model_dir, drift=True, max_batch=64)
        try:
            for i in range(2):
                server.submit(t.slice_rows(i * 32, (i + 1) * 32)).result(
                    timeout=60)
            assert server.drift_monitor.reference_complete
        finally:
            server.shutdown()
        assert os.path.exists(os.path.join(model_dir, drift.REFERENCE_FILE))
        server2 = ModelServer(path=model_dir, drift=True, max_batch=64)
        try:
            assert server2.drift_monitor.reference_complete
            assert server2.drift_monitor._loaded_from is not None
        finally:
            server2.shutdown()


class TestDriftSLO:
    def _burning_monitor(self, rng):
        mon = drift.DriftMonitor(name="slo-test", ref_target=100,
                                 threshold=0.2, min_window_rows=32,
                                 window=3600)
        mon.observe_input(_features_table(rng, 128), _SPEC)
        mon.roll()
        mon.observe_input(_features_table(rng, 64, shift=4.0), _SPEC)
        return mon

    def test_drift_slo_burns_and_recovers(self, monkeypatch):
        monkeypatch.setenv("FMT_FLIGHT_MIN_S", "0")
        rng = np.random.RandomState(0)
        mon = self._burning_monitor(rng)
        monitor = slo.SLOMonitor(window=3600, drift=mon)
        try:
            assert monitor.armed()
            results = monitor.sample_once()
            assert results[slo.DRIFT_SLO]["burning"]
            assert obs.registry().gauge("slo.burning.drift") == 1.0
            assert obs.registry().gauge("slo.burn_rate.drift") > 1.0
            reasons = monitor.readiness_reasons()
            assert reasons and reasons[0]["reason"] == "drift"
            # recovery: stable traffic replaces the shifted window
            mon.reset_reference()
            mon.observe_input(_features_table(rng, 128), _SPEC)
            mon.roll()
            mon.observe_input(_features_table(rng, 64), _SPEC)
            results = monitor.sample_once()
            assert not results[slo.DRIFT_SLO]["burning"]
            assert obs.registry().gauge("slo.burning.drift") == 0.0
            assert monitor.readiness_reasons() == []
        finally:
            monitor.stop()
            mon.close()

    def test_drift_breach_black_box_names_columns(self, monkeypatch,
                                                  tmp_path):
        """The dump is reason-coded ``drift_breach``; its header names
        the worst column and the ring holds one ``drift.column_breach``
        event per offending column with ref-vs-live quantiles."""
        monkeypatch.setenv("FMT_FLIGHT_MIN_S", "0")
        monkeypatch.setenv("FMT_FLIGHT_DIR", str(tmp_path / "fl"))
        rng = np.random.RandomState(1)
        mon = self._burning_monitor(rng)
        monitor = slo.SLOMonitor(window=3600, drift=mon)
        try:
            monitor.sample_once()
            path = flight.last_dump_path()
            assert path is not None and "drift_breach" in path
            with open(path) as f:
                lines = [json.loads(line) for line in f]
            header = lines[0]
            assert header["reason"] == "drift_breach"
            assert header["slo"] == "drift"
            assert header["worst_column"].startswith("features[")
            col_events = [e for e in lines[1:]
                          if e.get("kind") == "drift.column_breach"]
            assert col_events
            e = col_events[0]
            assert {"column", "psi", "ks", "ref_p50",
                    "live_p50"} <= set(e)
            # the live median really is the shifted one
            assert e["live_p50"] > e["ref_p50"] + 1.0
        finally:
            monitor.stop()
            mon.close()

    def test_min_window_gating_skips_quiet_entry(self):
        rng = np.random.RandomState(2)
        mon = drift.DriftMonitor(name="gate", ref_target=64,
                                 threshold=0.2, min_window_rows=1000,
                                 window=3600)
        monitor = slo.SLOMonitor(window=3600, drift=mon)
        try:
            mon.observe_input(_features_table(rng, 64), _SPEC)
            mon.roll()
            mon.observe_input(_features_table(rng, 64, shift=4.0), _SPEC)
            # 64 shifted live rows < min 1000: no verdict, no gauge flip
            assert monitor.sample_once() == {}
            assert obs.registry().gauge("slo.burning.drift") is None
        finally:
            monitor.stop()
            mon.close()


class TestDriftTelemetrySurfaces:
    def test_histograms_in_metrics_round_trip(self):
        """A monitor's sketches export as OpenMetrics histogram families
        that survive the strict parser, reference and live both."""
        rng = np.random.RandomState(0)
        mon = drift.DriftMonitor(name="metrics", ref_target=64,
                                 window=3600)
        try:
            mon.observe_input(_features_table(rng, 128, dim=2),
                              {"dim": 2, "vector_col": "features"})
            mon.roll()
            mon.observe_input(_features_table(rng, 32, dim=2),
                              {"dim": 2, "vector_col": "features"})
            text = telemetry.render_openmetrics()
            samples = telemetry.parse_openmetrics(text)
            ref_buckets = [k for k in samples
                           if k.startswith("fmt_drift_ref_features_0_")
                           and "_bucket" in k]
            live_buckets = [k for k in samples
                            if k.startswith("fmt_drift_live_features_0_")
                            and "_bucket" in k]
            assert ref_buckets and live_buckets
            inf_key = 'fmt_drift_ref_features_0__bucket{le="+Inf"}'
            assert samples[inf_key] == 128
            assert samples["fmt_drift_ref_features_0__count"] == 128
        finally:
            mon.close()

    def test_statusz_and_readyz_over_http(self, monkeypatch):
        """End-to-end over the real endpoint: /statusz carries the
        per-column drift section, and a burning drift SLO turns /readyz
        503 with the reason-coded ``drift`` entry."""
        rng = np.random.RandomState(1)
        from flink_ml_tpu.serving import ModelServer

        monkeypatch.setenv("FMT_DRIFT_REF_ROWS", "64")
        model, t = TestDriftTaps()._fitted_pipeline(rng)
        server = ModelServer(model, drift=True, max_batch=64,
                             telemetry_port=0)
        try:
            for i in range(2):
                server.submit(t.slice_rows(i * 32, (i + 1) * 32)).result(
                    timeout=60)
            Xs = (rng.randn(64, 4) + 5).astype(np.float32)
            shifted = Table.from_columns(t.schema, {
                "features": Xs, "label": np.zeros(64),
            })
            server.submit(shifted).result(timeout=60)
            server._slo.sample_once()

            def get(path):
                url = server.telemetry.url(path)
                try:
                    with urllib.request.urlopen(url, timeout=10) as r:
                        return r.status, r.read().decode()
                except urllib.error.HTTPError as exc:
                    return exc.code, exc.read().decode()

            code, body = get("/statusz")
            assert code == 200
            status = json.loads(body)
            assert status["drift"]["reference"]["complete"]
            assert status["drift"]["columns"]
            code, body = get("/readyz")
            assert code == 503
            reasons = [r["reason"] for r in json.loads(body)["reasons"]]
            assert "drift" in reasons
        finally:
            server.shutdown()


class TestHistogramParserStrictness:
    def _wrap(self, *lines):
        return "\n".join(lines + ("# EOF",)) + "\n"

    def test_valid_histogram_parses(self):
        text = self._wrap(
            "# TYPE h histogram",
            'h_bucket{le="1"} 3',
            'h_bucket{le="2.5"} 7',
            'h_bucket{le="+Inf"} 9',
            "h_count 9",
            "h_sum 14.5",
        )
        samples = telemetry.parse_openmetrics(text)
        assert samples['h_bucket{le="2.5"}'] == 7
        assert samples["h_count"] == 9

    def test_rejects_non_cumulative_buckets(self):
        text = self._wrap(
            "# TYPE h histogram",
            'h_bucket{le="1"} 5',
            'h_bucket{le="2"} 3',
            'h_bucket{le="+Inf"} 5',
            "h_count 5",
        )
        with pytest.raises(ValueError, match="cumulative"):
            telemetry.parse_openmetrics(text)

    def test_rejects_non_ascending_bounds(self):
        text = self._wrap(
            "# TYPE h histogram",
            'h_bucket{le="2"} 3',
            'h_bucket{le="1"} 3',
            'h_bucket{le="+Inf"} 3',
            "h_count 3",
        )
        with pytest.raises(ValueError, match="ascending"):
            telemetry.parse_openmetrics(text)

    def test_rejects_missing_inf_bucket(self):
        text = self._wrap(
            "# TYPE h histogram",
            'h_bucket{le="1"} 3',
        )
        with pytest.raises(ValueError, match=r"\+Inf"):
            telemetry.parse_openmetrics(text)

    def test_rejects_count_mismatch(self):
        text = self._wrap(
            "# TYPE h histogram",
            'h_bucket{le="+Inf"} 3',
            "h_count 4",
        )
        with pytest.raises(ValueError, match="_count"):
            telemetry.parse_openmetrics(text)

    def test_rejects_le_on_summary(self):
        text = self._wrap(
            "# TYPE s summary",
            's{le="1"} 3',
        )
        with pytest.raises(ValueError, match="belong"):
            telemetry.parse_openmetrics(text)

    def test_rejects_bucket_on_counter(self):
        text = self._wrap(
            "# TYPE c counter",
            'c_bucket{le="1"} 3',
        )
        with pytest.raises(ValueError, match="belong"):
            telemetry.parse_openmetrics(text)

    def test_render_parse_round_trip_with_provider(self):
        key = telemetry.register_histograms("rt", lambda: {
            "rt.lat": ([0.5, 1.0, 5.0], [2, 5, 9], 12.5, 9),
        })
        try:
            obs.counter_add("c.x", 3)
            text = telemetry.render_openmetrics()
            samples = telemetry.parse_openmetrics(text)
            assert samples['fmt_rt_lat_bucket{le="0.5"}'] == 2
            assert samples['fmt_rt_lat_bucket{le="+Inf"}'] == 9
            assert samples["fmt_rt_lat_count"] == 9
            assert samples["fmt_rt_lat_sum"] == 12.5
            assert samples["fmt_c_x_total"] == 3
        finally:
            telemetry.unregister_histograms(key)

    def test_empty_provider_histogram(self):
        key = telemetry.register_histograms("empty", lambda: {
            "empty.h": ([], [], 0.0, 0),
        })
        try:
            samples = telemetry.parse_openmetrics(
                telemetry.render_openmetrics())
            assert samples['fmt_empty_h_bucket{le="+Inf"}'] == 0
            assert samples["fmt_empty_h_count"] == 0
        finally:
            telemetry.unregister_histograms(key)

    def test_broken_provider_never_kills_a_scrape(self):
        def boom():
            raise RuntimeError("provider died")

        key = telemetry.register_histograms("boom", boom)
        try:
            obs.counter_add("c.ok", 1)
            samples = telemetry.parse_openmetrics(
                telemetry.render_openmetrics())
            assert samples["fmt_c_ok_total"] == 1
        finally:
            telemetry.unregister_histograms(key)


class TestDriftReportsAndCLI:
    def test_serving_report_carries_drift_and_check_prints_line(
            self, monkeypatch, tmp_path, capsys):
        from flink_ml_tpu.obs.report import drift_runs, load_reports
        from flink_ml_tpu.serving import ModelServer

        reports_dir = str(tmp_path / "reports")
        monkeypatch.setenv("FMT_OBS_REPORTS", reports_dir)
        monkeypatch.setenv("FMT_DRIFT_REF_ROWS", "64")
        rng = np.random.RandomState(0)
        model, t = TestDriftTaps()._fitted_pipeline(rng)
        server = ModelServer(model, drift=True, max_batch=64)
        try:
            for i in range(2):
                server.submit(t.slice_rows(i * 32, (i + 1) * 32)).result(
                    timeout=60)
            Xs = (rng.randn(64, 4) + 5).astype(np.float32)
            server.submit(Table.from_columns(t.schema, {
                "features": Xs, "label": np.zeros(64),
            })).result(timeout=60)
        finally:
            server.shutdown()
        reports = load_reports(reports_dir)
        rows = drift_runs(reports)
        assert rows and rows[0]["kind"] == "serving"
        assert rows[0]["reference_complete"]
        assert rows[0]["breaching"]
        # the CLI renders the same report
        rc = drift.drift_main(["--reports", reports_dir])
        out = capsys.readouterr().out
        assert rc == 0
        assert "BREACH" in out
        assert "features[" in out

    def test_check_json_includes_drift_rows(self, monkeypatch, tmp_path):
        from flink_ml_tpu.obs.report import RunReport, main, \
            write_run_report

        reports_dir = str(tmp_path / "reports")
        report = RunReport(
            kind="serving", name="ModelServer", ts=1.0, git_sha="abc",
            device={"backend": "cpu"},
            extra={"drift": {
                "monitor": "serving", "reference_complete": True,
                "threshold": 0.2, "live_rows": 100,
                "columns": [{"column": "pred", "psi": 0.5, "ks": 0.4,
                             "ref": {"p05": 0, "p50": 1, "p95": 2},
                             "live": {"p05": 2, "p50": 3, "p95": 4}}],
            }},
        )
        write_run_report(report, reports_dir)
        import io
        from contextlib import redirect_stdout

        buf = io.StringIO()
        with redirect_stdout(buf):
            main(["--reports", reports_dir, "--json",
                  "--baseline", os.path.join(
                      os.path.dirname(os.path.dirname(
                          os.path.abspath(__file__))), "BASELINE.json")])
        payload = json.loads(buf.getvalue())
        assert payload["drift"]
        assert payload["drift"][0]["worst_column"] == "pred"
        assert payload["drift"][0]["breaching"] is True

    def test_cli_renders_persisted_reference(self, tmp_path, capsys):
        rng = np.random.RandomState(1)
        model_dir = tmp_path / "model"
        model_dir.mkdir()
        mon = drift.DriftMonitor(name="cli", ref_target=64,
                                 persist_path=str(model_dir))
        try:
            mon.observe_input(_features_table(rng, 128), _SPEC)
            mon.roll()
        finally:
            mon.close()
        rc = drift.drift_main(["--ref", str(model_dir)])
        out = capsys.readouterr().out
        assert rc == 0
        assert "features[0]" in out
