"""Fault-tolerance layer (ISSUE 3): injection, retry, watchdog, guarded
fits, spill/checkpoint crash-consistency, SIGTERM kill-and-resume."""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from flink_ml_tpu import obs
from flink_ml_tpu.fault import guard, injection, retry, watchdog
from flink_ml_tpu.fault.injection import InjectedFault

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_fault_state(tmp_path, monkeypatch):
    # fit RunReports must land in a per-test dir, never the committed
    # reports/ (chaos counters there would pollute every obs --check)
    monkeypatch.setenv("FMT_OBS_REPORTS", str(tmp_path / "_reports"))
    injection.reset()
    guard.reset_preempted()
    yield
    injection.reset()
    guard.reset_preempted()
    obs.disable()
    obs.reset()


def _dense_table(n=256, dim=5, seed=3):
    from flink_ml_tpu.table.schema import DataTypes, Schema
    from flink_ml_tpu.table.table import Table

    rng = np.random.RandomState(seed)
    X = rng.randn(n, dim).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float64)
    return Table.from_columns(
        Schema.of(("features", DataTypes.DENSE_VECTOR), ("label", "double")),
        {"features": X, "label": y},
    )


def _logreg(lr=0.5, iters=3, **extra):
    from flink_ml_tpu.lib import LogisticRegression

    est = (
        LogisticRegression().set_vector_col("features")
        .set_label_col("label").set_prediction_col("p")
        .set_learning_rate(lr).set_max_iter(iters)
    )
    for k, v in extra.items():
        getattr(est, f"set_{k}")(v)
    return est


class TestInjectionRegistry:
    def test_nth_call_fires_once(self):
        injection.configure("x.y@2")
        injection.maybe_fail("x.y")  # call 1 passes
        with pytest.raises(InjectedFault):
            injection.maybe_fail("x.y")  # call 2 fires
        injection.maybe_fail("x.y")  # call 3 passes again
        assert injection.fire_count("x.y") == 1

    def test_sticky_fires_from_n(self):
        injection.configure("x.y@2+")
        injection.maybe_fail("x.y")
        for _ in range(3):
            with pytest.raises(InjectedFault):
                injection.maybe_fail("x.y")
        assert injection.fire_count("x.y") == 3

    def test_probability_mode_is_seeded_deterministic(self):
        def run(seed):
            injection.configure("p~0.5", seed=seed)
            fired = []
            for i in range(32):
                try:
                    injection.maybe_fail("p")
                    fired.append(0)
                except InjectedFault:
                    fired.append(1)
            return fired

        a, b = run(7), run(7)
        assert a == b and 0 < sum(a) < 32
        assert run(8) != a  # a different seed is a different schedule

    def test_unknown_point_and_inactive_are_noops(self):
        injection.maybe_fail("never.configured")
        injection.configure("a@1")
        injection.maybe_fail("other.point")
        assert not injection.fire_count()

    def test_bad_spec_rejected(self):
        with pytest.raises(ValueError):
            injection.configure("point-without-schedule")
        with pytest.raises(ValueError):
            injection.configure("x@0")


class TestRetry:
    def test_transient_retried_then_succeeds(self):
        obs.enable()
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise OSError("blip")
            return "ok"

        policy = retry.RetryPolicy(attempts=3, base_delay_s=0.001)
        assert retry.with_retry(flaky, "t", policy) == "ok"
        assert obs.registry().counter("fault.retries") == 2
        assert obs.registry().counter("fault.retries.t") == 2

    def test_nontransient_raises_immediately(self):
        calls = {"n": 0}

        def bug():
            calls["n"] += 1
            raise ValueError("real bug")

        with pytest.raises(ValueError):
            retry.with_retry(bug, "t", retry.RetryPolicy(attempts=5))
        assert calls["n"] == 1

    def test_giveup_reraises_and_counts(self):
        obs.enable()

        def always():
            raise OSError("down")

        with pytest.raises(OSError):
            retry.with_retry(
                always, "t", retry.RetryPolicy(attempts=2, base_delay_s=0.001)
            )
        assert obs.registry().counter("fault.giveups") == 1

    def test_transient_statuses(self):
        assert retry.is_transient(InjectedFault("x", 1))
        assert retry.is_transient(OSError())
        assert retry.is_transient(RuntimeError("RESOURCE_EXHAUSTED: oom"))
        assert not retry.is_transient(RuntimeError("shape mismatch"))
        assert not retry.is_transient(ValueError("nope"))

    def test_backoff_grows_and_caps(self):
        p = retry.RetryPolicy(attempts=9, base_delay_s=0.1, max_delay_s=0.4,
                              factor=2.0, jitter=0.0)
        assert [p.delay(k) for k in (1, 2, 3, 4)] == [0.1, 0.2, 0.4, 0.4]


class TestWatchdog:
    def test_timeout_names_the_collective(self):
        t0 = time.perf_counter()
        with pytest.raises(watchdog.CollectiveTimeoutError) as ei:
            watchdog.with_timeout(
                lambda: time.sleep(30), "agree_max", timeout_s=0.3
            )
        assert time.perf_counter() - t0 < 5.0
        assert "agree_max" in str(ei.value)
        assert "FMT_AGREE_TIMEOUT_S" in str(ei.value)

    def test_result_and_errors_pass_through(self):
        assert watchdog.with_timeout(lambda: 42, "x", timeout_s=1.0) == 42

        def boom():
            raise ValueError("the collective's own error")

        with pytest.raises(ValueError, match="own error"):
            watchdog.with_timeout(boom, "x", timeout_s=1.0)

    def test_zero_timeout_is_identity(self, monkeypatch):
        monkeypatch.delenv("FMT_AGREE_TIMEOUT_S", raising=False)
        assert watchdog.with_timeout(lambda: "v", "x") == "v"

    def test_agree_max_dead_peer_raises_diagnostic(self, monkeypatch):
        """The acceptance scenario: a dead peer wedges the allgather;
        agree_max must raise the watchdog diagnostic, not hang."""
        import jax
        from jax.experimental import multihost_utils

        from flink_ml_tpu.parallel import mesh

        monkeypatch.setattr(jax, "process_count", lambda: 2)
        monkeypatch.setattr(
            multihost_utils, "process_allgather",
            lambda *_a, **_k: time.sleep(60),
        )
        monkeypatch.setenv("FMT_AGREE_TIMEOUT_S", "0.3")
        with pytest.raises(watchdog.CollectiveTimeoutError) as ei:
            mesh.agree_max(3)
        assert ei.value.collective == "agree_max"

    def test_agree_injection_point(self):
        from flink_ml_tpu.parallel import mesh

        injection.configure("agree@1")
        with pytest.raises(InjectedFault):
            mesh.agree_max(1)


class TestGuard:
    def test_check_health_raises_on_nonfinite(self):
        guard.check_health([0.5, 0.2], [np.ones(3)])  # healthy: no raise
        # a transient early overflow the run RECOVERED from is healthy —
        # only the current (last) loss judges the state
        guard.check_health([float("inf"), 0.5], [np.ones(3)])
        with pytest.raises(guard.NumericHealthError):
            guard.check_health([0.5, float("nan")], [])
        with pytest.raises(guard.NumericHealthError):
            guard.check_health([], [np.array([1.0, np.inf])])
        with pytest.raises(guard.NumericHealthError):
            guard.check_health([], [], delta=float("nan"))

    def test_check_health_disabled(self, monkeypatch):
        monkeypatch.setenv("FMT_GUARD", "0")
        guard.check_health([float("nan")], [])  # no raise

    def test_run_guarded_backs_off_and_recovers(self, monkeypatch):
        monkeypatch.setenv("FMT_GUARD_LR_BACKOFF", "0.25")
        obs.enable()
        seen = []

        def attempt(scale):
            seen.append(scale)
            if len(seen) < 3:
                raise guard.NumericHealthError("diverged")
            return "model"

        with pytest.warns(RuntimeWarning):
            assert guard.run_guarded(attempt) == "model"
        assert seen == [1.0, 0.25, 0.0625]
        assert obs.registry().counter("fault.rollbacks") == 2

    def test_run_guarded_gives_up_with_history(self, monkeypatch):
        monkeypatch.setenv("FMT_GUARD_MAX_RETRIES", "1")

        def attempt(scale):
            raise guard.NumericHealthError("still bad")

        with pytest.warns(RuntimeWarning), \
                pytest.raises(guard.NumericHealthError, match="2 attempt"):
            guard.run_guarded(attempt)

    def test_diverged_fit_rolls_back_to_colder_lr(self, monkeypatch):
        """End to end: an absurd learning rate drives the fused GLM fit to
        non-finite params; the guard retries at a backed-off scale and the
        returned model is finite, with the rollback accounted."""
        from flink_ml_tpu.lib import LinearRegression

        monkeypatch.setenv("FMT_GUARD_LR_BACKOFF", "1e-9")
        obs.enable()
        t = _dense_table()
        est = (
            LinearRegression().set_vector_col("features")
            .set_label_col("label").set_prediction_col("p")
            .set_learning_rate(1e6).set_max_iter(6)  # squared loss explodes
        )
        with pytest.warns(RuntimeWarning):
            model = est.fit(t)
        assert np.all(np.isfinite(model.coefficients()))
        assert obs.registry().counter("fault.rollbacks") >= 1
        snap = obs.registry().snapshot()["counters"]
        assert snap.get("fault.numeric_errors", 0) >= 1


class TestPlacementFaults:
    def test_injected_placement_fault_is_retried(self):
        """A transient H2D failure inside the pooled cold placement is
        retried with backoff; the fit completes and matches fault-free."""
        t = _dense_table()
        reference = _logreg().fit(t).coefficients()
        from flink_ml_tpu.table import slab_pool

        slab_pool.reset_pool()
        obs.enable()
        injection.configure("place.h2d@1")
        model = _logreg().fit(_dense_table())
        np.testing.assert_array_equal(model.coefficients(), reference)
        assert obs.registry().counter("fault.retries") >= 1
        assert injection.fire_count("place.h2d") == 1

    def test_pool_lookup_fault_degrades_to_streamed_placement(self):
        t = _dense_table()
        reference = _logreg().fit(t).coefficients()
        from flink_ml_tpu.table import slab_pool

        slab_pool.reset_pool()
        obs.enable()
        injection.configure("slab.lookup@1")
        with pytest.warns(RuntimeWarning, match="falling back"):
            model = _logreg().fit(_dense_table())
        np.testing.assert_array_equal(model.coefficients(), reference)
        assert obs.registry().counter("fault.fallbacks") >= 1

    def test_prefetch_producer_fault_surfaces_at_consumer(self):
        from flink_ml_tpu.utils.prefetch import prefetch_iter

        injection.configure("prefetch.produce@3")
        out = []
        with pytest.raises(InjectedFault):
            for x in prefetch_iter(iter(range(6)), depth=2, name="t"):
                out.append(x)
        assert out == [0, 1]


class TestSpillFaults:
    def _factory(self, n_blocks=3, dim=3):
        def factory():
            for i in range(n_blocks):
                yield (
                    np.full((4, dim), i, np.float32),
                    np.arange(4, dtype=np.float32) + i,
                ), 4

        return factory

    def test_partial_write_restarts_clean(self, tmp_path):
        """RED for the pre-fix BlockSpill: an interrupted first epoch left
        stale meta + orphan blocks, and the restarted save APPENDED to
        them — replay then yielded the dead attempt's blocks too."""
        from flink_ml_tpu.lib.out_of_core import BlockSpill

        spill = BlockSpill(str(tmp_path / "s"))
        good = self._factory(3)

        def dying():
            yield from list(good())[:2]
            raise RuntimeError("interrupted mid-iteration")

        with pytest.raises(RuntimeError, match="interrupted"):
            list(spill.wrap(lambda: dying())())
        assert not spill.complete
        # orphan artifacts of the dead attempt are on disk (the red
        # precondition the restart must truncate)
        assert any(
            f.startswith("block-") for f in os.listdir(spill.directory)
        )
        out = list(spill.wrap(good)())
        assert len(out) == 3 and spill.complete
        replay = list(spill.wrap(good)())
        assert len(replay) == 3
        for (got, n), (want, wn) in zip(replay, good()):
            assert n == wn
            np.testing.assert_array_equal(np.asarray(got[0]), want[0])
            np.testing.assert_array_equal(np.asarray(got[1]), want[1])
        spill.close()
        assert not os.path.exists(spill.directory)

    def test_corrupted_block_rebuilds_from_source(self, tmp_path):
        from flink_ml_tpu.lib.out_of_core import BlockSpill

        obs.enable()
        spill = BlockSpill(str(tmp_path / "s"))
        good = self._factory(3)
        list(spill.wrap(good)())  # epoch 1: save
        with open(spill._path(1, 0), "r+b") as f:  # truncate a leaf
            f.truncate(8)
        with pytest.warns(RuntimeWarning, match="rebuilding"):
            out = list(spill.wrap(good)())  # epoch 2: rebuild, no crash
        assert len(out) == 3
        assert obs.registry().counter("fault.spill_rebuilds") == 1
        # the rebuild recommitted valid blocks: replay works again
        replay = list(spill.wrap(good)())
        np.testing.assert_array_equal(
            np.asarray(replay[1][0][0]), list(good())[1][0][0]
        )

    def test_flipped_byte_caught_by_crc(self, tmp_path):
        from flink_ml_tpu.lib.out_of_core import BlockSpill

        spill = BlockSpill(str(tmp_path / "s"))
        good = self._factory(2)
        list(spill.wrap(good)())
        p = spill._path(0, 0)
        size = os.path.getsize(p)
        with open(p, "r+b") as f:  # same length, different content
            f.seek(size - 4)
            f.write(b"\xff\xff\xff\xff")
        with pytest.warns(RuntimeWarning, match="rebuilding"):
            list(spill.wrap(good)())

    def test_injected_spill_read_fault_rebuilds(self, tmp_path):
        from flink_ml_tpu.lib.out_of_core import BlockSpill

        spill = BlockSpill(str(tmp_path / "s"))
        good = self._factory(2)
        list(spill.wrap(good)())
        injection.configure("spill.read@1")
        with pytest.warns(RuntimeWarning, match="rebuilding"):
            out = list(spill.wrap(good)())
        assert len(out) == 2

    def test_spill_write_fault_retried_transparently(self, tmp_path):
        from flink_ml_tpu.lib.out_of_core import BlockSpill

        obs.enable()
        injection.configure("spill.write@2")
        spill = BlockSpill(str(tmp_path / "s"))
        good = self._factory(3)
        out = list(spill.wrap(good)())
        assert len(out) == 3 and spill.complete
        assert obs.registry().counter("fault.retries.spill.write") == 1
        assert len(list(spill.wrap(good)())) == 3  # replay valid

    def test_streamed_fit_with_spill_corruption_matches_fault_free(
        self, tmp_path
    ):
        """Chaos parity, spill leg: a corrupted spill read mid-fit must
        not change the trained model (the epoch rebuilds from source)."""
        from flink_ml_tpu.table.schema import Schema
        from flink_ml_tpu.table.sources import ChunkedTable, CollectionSource

        rng = np.random.RandomState(5)
        X = rng.randn(200, 4)
        y = (X[:, 0] > 0).astype(np.float64)
        rows = [tuple(X[i]) + (y[i],) for i in range(200)]
        schema = Schema([f"f{i}" for i in range(4)] + ["label"],
                        ["double"] * 5)

        def fit():
            from flink_ml_tpu.lib import LogisticRegression

            return (
                LogisticRegression()
                .set_feature_cols([f"f{i}" for i in range(4)])
                .set_label_col("label").set_prediction_col("p")
                .set_learning_rate(0.5).set_max_iter(3)
                .set_global_batch_size(32)
                .fit(ChunkedTable(CollectionSource(rows, schema), 64,
                                  spill=True))
            )

        reference = fit().coefficients()
        injection.configure("spill.read@1")
        with pytest.warns(RuntimeWarning, match="rebuilding"):
            model = fit()
        np.testing.assert_array_equal(model.coefficients(), reference)


class TestCheckpointCrashConsistency:
    def test_orphan_sidecar_swept_on_scan(self, tmp_path):
        from flink_ml_tpu.iteration.checkpoint import (
            latest_checkpoint,
            save_checkpoint,
        )

        save_checkpoint(str(tmp_path), 2, (np.arange(3.0),))
        orphan = tmp_path / "epoch_5.npz.meta.json"
        orphan.write_text(json.dumps({"epoch": 5}))
        stale_tmp = tmp_path / "epoch_6.npz.tmp"
        stale_tmp.write_bytes(b"partial")
        latest = latest_checkpoint(str(tmp_path))
        assert latest is not None and latest.endswith("epoch_2.npz")
        assert not orphan.exists()
        assert not stale_tmp.exists()

    def test_data_written_before_meta(self, tmp_path, monkeypatch):
        """A crash during the DATA write must leave NO sidecar (meta is
        the commit record, written last) — the pre-fix order stranded an
        orphan sidecar describing data that never existed."""
        from flink_ml_tpu.iteration.checkpoint import (
            load_checkpoint,
            save_checkpoint,
        )

        monkeypatch.setenv("FMT_RETRY_ATTEMPTS", "1")
        injection.configure("ckpt.save@1")
        params = (np.arange(4.0),)
        with pytest.raises(InjectedFault):
            save_checkpoint(str(tmp_path), 0, params)
        assert not any(
            n.endswith(".meta.json") for n in os.listdir(tmp_path)
        ), "orphan sidecar committed before its data"
        injection.reset()
        path = save_checkpoint(str(tmp_path), 0, params)
        loaded, meta = load_checkpoint(path, like=params)
        np.testing.assert_array_equal(loaded[0], params[0])
        assert meta["epoch"] == 0

    def test_save_fault_retried(self, tmp_path):
        from flink_ml_tpu.iteration.checkpoint import (
            load_checkpoint,
            save_checkpoint,
        )

        obs.enable()
        injection.configure("ckpt.save@1")
        params = (np.arange(4.0),)
        path = save_checkpoint(str(tmp_path), 1, params)
        assert obs.registry().counter("fault.retries.ckpt.save") == 1
        loaded, meta = load_checkpoint(path, like=params)
        np.testing.assert_array_equal(loaded[0], params[0])


class TestObsFlagging:
    def test_fault_assisted_runs_flagged(self):
        from flink_ml_tpu.obs.report import fault_assisted_runs

        reports = [
            {"kind": "fit", "name": "A", "git_sha": "x",
             "metrics": {"counters": {"fault.retries": 2.0,
                                      "fault.retries.ckpt.save": 2.0,
                                      "train.epochs": 3}}},
            {"kind": "fit", "name": "B",
             "metrics": {"counters": {"train.epochs": 3}}},
            {"kind": "bench", "name": "C",
             "metrics": {"counters": {"fault.retries": 1}}},
            {"kind": "fit", "name": "D",
             "metrics": {"counters": {"fault.rollbacks": 1}}},
        ]
        flagged = fault_assisted_runs(reports)
        assert [f["name"] for f in flagged] == ["A", "D"]
        assert flagged[0]["fault_counters"] == {
            "fault.retries": 2.0, "fault.retries.ckpt.save": 2.0,
        }

    def test_retrying_fit_report_carries_fault_delta(self, tmp_path,
                                                     monkeypatch):
        """End to end: a fit that passed only by retrying writes a
        RunReport whose per-fit counter delta the CLI flags."""
        monkeypatch.setenv("FMT_OBS_REPORTS", str(tmp_path))
        from flink_ml_tpu.obs.report import fault_assisted_runs, load_reports
        from flink_ml_tpu.table import slab_pool

        slab_pool.reset_pool()
        obs.enable()
        injection.configure("place.h2d@1")
        _logreg().fit(_dense_table(seed=21))
        flagged = fault_assisted_runs(load_reports(str(tmp_path)))
        assert flagged and flagged[-1]["name"] == "LogisticRegression"
        assert flagged[-1]["fault_counters"].get("fault.retries", 0) >= 1


class TestPreemption:
    N, DIM, CHUNK = 192, 4, 48

    def _chunked(self, kill_at=None):
        from flink_ml_tpu.table.schema import Schema
        from flink_ml_tpu.table.sources import ChunkedTable, CollectionSource

        rng = np.random.RandomState(9)
        X = rng.randn(self.N, self.DIM)
        y = (X @ rng.randn(self.DIM) > 0).astype(np.float64)
        rows = [tuple(X[i]) + (y[i],) for i in range(self.N)]
        schema = Schema([f"f{i}" for i in range(self.DIM)] + ["label"],
                        ["double"] * (self.DIM + 1))
        source = CollectionSource(rows, schema)

        class Killing(ChunkedTable):
            served = 0

            def chunks(inner):
                for t in super().chunks():
                    Killing.served += 1
                    if Killing.served == kill_at:
                        os.kill(os.getpid(), signal.SIGTERM)
                    yield t

        cls = ChunkedTable if kill_at is None else Killing
        return cls(source, self.CHUNK)

    def _fit(self, table, ckpt_dir):
        from flink_ml_tpu.lib import LogisticRegression

        return (
            LogisticRegression()
            .set_feature_cols([f"f{i}" for i in range(self.DIM)])
            .set_label_col("label").set_prediction_col("p")
            .set_learning_rate(0.5).set_max_iter(4)
            .set_global_batch_size(32)
            .set_checkpoint_dir(str(ckpt_dir)).set_checkpoint_interval(2)
            .fit(table)
        )

    def test_sigterm_mid_epoch_emergency_checkpoint_then_exact_resume(
        self, tmp_path
    ):
        """SIGTERM lands mid-epoch-1 of a streamed fit; the guard finishes
        the epoch, commits an emergency snapshot OFF the every-2-epochs
        cadence, raises a clean SystemExit(0) — and the resumed run is
        bit-identical to the uninterrupted one."""
        from flink_ml_tpu.iteration.checkpoint import latest_checkpoint

        obs.enable()
        reference = self._fit(self._chunked(), tmp_path / "ref")

        with pytest.warns(RuntimeWarning, match="emergency"), \
                pytest.raises(SystemExit) as ei:
            self._fit(self._chunked(kill_at=2), tmp_path / "c")
        assert ei.value.code == 0
        # only epoch 1 completed -> emergency snapshot epoch_0: off the
        # every-2-epochs cadence (the first regular boundary is epoch_1)
        latest = latest_checkpoint(str(tmp_path / "c"))
        assert latest is not None and latest.endswith("epoch_0.npz")
        assert obs.registry().counter("fault.emergency_checkpoints") == 1

        guard.reset_preempted()
        resumed = self._fit(self._chunked(), tmp_path / "c")
        np.testing.assert_array_equal(
            resumed.coefficients(), reference.coefficients()
        )
        assert resumed.intercept() == reference.intercept()

    def test_preemption_on_finished_run_returns_result(self, tmp_path,
                                                       monkeypatch):
        """A SIGTERM that lands on the run's FINAL epoch must not discard
        the completed fit for a pointless resume round-trip: the driver
        returns the result (the listener-path driver used to exit)."""
        import flink_ml_tpu.fault as fault_pkg

        monkeypatch.setattr(fault_pkg, "preempted", lambda: True)
        from flink_ml_tpu.lib import LogisticRegression

        model = (
            LogisticRegression().set_vector_col("features")
            .set_label_col("label").set_prediction_col("p")
            .set_learning_rate(0.5).set_max_iter(1)
            .set_checkpoint_dir(str(tmp_path / "ck"))
            .set_checkpoint_interval(1)
            .fit(_dense_table())
        )
        assert model.train_epochs_ == 1
        assert np.all(np.isfinite(model.coefficients()))

    def test_subprocess_kill_and_resume_bit_identical(self, tmp_path):
        """The satellite's full scenario in real processes: worker dies to
        a delivered SIGTERM with exit code 0, a fresh process resumes, and
        the final params match an uninterrupted worker bit-for-bit."""
        worker = os.path.join(REPO, "tests", "ooc_preempt_worker.py")
        env = {k: v for k, v in os.environ.items()
               if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}

        def run(phase, ckpt):
            return subprocess.run(
                [sys.executable, worker, phase, str(ckpt)],
                capture_output=True, text=True, timeout=240, env=env,
            )

        plain = run("plain", tmp_path / "ref")
        assert plain.returncode == 0, plain.stderr
        ref_line = [ln for ln in plain.stdout.splitlines()
                    if ln.startswith("PARAMS")]
        assert ref_line, plain.stdout

        crashed = run("crash", tmp_path / "c")
        assert crashed.returncode == 0, (crashed.stdout, crashed.stderr)
        assert "PARAMS" not in crashed.stdout  # died before completion
        assert os.listdir(tmp_path / "c"), "no emergency checkpoint"

        resumed = run("resume", tmp_path / "c")
        assert resumed.returncode == 0, resumed.stderr
        res_line = [ln for ln in resumed.stdout.splitlines()
                    if ln.startswith("PARAMS")]
        assert res_line == ref_line  # bit-identical
