"""Categorical encoding head (lib/encoding.py): StringIndexer ->
OneHotEncoder -> sparse LogisticRegression, columnar end-to-end — the
Criteo-shaped pipeline the reference's colname/merge-rule design serves
(HasSelectedCol.java:33-47, OutputColsHelper.java:32-52)."""

import numpy as np
import pytest

from flink_ml_tpu.api.pipeline import Pipeline
from flink_ml_tpu.lib import (
    BinaryClassificationEvaluator,
    LogisticRegression,
    OneHotEncoder,
    StringIndexer,
)
from flink_ml_tpu.ops.batch import CsrRows
from flink_ml_tpu.table.schema import DataTypes, Schema
from flink_ml_tpu.table.table import Table

CAT_SCHEMA = Schema.of(
    ("c0", DataTypes.STRING), ("c1", DataTypes.STRING),
    ("label", DataTypes.DOUBLE),
)


def _cat_table(n=600, seed=0):
    rng = np.random.RandomState(seed)
    c0 = rng.choice(["red", "green", "blue", "cyan"], n,
                    p=[0.5, 0.3, 0.15, 0.05])
    c1 = rng.choice([f"v{i}" for i in range(8)], n)
    # label depends on the categories so the pipeline can learn it
    w0 = {"red": 1.2, "green": -0.8, "blue": 0.3, "cyan": -1.5}
    w1 = {f"v{i}": ((i % 3) - 1) * 0.9 for i in range(8)}
    score = np.asarray([w0[a] + w1[b] for a, b in zip(c0, c1)])
    y = (score + 0.2 * rng.randn(n) > 0).astype(np.float64)
    return Table.from_columns(
        CAT_SCHEMA,
        {"c0": c0.astype(object), "c1": c1.astype(object), "label": y},
    )


class TestStringIndexer:
    def test_frequency_desc_default_order(self):
        t = _cat_table()
        model = (StringIndexer().set_selected_cols(["c0"])
                 .set_output_cols(["i0"]).fit(t))
        (out,) = model.transform(t)
        idx = np.asarray(out.col("i0"))
        c0 = [str(v) for v in t.col("c0")]
        # most frequent value gets index 0
        assert idx[c0.index("red")] == 0.0
        assert idx[c0.index("cyan")] == 3.0
        # input columns survive (reserve-all default)
        assert "c0" in out.schema.field_names
        assert "label" in out.schema.field_names

    def test_alphabet_order_and_in_place_overwrite(self):
        t = _cat_table()
        model = (StringIndexer().set_selected_cols(["c0", "c1"])
                 .set_string_order_type("alphabetAsc").fit(t))
        (out,) = model.transform(t)
        # outputCols null -> overwrite in place
        assert np.asarray(out.col("c0")).dtype == np.float64
        c0 = [str(v) for v in t.col("c0")]
        idx = np.asarray(out.col("c0"))
        assert idx[c0.index("blue")] == 0.0  # alphabetically first

    def test_unseen_value_error_and_keep(self):
        t = _cat_table()
        model = (StringIndexer().set_selected_cols(["c0"])
                 .set_output_cols(["i0"]).fit(t))
        novel = Table.from_columns(
            CAT_SCHEMA,
            {"c0": np.asarray(["purple"], dtype=object),
             "c1": np.asarray(["v0"], dtype=object),
             "label": np.asarray([1.0])},
        )
        with pytest.raises(ValueError, match="unseen"):
            model.transform(novel)
        model.set_handle_invalid("keep")
        (out,) = model.transform(novel)
        assert np.asarray(out.col("i0"))[0] == 4.0  # extra slot

    def test_save_load_roundtrip(self, tmp_path):
        from flink_ml_tpu.api.core import Stage

        t = _cat_table()
        model = (StringIndexer().set_selected_cols(["c0"])
                 .set_output_cols(["i0"]).fit(t))
        model.save(str(tmp_path / "si"))
        loaded = Stage.load(str(tmp_path / "si"))
        (a,) = model.transform(t)
        (b,) = loaded.transform(t)
        np.testing.assert_array_equal(
            np.asarray(a.col("i0")), np.asarray(b.col("i0"))
        )


class TestOneHotEncoder:
    def test_offset_stacked_csr_output(self):
        t = _cat_table()
        indexer = (StringIndexer().set_selected_cols(["c0", "c1"])
                   .set_output_cols(["i0", "i1"]).fit(t))
        (indexed,) = indexer.transform(t)
        enc = (OneHotEncoder().set_selected_cols(["i0", "i1"])
               .set_output_col("features").fit(indexed))
        assert enc.total_size() == 4 + 8
        (out,) = enc.transform(indexed)
        feats = out.col("features")
        assert isinstance(feats, CsrRows)
        assert feats.dim == 12
        # two slots per row: one in [0,4), one in [4,12)
        assert np.all(np.diff(feats.indptr) == 2)
        first = feats.indices[feats.indptr[:-1]]
        second = feats.indices[feats.indptr[:-1] + 1]
        assert np.all((first >= 0) & (first < 4))
        assert np.all((second >= 4) & (second < 12))
        np.testing.assert_array_equal(feats.values, 1.0)

    def test_rejects_non_integer_indices(self):
        t = Table.from_columns(
            Schema.of(("i0", DataTypes.DOUBLE)),
            {"i0": np.asarray([0.0, 1.5])},
        )
        with pytest.raises(ValueError, match="integer"):
            OneHotEncoder().set_selected_cols(["i0"]) \
                .set_output_col("f").fit(t)

    def test_out_of_range_error_and_keep_bucket(self):
        fit_t = Table.from_columns(
            Schema.of(("i0", DataTypes.DOUBLE)),
            {"i0": np.asarray([0.0, 1.0, 2.0])},
        )
        enc = (OneHotEncoder().set_selected_cols(["i0"])
               .set_output_col("f").fit(fit_t))
        bad = Table.from_columns(
            Schema.of(("i0", DataTypes.DOUBLE)), {"i0": np.asarray([7.0])}
        )
        with pytest.raises(ValueError, match="outside"):
            enc.transform(bad)
        enc.set_handle_invalid("keep")
        (out,) = enc.transform(bad)
        feats = out.col("f")
        assert feats.dim == 4  # 3 + invalid bucket
        assert feats.indices[0] == 3


class TestCategoricalPipelineE2E:
    def _pipeline(self):
        return Pipeline([
            StringIndexer().set_selected_cols(["c0", "c1"])
            .set_output_cols(["i0", "i1"]),
            OneHotEncoder().set_selected_cols(["i0", "i1"])
            .set_output_col("features"),
            LogisticRegression().set_vector_col("features")
            .set_label_col("label").set_prediction_col("pred")
            .set_num_features(12).set_learning_rate(0.5)
            .set_global_batch_size(64).set_max_iter(30),
        ])

    def test_fit_transform_learns(self):
        t = _cat_table()
        pm = self._pipeline().fit(t)
        (scored,) = pm.transform(t)
        acc = np.mean(np.asarray(scored.col("pred"))
                      == np.asarray(t.col("label")))
        assert acc > 0.9, acc
        # reserved input columns survive the whole chain
        for c in ("c0", "c1", "label"):
            assert c in scored.schema.field_names

    def test_chunked_pipeline_matches_in_memory(self):
        """The same pipeline fit over a ChunkedTable (the out-of-core
        forward chain, TransformedChunkedTable) matches the in-memory
        fit's predictions."""
        from flink_ml_tpu.table.sources import ChunkedTable, CollectionSource

        t = _cat_table()
        rows = t.to_rows()
        pm_mem = self._pipeline().fit(t)
        chunked = ChunkedTable(
            CollectionSource(rows, t.schema), chunk_rows=128
        )
        pm_ooc = self._pipeline().fit(chunked)
        (a,) = pm_mem.transform(t)
        (b,) = pm_ooc.transform(t)
        np.testing.assert_array_equal(
            np.asarray(a.col("pred")), np.asarray(b.col("pred"))
        )

    def test_evaluator_on_pipeline_scores(self):
        t = _cat_table()
        pm = self._pipeline().fit(t)
        (scored,) = pm.transform(t)
        (m,) = (BinaryClassificationEvaluator().set_label_col("label")
                .set_raw_prediction_col("pred").transform(scored))
        auc = float(m.col("areaUnderROC")[0])
        assert 0.85 < auc <= 1.0, auc

    def test_pipeline_model_save_load(self, tmp_path):
        from flink_ml_tpu.api.core import Stage

        t = _cat_table()
        pm = self._pipeline().fit(t)
        pm.save(str(tmp_path / "pm"))
        loaded = Stage.load(str(tmp_path / "pm"))
        (a,) = pm.transform(t)
        (b,) = loaded.transform(t)
        np.testing.assert_array_equal(
            np.asarray(a.col("pred")), np.asarray(b.col("pred"))
        )


def test_chunked_pipeline_parses_source_once(tmp_path):
    """Multi-estimator chunked Pipeline.fit shares one binary replay cache:
    indexer fit records the parse; encoder and trainer passes replay."""
    t = _cat_table(n=500)
    path = tmp_path / "cat.csv"
    with open(path, "w") as f:
        for c0, c1, y in t.to_rows():
            f.write(f"{c0},{c1},{y:g}\n")
    from flink_ml_tpu.table.sources import ChunkedTable, CsvSource

    class CountingCsv:
        def __init__(self, inner):
            self.inner = inner
            self.chunk_reads = 0

        def schema(self):
            return self.inner.schema()

        def read_chunks(self, max_rows):
            self.chunk_reads += 1
            return self.inner.read_chunks(max_rows)

        def read(self):
            return self.inner.read()

    src = CountingCsv(CsvSource(str(path), CAT_SCHEMA))
    pipeline = Pipeline([
        StringIndexer().set_selected_cols(["c0", "c1"])
        .set_output_cols(["i0", "i1"]),
        OneHotEncoder().set_selected_cols(["i0", "i1"])
        .set_output_col("features"),
        LogisticRegression().set_vector_col("features")
        .set_label_col("label").set_prediction_col("pred")
        .set_learning_rate(0.5).set_global_batch_size(64)
        .set_max_iter(4),
    ])
    pm = pipeline.fit(ChunkedTable(src, chunk_rows=128, spill=True))
    assert src.chunk_reads == 1, src.chunk_reads
    (scored,) = pm.transform(t)
    assert np.mean(np.asarray(scored.col("pred"))
                   == np.asarray(t.col("label"))) > 0.8
