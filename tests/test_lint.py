"""Style gate (checkstyle analog — reference tools/maven/checkstyle.xml wired in
the root pom.xml).  CI additionally runs ruff; this keeps the gate enforced in
environments where ruff is unavailable."""

import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def test_lint_clean():
    proc = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "lint.py")],
        capture_output=True,
        text=True,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
