"""Out-of-core training example — Criteo-shaped scale on bounded memory.

The reference reads its training CSV as a partitioned DataSet so no node
holds the whole input (examples-batch/.../LinearRegression.java:91-102);
this example is that capability on the TPU path: a directory of part-files
streams through ``Estimator.fit`` via a ``ChunkedTable`` with

  * host residency bounded by the chunk cap (never the dataset),
  * host→device prefetch one block ahead of device compute,
  * a binary spill cache so only the first epoch pays text parsing,
  * a model bit-identical to the in-memory fit of the same rows.

Run: python examples/out_of_core_training.py [--rows N] [--chunk-rows N]
"""

import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

from flink_ml_tpu.lib import LogisticRegression
from flink_ml_tpu.table.schema import Schema
from flink_ml_tpu.table.sources import ChunkedTable, CsvSource, ShardedSource

TRUE_W = np.array([1.5, -2.0, 0.5, 3.0, -1.0])


def write_part_files(directory: str, rows: int, shards: int = 4) -> str:
    """A directory of part-files, the way bulk exports arrive."""
    rng = np.random.RandomState(0)
    per = -(-rows // shards)
    for i in range(shards):
        n = min(per, rows - i * per)
        X = rng.randn(n, len(TRUE_W))
        y = ((X @ TRUE_W + 0.3 * rng.randn(n)) > 0).astype(np.float64)
        np.savetxt(
            os.path.join(directory, f"part-{i:05d}.csv"),
            np.column_stack([X, y]), delimiter=",", fmt="%.9g",
        )
    return os.path.join(directory, "part-*.csv")


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--rows", type=int, default=200_000)
    parser.add_argument("--chunk-rows", type=int, default=16_384)
    args = parser.parse_args()

    schema = Schema.of(
        *[(f"f{i}", "double") for i in range(len(TRUE_W))], ("label", "double")
    )
    with tempfile.TemporaryDirectory() as tmp:
        pattern = write_part_files(tmp, args.rows)
        source = ShardedSource.glob(pattern, lambda p: CsvSource(p, schema))
        table = ChunkedTable(source, chunk_rows=args.chunk_rows, spill=True)

        model = (
            LogisticRegression()
            .set_feature_cols([f"f{i}" for i in range(len(TRUE_W))])
            .set_label_col("label")
            .set_prediction_col("pred")
            .set_learning_rate(0.5)
            .set_global_batch_size(8192)
            .set_max_iter(5)
            .fit(table)
        )

        w = model.coefficients()
        direction = w / np.linalg.norm(w) * np.linalg.norm(TRUE_W)
        print(
            f"trained on {args.rows} rows with host residency capped at "
            f"{args.chunk_rows} rows/chunk ({model.train_epochs_} epochs)"
        )
        print(f"true weights:      {np.round(TRUE_W, 2)}")
        print(f"fitted (rescaled): {np.round(direction, 2)}")
        summary = model.train_metrics_.summary()
        print(
            f"throughput: {summary['samples_per_sec']:.0f} samples/sec "
            f"({summary['total_samples']} samples in "
            f"{summary['total_seconds']:.2f}s)"
        )


if __name__ == "__main__":
    main()
