"""Out-of-core training example — Criteo-shaped scale on bounded memory.

The reference reads its training CSV as a partitioned DataSet so no node
holds the whole input (examples-batch/.../LinearRegression.java:91-102);
this example is that capability on the TPU path: a directory of part-files
streams through ``Estimator.fit`` via a ``ChunkedTable`` with

  * host residency bounded by the chunk cap (never the dataset),
  * host→device prefetch one block ahead of device compute,
  * a binary spill cache so only the first epoch pays text parsing,
  * a model bit-identical to the in-memory fit of the same rows.

Run: python examples/out_of_core_training.py [--rows N] [--chunk-rows N]
"""

import argparse
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

from flink_ml_tpu.lib import LogisticRegression
from flink_ml_tpu.table.schema import Schema
from flink_ml_tpu.table.sources import ChunkedTable, CsvSource, ShardedSource
from scripts.generate_linreg_data import generate

DIM = 5


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--rows", type=int, default=200_000)
    parser.add_argument("--chunk-rows", type=int, default=16_384)
    args = parser.parse_args()

    schema = Schema.of(
        *[(f"f{i}", "double") for i in range(DIM)], ("label", "double")
    )
    with tempfile.TemporaryDirectory() as tmp:
        # the seeded example data generator (the reference ships
        # LinearRegressionDataGenerator.java for the same job)
        pattern = generate(tmp, rows=args.rows, dim=DIM, eval_rows=0,
                           task="binary")
        meta = json.load(open(os.path.join(tmp, "meta.json")))
        true_w = np.asarray(meta["true_w"])
        source = ShardedSource.glob(pattern, lambda p: CsvSource(p, schema))
        table = ChunkedTable(source, chunk_rows=args.chunk_rows, spill=True)

        model = (
            LogisticRegression()
            .set_feature_cols([f"f{i}" for i in range(DIM)])
            .set_label_col("label")
            .set_prediction_col("pred")
            .set_learning_rate(0.5)
            .set_global_batch_size(8192)
            .set_max_iter(5)
            .fit(table)
        )

        w = model.coefficients()
        direction = w / np.linalg.norm(w) * np.linalg.norm(true_w)
        print(
            f"trained on {args.rows} rows with host residency capped at "
            f"{args.chunk_rows} rows/chunk ({model.train_epochs_} epochs)"
        )
        print(f"true weights:      {np.round(true_w, 2)}")
        print(f"fitted (rescaled): {np.round(direction, 2)}")
        summary = model.train_metrics_.summary()
        print(
            f"throughput: {summary['samples_per_sec']:.0f} samples/sec "
            f"({summary['total_samples']} samples in "
            f"{summary['total_seconds']:.2f}s)"
        )


if __name__ == "__main__":
    main()
