"""Replica-router example — a saved pipeline behind a 3-replica
scale-out fleet, under concurrent traffic, rolling-deployed and
chaos-killed mid-stream.

One ``ModelServer`` process is a ceiling; this is the shape past it
(ISSUE 13): the :class:`~flink_ml_tpu.serving.ReplicaRouter` fans the
same ``submit() -> Future`` contract across N replica subprocesses,
each running the full single-process serving stack (micro-batching,
breakers, telemetry) discovered through the ephemeral-port handshake.
The script:

1. fits a 3-stage pipeline twice (v1/v2) and SAVES both (integrity
   commit records included);
2. spins up a ``ReplicaRouter`` over the saved v1 — three replica
   children, health-aware power-of-two-choices balancing — and fires
   concurrent small requests at it from a thread pool;
3. mid-traffic, rolling-deploys v2 with zero downtime: one replica at a
   time drains, swaps, and re-admits on ``/readyz`` 200 while the rest
   of the fleet serves;
4. ``kill -9``\\ s one replica mid-traffic: its in-flight requests retry
   on the survivors (zero caller-visible failures) and a replacement is
   respawned;
5. prints throughput, request-latency p50/p99, the zero-failure count,
   and the death/respawn/deploy accounting.

Run: python examples/router_serving.py [--requests N] [--threads K]
     [--replicas R]
"""

import argparse
import os
import signal
import sys
import tempfile
import time
from concurrent.futures import ThreadPoolExecutor

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

from flink_ml_tpu import obs
from flink_ml_tpu.api.pipeline import Pipeline
from flink_ml_tpu.lib import LogisticRegression
from flink_ml_tpu.lib.feature import MinMaxScaler, StandardScaler
from flink_ml_tpu.serving import ReplicaRouter
from flink_ml_tpu.table.schema import DataTypes, Schema
from flink_ml_tpu.table.table import Table

N_ROWS, N_FEATURES = 4096, 12


def fit_pipeline(table, max_iter):
    return Pipeline([
        StandardScaler().set_selected_col("features"),
        MinMaxScaler().set_selected_col("features"),
        LogisticRegression().set_vector_col("features")
        .set_label_col("label").set_prediction_col("pred")
        .set_learning_rate(0.5).set_max_iter(max_iter),
    ]).fit(table)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--requests", type=int, default=120)
    parser.add_argument("--threads", type=int, default=8)
    parser.add_argument("--replicas", type=int, default=3)
    args = parser.parse_args()

    obs.enable()
    rng = np.random.RandomState(42)
    X = (2.0 * rng.randn(N_ROWS, N_FEATURES) + 1.0).astype(np.float32)
    w = rng.randn(N_FEATURES).astype(np.float32)
    y = ((X - 1.0) @ w > 0).astype(np.float64)
    table = Table.from_columns(
        Schema.of(("features", DataTypes.DENSE_VECTOR), ("label", "double")),
        {"features": X, "label": y},
    )

    # 1. fit + save both versions (atomic writes, CRC commit records)
    save_root = tempfile.mkdtemp(prefix="router_serving_")
    v1_dir = os.path.join(save_root, "v1")
    v2_dir = os.path.join(save_root, "v2")
    fit_pipeline(table, max_iter=3).save(v1_dir)
    fit_pipeline(table, max_iter=6).save(v2_dir)
    print(f"saved v1 and v2 pipelines under {save_root}")

    # 2. the fleet: N replica children behind the router
    router = ReplicaRouter(v1_dir, version="v1", replicas=args.replicas,
                           poll_ms=30)
    print(f"fleet up: {router.ready_count()}/{args.replicas} replicas "
          f"ready (pids {[r['pid'] for r in router.replicas]})")

    sizes = rng.choice([1, 2, 4, 8], size=args.requests)
    offsets = np.cumsum(np.concatenate([[0], sizes[:-1]]))
    outcomes, errors = [], []

    def call(i):
        lo = int(offsets[i]) % (N_ROWS - 8)
        res = router.predict(table.slice_rows(lo, lo + int(sizes[i])),
                             timeout=120)
        return res.version, res.num_rows

    def fire(indices, pool):
        for future in [pool.submit(call, i) for i in indices]:
            try:
                outcomes.append(future.result())
            except Exception as exc:  # noqa: BLE001 - counted, reported
                errors.append(exc)

    router.predict(table.slice_rows(0, 4), timeout=120)  # warm the fleet
    deploy_at = args.requests // 3
    kill_at = 2 * args.requests // 3
    t0 = time.perf_counter()
    with ThreadPoolExecutor(max_workers=args.threads) as pool:
        fire(range(deploy_at), pool)
        # 3. zero-downtime rolling deploy, one replica at a time
        status = router.deploy(v2_dir, "v2")
        deployed = sum(1 for r in status["replicas"]
                       if r["outcome"] == "deployed")
        fire(range(deploy_at, kill_at), pool)
        # 4. chaos: kill one replica outright, keep the traffic coming
        victim = router.replicas[0]["pid"]
        os.kill(victim, signal.SIGKILL)
        fire(range(kill_at, args.requests), pool)
    wall = time.perf_counter() - t0

    # wait out the respawn so the fleet leaves whole
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        stats = router.stats()
        if (stats.get("router.respawns", 0) >= 1
                and router.ready_count() >= args.replicas):
            break
        time.sleep(0.1)
    stats = router.stats()
    versions = sorted({v for v, _n in outcomes})
    total_rows = sum(n for _v, n in outcomes)
    ready = router.ready_count()
    router.shutdown()

    # 5. the numbers an operator would watch
    print(f"served {len(outcomes)} requests ({total_rows} rows) in "
          f"{wall * 1e3:.1f} ms -> {len(outcomes) / wall:.0f} req/s, "
          f"{total_rows / wall:.0f} rows/s")
    print(f"request latency p50 {stats.get('latency_p50_ms', 0):.1f} ms, "
          f"p99 {stats.get('latency_p99_ms', 0):.1f} ms")
    print(f"rolling deploy: {deployed}/{args.replicas} replicas on v2; "
          f"versions served: {versions}; failed requests: {len(errors)}")
    if errors:
        print(f"first failure: {errors[0]!r}")
    print(f"killed replica pid {victim}; fleet back to {ready}/"
          f"{args.replicas} ready "
          f"(deaths: {stats.get('router.replica_deaths', 0):.0f}, "
          f"respawns: {stats.get('router.respawns', 0):.0f}, "
          f"retries: {stats.get('router.retries', 0):.0f})")


if __name__ == "__main__":
    main()
