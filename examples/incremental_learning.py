"""Incremental learning example — the reference's streaming skeleton
(examples-streaming/.../IncrementalLearningSkeleton.java:54-83) made concrete.

Topology (SURVEY.md §3.4): an unbounded training stream is cut into 5000 ms
event-time tumbling windows; each fired window updates the model; a concurrent
prediction stream is served by the freshest model at each record's event time.
Instead of the skeleton's dummy Double[] model, the model is a real online
logistic regression.

Run: python examples/incremental_learning.py
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

from flink_ml_tpu.lib import OnlineLogisticRegression
from flink_ml_tpu.ops.vector import DenseVector
from flink_ml_tpu.table.schema import DataTypes, Schema
from flink_ml_tpu.table.sources import GeneratorSource

TRAIN_SCHEMA = Schema.of(("features", DataTypes.DENSE_VECTOR), ("label", "double"))
PREDICT_SCHEMA = Schema.of(("features", DataTypes.DENSE_VECTOR),)


def main():
    rng = np.random.RandomState(0)
    n = 2000
    X = rng.randn(n, 2)
    true_w = np.array([1.0, -2.0])
    y = ((X @ true_w) > 0).astype(np.float64)

    # one training record every 50 ms -> 100 records per 5000 ms window
    train_rows = [(DenseVector(X[i]), y[i]) for i in range(n)]
    train_src = GeneratorSource.linear_timestamps(train_rows, 50, TRAIN_SCHEMA)
    predict_rows = [(DenseVector(X[i]),) for i in range(n)]
    predict_src = GeneratorSource.linear_timestamps(predict_rows, 50, PREDICT_SCHEMA)

    model, result = (
        OnlineLogisticRegression()
        .set_vector_col("features")
        .set_label_col("label")
        .set_prediction_col("pred")
        .set_learning_rate(0.5)
        .set_window_ms(5000)
        .fit_unbounded(train_src, prediction_source=predict_src)
    )

    correct = sum(
        1 for i, (_, p) in enumerate(result.predictions) if p == y[i]
    )
    print(f"windows fired: {result.windows_fired}")
    print(f"streaming predictions: {len(result.predictions)}, "
          f"accuracy {correct / len(result.predictions):.3f}")
    print(f"final coefficients: {model.coefficients()}")


if __name__ == "__main__":
    main()
