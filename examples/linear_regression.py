"""Batch LinearRegression example — the reference's own flagship example
(examples-batch/.../LinearRegression.java:77-131) rebuilt on the TPU path.

The reference iterates a per-record BGD over the 21-point default dataset
(LinearRegressionData.java:37-52 shape: y ≈ θ0 + θ1·x) with broadcast
parameters and a reduce-average round per epoch.  Here the same dataset
trains in one data-parallel SGD loop; the script prints the fitted line and
per-point predictions, mirroring the example's `result.print()`.

Run: python examples/linear_regression.py [--iterations N]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

from flink_ml_tpu.lib import LinearRegression
from flink_ml_tpu.table.schema import Schema
from flink_ml_tpu.table.table import Table

# the reference's default 21-point dataset shape: y = 2x + noise-free-ish line
DEFAULT_X = np.arange(0.0, 21.0)
DEFAULT_Y = 2.0 * DEFAULT_X + 1.0


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--iterations", type=int, default=200)
    args = parser.parse_args()

    schema = Schema.of(("x", "double"), ("y", "double"))
    train = Table.from_columns(schema, {"x": DEFAULT_X, "y": DEFAULT_Y})

    model = (
        LinearRegression()
        .set_feature_cols(["x"])
        .set_label_col("y")
        .set_prediction_col("pred")
        .set_learning_rate(0.005)
        .set_max_iter(args.iterations)
        .fit(train)
    )

    theta1 = model.coefficients()[0]
    theta0 = model.intercept()
    print(f"fitted: y = {theta0:.3f} + {theta1:.3f} * x  "
          f"({model.train_epochs_} epochs)")

    (out,) = model.transform(train)
    for x, y, p in zip(out.col("x"), out.col("y"), out.col("pred")):
        print(f"x={x:5.1f}  y={y:6.2f}  pred={p:6.2f}")


if __name__ == "__main__":
    main()
