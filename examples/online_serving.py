"""Online serving example — a saved pipeline behind the micro-batching
ModelServer, under concurrent traffic, hot-swapped mid-stream.

The production shape the serving runtime exists for: many callers each
holding one-or-a-few rows, none of whom should pay a whole fused dispatch
alone.  The script:

1. fits a 3-stage pipeline (StandardScaler -> MinMaxScaler -> logistic
   regression score) and SAVES it (integrity commit records included);
2. spins up a :class:`~flink_ml_tpu.serving.ModelServer` FROM THE SAVED
   PATH (the loaders verify the commit records) and fires concurrent
   small requests at it from a thread pool;
3. mid-traffic, deploys a v2 of the model with zero downtime — in-flight
   requests finish on v1, later ones serve on v2, nothing fails;
4. prints throughput, request-latency p50/p99, and the swap accounting.

Run: python examples/online_serving.py [--requests N] [--threads K]
"""

import argparse
import os
import sys
import tempfile
import time
from concurrent.futures import ThreadPoolExecutor

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

from flink_ml_tpu import obs
from flink_ml_tpu.api.pipeline import Pipeline
from flink_ml_tpu.lib import LogisticRegression
from flink_ml_tpu.lib.feature import MinMaxScaler, StandardScaler
from flink_ml_tpu.serving import ModelServer
from flink_ml_tpu.table.schema import DataTypes, Schema
from flink_ml_tpu.table.table import Table

N_ROWS, N_FEATURES = 4096, 12


def fit_pipeline(table, max_iter):
    return Pipeline([
        StandardScaler().set_selected_col("features"),
        MinMaxScaler().set_selected_col("features"),
        LogisticRegression().set_vector_col("features")
        .set_label_col("label").set_prediction_col("pred")
        .set_prediction_detail_col("proba")
        .set_learning_rate(0.5).set_max_iter(max_iter),
    ]).fit(table)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--requests", type=int, default=120)
    parser.add_argument("--threads", type=int, default=8)
    args = parser.parse_args()

    obs.enable()
    rng = np.random.RandomState(42)
    X = (2.0 * rng.randn(N_ROWS, N_FEATURES) + 1.0).astype(np.float32)
    w = rng.randn(N_FEATURES).astype(np.float32)
    y = ((X - 1.0) @ w > 0).astype(np.float64)
    table = Table.from_columns(
        Schema.of(("features", DataTypes.DENSE_VECTOR), ("label", "double")),
        {"features": X, "label": y},
    )

    # 1. fit + save (atomic writes with CRC commit records)
    save_root = tempfile.mkdtemp(prefix="online_serving_")
    v1_dir = os.path.join(save_root, "v1")
    v2_dir = os.path.join(save_root, "v2")
    fit_pipeline(table, max_iter=3).save(v1_dir)
    fit_pipeline(table, max_iter=6).save(v2_dir)
    print(f"saved v1 and v2 pipelines under {save_root}")

    # 2. serve from the saved path — the load verifies integrity sidecars
    server = ModelServer(path=v1_dir, version="v1", max_batch=256,
                         max_wait_ms=2, warmup=table.slice_rows(0, 8))
    sizes = rng.choice([1, 2, 4, 8], size=args.requests)
    offsets = np.cumsum(np.concatenate([[0], sizes[:-1]]))
    swap_at = args.requests // 2

    def call(i):
        lo = int(offsets[i]) % (N_ROWS - 8)
        res = server.predict(table.slice_rows(lo, lo + int(sizes[i])),
                             timeout=120)
        return res.version, res.num_rows

    # warm the request path, then fire the timed concurrent traffic with a
    # hot swap landing in the middle of it
    server.predict(table.slice_rows(0, 4), timeout=120)
    t0 = time.perf_counter()
    outcomes, errors = [], []
    with ThreadPoolExecutor(max_workers=args.threads) as pool:
        first_half = [pool.submit(call, i) for i in range(swap_at)]
        # 3. zero-downtime hot swap while the pool is mid-traffic
        server.deploy(v2_dir, "v2")
        second_half = [pool.submit(call, i)
                       for i in range(swap_at, args.requests)]
        for f in first_half + second_half:
            try:
                outcomes.append(f.result())
            except Exception as exc:  # noqa: BLE001 - counted, reported
                errors.append(exc)
    wall = time.perf_counter() - t0

    failed = len(errors)
    if errors:
        print(f"first failure: {errors[0]!r}")
    versions = sorted({v for v, _n in outcomes})
    total_rows = sum(n for _v, n in outcomes)
    stats = server.stats()
    server.shutdown()

    # 4. the numbers an operator would watch
    print(f"served {len(outcomes)} requests ({total_rows} rows) in "
          f"{wall * 1e3:.1f} ms -> {len(outcomes) / wall:.0f} req/s, "
          f"{total_rows / wall:.0f} rows/s")
    print(f"request latency p50 {stats.get('latency_p50_ms', 0):.1f} ms, "
          f"p99 {stats.get('latency_p99_ms', 0):.1f} ms")
    print(f"hot-swapped to v2 mid-traffic; versions served: {versions}; "
          f"failed requests: {failed}")
    print(f"coalesced {stats.get('serving.coalesced_requests', 0):.0f} "
          f"requests into {stats.get('serving.batches', 0):.0f} dispatch "
          f"batches (swaps: {stats.get('serving.swaps', 0):.0f})")


if __name__ == "__main__":
    main()
