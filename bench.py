"""Headline benchmark: LogisticRegression.fit samples/sec/chip.

Thin wrapper over :func:`bench_all.bench_logreg` (the full matrix lives in
``bench_all.py`` — all five BASELINE.json configs plus the Criteo-shaped
sparse path).  Prints ONE JSON line:
  {"metric", "value", "unit", "vs_baseline", ...}

``vs_baseline`` is against the honest vectorized-numpy minibatch SGD on the
host CPU (identical update rule); the reference-shaped per-record loop is
also measured and reported as ``vs_per_record``.  AUC parity against the
vectorized baseline is computed on held-out rows (``auc_parity``).
Throughput is read from the training driver's own StepMetrics.
"""

from bench_all import bench_logreg


def main():
    from flink_ml_tpu import obs

    obs.enable()
    obs.reset()
    bench_logreg()


if __name__ == "__main__":
    main()
