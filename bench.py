"""Headline benchmark: LogisticRegression.fit samples/sec/chip, plus the
repeated-fit (warm-path) sweep.

Thin wrapper over :func:`bench_all.bench_logreg` and
:func:`bench_all.bench_warm_fit` (the full matrix lives in ``bench_all.py``
— all five BASELINE.json configs plus the Criteo-shaped sparse path).
Prints one JSON line per workload:
  {"metric", "value", "unit", "vs_baseline", ...}

``vs_baseline`` is against the honest vectorized-numpy minibatch SGD on the
host CPU (identical update rule); the reference-shaped per-record loop is
also measured and reported as ``vs_per_record``.  AUC parity against the
vectorized baseline is computed on held-out rows (``auc_parity``).
Throughput is read from the training driver's own StepMetrics.

The repeated-fit sweep (ISSUE 2) fits ONE table three times (learning rate
varied on the third) and reports cold vs warm call latency plus slab-pool
hit counts — ``warm_over_cold`` is the ratio BASELINE.json gates.
"""

from bench_all import bench_logreg, bench_warm_fit


def main():
    from flink_ml_tpu import obs

    obs.enable()
    obs.reset()
    bench_logreg()
    # fresh registry scope so the warm-fit RunReport's metrics snapshot
    # describes the repeated-fit sweep alone
    obs.reset()
    bench_warm_fit()


if __name__ == "__main__":
    main()
