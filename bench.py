"""Headline benchmark: LogisticRegression.fit samples/sec/chip.

BASELINE.md records no published reference numbers, so the baseline is
measured here too: the reference-shaped CPU path — per-record gradient
math exactly like SubUpdate.map (examples-batch/.../LinearRegression.java:
215-231) / ModelMapperAdapter.map (ModelMapperAdapter.java:58-61), one row
at a time through numpy — versus the batched-XLA device path.  The printed
``vs_baseline`` is device-samples-per-sec over per-record-samples-per-sec
(north star: >= 4x at identical AUC; BASELINE.json).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import time

import numpy as np


N_ROWS = 200_000
N_FEATURES = 28  # HIGGS feature count
EPOCHS = 20
BATCH = 8192


def make_data(seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(N_ROWS, N_FEATURES).astype(np.float64)
    true_w = rng.randn(N_FEATURES)
    y = ((X @ true_w + 0.5 * rng.randn(N_ROWS)) > 0).astype(np.float64)
    return X, y


def bench_tpu_path(X, y):
    """Full Estimator.fit through the framework; returns samples/sec/chip."""
    import jax

    from flink_ml_tpu.lib import LogisticRegression
    from flink_ml_tpu.table.schema import Schema
    from flink_ml_tpu.table.table import Table

    schema = Schema.of(
        *[(f"f{i}", "double") for i in range(N_FEATURES)], ("label", "double")
    )
    cols = {f"f{i}": X[:, i] for i in range(N_FEATURES)}
    cols["label"] = y
    table = Table.from_columns(schema, cols)

    feature_cols = [f"f{i}" for i in range(N_FEATURES)]

    def fit(iters):
        return (
            LogisticRegression()
            .set_feature_cols(feature_cols)
            .set_label_col("label")
            .set_prediction_col("pred")
            .set_learning_rate(0.5)
            .set_global_batch_size(BATCH)
            .set_max_iter(iters)
            .fit(table)
        )

    fit(EPOCHS)  # warmup: compile + pack (steady-state measurement below)
    n_chips = jax.device_count()
    t0 = time.perf_counter()
    model = fit(EPOCHS)
    elapsed = time.perf_counter() - t0
    sps_per_chip = EPOCHS * N_ROWS / elapsed / n_chips
    return sps_per_chip, model


def bench_per_record_baseline(X, y, budget_rows=20_000):
    """The reference-shaped hot loop: one row at a time, vector math per row."""
    w = np.zeros(N_FEATURES)
    b = 0.0
    lr = 0.5 / BATCH
    n = min(budget_rows, len(y))
    t0 = time.perf_counter()
    for i in range(n):
        xi = X[i]
        p = 1.0 / (1.0 + np.exp(-(xi @ w + b)))
        err = p - y[i]
        w -= lr * err * xi
        b -= lr * err
    elapsed = time.perf_counter() - t0
    return n / elapsed


def main():
    X, y = make_data()
    device_sps, _ = bench_tpu_path(X, y)
    record_sps = bench_per_record_baseline(X, y)
    print(
        json.dumps(
            {
                "metric": "LogisticRegression.fit samples/sec/chip",
                "value": round(device_sps, 1),
                "unit": "samples/sec/chip",
                "vs_baseline": round(device_sps / record_sps, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
